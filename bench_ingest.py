"""Ingest benchmark of record (ISSUE 16): sustained offered-load
throughput and submit->commit latency through the ingress pipeline
(`babble_tpu/ingress/`), gated by the SLOEngine on declared p50/p99
objectives.

The workload is the open-loop generator (`ingress/loadgen.py`): Poisson
arrivals at a fixed offered rate from a 10^5-client id space, driven
over the deterministic sim fabric on virtual time — so the numbers are
reproducible from the seed and coordinated omission cannot hide
queueing (the generator never slows down because the system queued).
Latency comes from the same `babble_commit_latency_seconds` histograms
production nodes expose, merged across the cluster; each node's last
commit exemplar (PR 11) rides in the headline so a p99 breach links to
a concrete trace_id.

Two runs per invocation:

1. the measured run — submissions through `submit_tx_batch` (the
   pipeline path), with periodic client retries exercising the dedup
   window;
2. the control run — the SAME seeded workload submitted single-tx,
   bypassing the pipeline. The two clusters' commit digests must be
   byte-identical: batching, dedup and fairness may reshape HOW txs
   enter, never WHAT is committed.

Prints the headline as the LAST stdout line:
  {"metric": ..., "value": committed tx/s, "unit": "tx/s",
   "p50_s": ..., "p99_s": ..., "offered": N, "committed": N,
   "clients": N, "verdicts": {...}, "ingress": {...},
   "digest_match": true, "metrics": {...}}

`--slo` turns the latency trajectory into a gate: the p50/p99 estimates
are declared as SLO objectives and the process exits nonzero on breach
or on a digest mismatch. The SLO report goes to stderr so the headline
stays the last stdout line. `--smoke` shrinks the horizon for CI.
"""

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_CLIENTS = 100_000
OFFERED_RATE = 400.0  # tx/s over virtual time
BURST = 4
UNTIL = 10.0  # virtual seconds
RETRY_EVERY = 16  # every Nth burst re-offers a tx (dedup exercise)
SEED = 0
TARGET_P50_S = 5.0
TARGET_P99_S = 15.0


def run_cluster(seed, via, rate, clients, burst, until, retry_every):
    """One seeded cluster + loadgen run. Returns (cluster, gen, result)."""
    from babble_tpu.ingress import OpenLoopLoadGen
    from babble_tpu.sim import SimCluster

    cluster = SimCluster(
        n=4,
        seed=seed,
        heartbeat=0.05,
        # deadline 0: release on every pump — the setting under which
        # batched and single-tx submission commit identical digests
        ingress_batch_deadline=0.0,
        ingress_queue_cap=8192,
    )
    gen = OpenLoopLoadGen(
        rate=rate, clients=clients, burst=burst,
        retry_every=retry_every if via == "ingress" else retry_every,
        seed=seed,
    )
    gen.drive_sim(cluster, until=until, via=via)
    res = cluster.run(until=until, inject=False)
    return cluster, gen, res


def merge_latency(snapshots):
    """Merge per-node commit-latency histogram snapshots (same bucket
    bounds) into one (count, buckets, exemplar) triple."""
    count, sums = 0, {}
    exemplar = None
    order = []
    for snap in snapshots:
        if not snap:
            continue
        entry = snap.get("series", {}).get("")
        if not entry:
            continue
        count += entry["count"]
        exemplar = entry.get("exemplar", exemplar)
        for le, cum in entry["buckets"]:
            if le not in sums:
                sums[le] = 0
                order.append(le)
            sums[le] += cum
    return count, [(le, sums[le]) for le in order], exemplar


def quantile_le(count, buckets, q):
    """Conservative quantile estimate from cumulative buckets: the
    smallest bucket bound covering >= q of observations (inf when the
    quantile sits past the last bound)."""
    if count <= 0:
        return float("inf")
    need = math.ceil(q * count)
    for le, cum in buckets:
        if cum >= need:
            return float(le)
    return float("inf")


def sum_counter(per_node, series):
    """Sum one counter series' labeled values across the per-node
    ingress snapshots SimCluster.result() carries."""
    out = {}
    for snaps in per_node.values():
        snap = (snaps or {}).get(series)
        if not snap:
            continue
        for label, value in snap.get("series", {}).items():
            out[label] = out.get(label, 0) + value
    return out


def slo_gate(obs, p50_max, p99_max):
    """Declare the latency objectives over the bench registry and
    evaluate once (cumulative single-sample evaluation, like bench.py's
    throughput gate). Returns (ok, status_doc)."""
    from babble_tpu.obs import SLOEngine

    slo = SLOEngine(obs)
    slo.objective(
        "ingest_p50",
        series="babble_ingest_p50_seconds",
        kind="below", threshold=p50_max,
        description="median submit->commit latency under offered load",
    )
    slo.objective(
        "ingest_p99",
        series="babble_ingest_p99_seconds",
        kind="below", threshold=p99_max,
        description="p99 submit->commit latency under offered load",
    )
    status = slo.evaluate()
    return not slo.breached(), status


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slo", action="store_true",
                    help="Gate the run: exit 1 when p50/p99 breach the "
                         "declared objectives or the batched-vs-single "
                         "digests mismatch")
    ap.add_argument("--smoke", action="store_true",
                    help="Short CI horizon (fewer virtual seconds, lower "
                         "offered rate; same 10^5-client id space)")
    ap.add_argument("--seed", type=int, default=SEED)
    ap.add_argument("--rate", type=float, default=None,
                    help="Offered load in tx/s over virtual time")
    ap.add_argument("--until", type=float, default=None,
                    help="Virtual-time horizon in seconds")
    ap.add_argument("--clients", type=int, default=N_CLIENTS,
                    help="Simulated client id space")
    ap.add_argument("--slo-p50", type=float, default=TARGET_P50_S)
    ap.add_argument("--slo-p99", type=float, default=TARGET_P99_S)
    ap.add_argument("--no-digest-check", action="store_true",
                    help="Skip the single-tx control run")
    args = ap.parse_args(argv)

    rate = args.rate if args.rate is not None else (
        120.0 if args.smoke else OFFERED_RATE
    )
    until = args.until if args.until is not None else (
        3.0 if args.smoke else UNTIL
    )

    cluster, gen, res = run_cluster(
        args.seed, "ingress", rate, args.clients, BURST, until, RETRY_EVERY,
    )

    committed = max(
        sn.node.core.get_consensus_transactions_count()
        for sn in cluster.sns if not sn.crashed
    )
    vtime = res["virtual_time"] or 1.0
    tx_per_sec = committed / vtime
    count, buckets, exemplar = merge_latency(
        list(res["commit_latency"].values())
    )
    p50 = quantile_le(count, buckets, 0.50)
    p99 = quantile_le(count, buckets, 0.99)
    verdicts = sum_counter(res["ingress"], "babble_ingress_verdicts_total")
    sheds = sum_counter(res["ingress"], "babble_ingress_shed_total")
    dedups = sum_counter(
        res["ingress"], "babble_ingress_dedup_hits_total"
    ).get("", 0)

    digest_match = None
    if not args.no_digest_check:
        # control run: identical seeded workload, single-tx, no pipeline
        _, _, res_direct = run_cluster(
            args.seed, "direct", rate, args.clients, BURST, until,
            RETRY_EVERY,
        )
        digest_match = res["digest"] == res_direct["digest"]

    # bench-local registry: the obs-layer view the SLO gate runs over
    from babble_tpu.obs import Observability

    obs = Observability()
    obs.gauge(
        "babble_ingest_tx_per_second",
        "Ingest benchmark committed-transaction throughput",
    ).set(tx_per_sec)
    obs.gauge(
        "babble_ingest_p50_seconds",
        "Ingest benchmark submit->commit p50 estimate",
    ).set(p50)
    obs.gauge(
        "babble_ingest_p99_seconds",
        "Ingest benchmark submit->commit p99 estimate",
    ).set(p99)

    headline = {
        "metric": (
            f"txs committed/sec under {rate:.0f} tx/s open-loop offered "
            f"load, {args.clients} clients, 4 nodes, sim fabric"
        ),
        "value": round(tx_per_sec, 1),
        "unit": "tx/s",
        "offered": gen.offered,
        "committed": committed,
        "clients": args.clients,
        "p50_s": None if p50 == float("inf") else p50,
        "p99_s": None if p99 == float("inf") else p99,
        "latency_samples": count,
        "exemplar": exemplar,
        "verdicts": verdicts,
        "sheds": sheds,
        "dedup_hits": dedups,
        "retries_offered": gen.retries,
        "digest_match": digest_match,
        "virtual_time": vtime,
        # cluster health plane (ISSUE 20): worst-case skew/agreement and
        # partition suspicions over the run, for the bench_trend gate
        "cluster_health": (res.get("cluster_health") or {}).get("summary"),
        "metrics": obs.registry.snapshot(),
    }
    print(json.dumps(headline))

    rc = 0
    if digest_match is False:
        print(
            "DIGEST MISMATCH: batched and single-tx submission committed "
            "different blocks",
            file=sys.stderr,
        )
        rc = 1
    if args.slo:
        ok, status = slo_gate(obs, args.slo_p50, args.slo_p99)
        print(
            "SLO gate:",
            json.dumps(status["objectives"], sort_keys=True),
            file=sys.stderr,
        )
        if not ok:
            print(
                f"SLO BREACH: p50={p50}s p99={p99}s over the "
                f"({args.slo_p50}s, {args.slo_p99}s) objectives",
                file=sys.stderr,
            )
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
