"""Scale-point benchmark: the round-frontier pipeline at BASELINE's large
validator counts.

Two configs, selected by SCALE_CONFIG (default 5):
- SCALE_CONFIG=5 — 1024 validators, Zipf gossip (BASELINE.json configs[4],
  "streaming rounds with on-device DAG frontier").
- SCALE_CONFIG=4 — 256 validators with an adversarial 1/3-byzantine graph
  (withhold/flush cycles, Zipf fan-out; BASELINE.json configs[3]).

Complements bench.py (the 64-validator metric of record): same timed path,
same in-run bit-exactness gate vs the level-scan engine, at the configured
validator scale. Run on the real chip for the recorded scale point; the
multi-chip analog of this shape is exercised by the CPU-mesh differential
(tests/test_multichip.py::test_frontier_sharded_n256 and the 8-way run
recorded in BASELINE.md).

Prints one JSON line like bench.py.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SCALE_CONFIG = int(os.environ.get("SCALE_CONFIG", "5"))
if SCALE_CONFIG == 4:
    N_VALIDATORS = 256
    N_EVENTS = 16384
    SEED = 11
    ZIPF = 1.05
    BYZ_FRAC = 1.0 / 3.0
    LABEL = "BASELINE config #4, 1/3-byzantine withhold/flush graph"
else:
    N_VALIDATORS = 1024
    N_EVENTS = 32768
    SEED = 7
    ZIPF = 1.02
    BYZ_FRAC = 0.0
    LABEL = "BASELINE config #5 scale"

CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "bench_cache",
    f"grid_{N_VALIDATORS}x{N_EVENTS}_seed{SEED}_b{int(BYZ_FRAC * 100)}.npz",
)


def load_grid():
    import numpy as np

    from babble_tpu.tpu.grid import DagGrid, MIN_INT32, build_levels, synthetic_grid

    if os.path.exists(CACHE):
        z = np.load(CACHE)
        e = N_EVENTS
        levels, num_levels = build_levels(
            N_VALIDATORS, z["self_parent"], z["other_parent"]
        )
        return DagGrid(
            n=N_VALIDATORS,
            e=e,
            super_majority=2 * N_VALIDATORS // 3 + 1,
            creator=z["creator"],
            index=z["index"],
            self_parent=z["self_parent"],
            other_parent=z["other_parent"],
            last_ancestors=z["la"],
            first_descendants=z["fd"],
            coin_bit=z["coin"],
            fixed_round=np.where(
                (z["self_parent"] < 0) & (z["other_parent"] < 0), 0, -1
            ).astype(np.int32),
            ext_sp_round=np.full(e, -1, dtype=np.int32),
            ext_op_round=np.full(e, -1, dtype=np.int32),
            ext_sp_lamport=np.full(e, -1, dtype=np.int32),
            ext_op_lamport=np.full(e, MIN_INT32, dtype=np.int32),
            fixed_lamport=np.full(e, MIN_INT32, dtype=np.int32),
            levels=levels,
            num_levels=num_levels,
        )

    grid = synthetic_grid(
        N_VALIDATORS, N_EVENTS, seed=SEED, zipf_a=ZIPF,
        byzantine_frac=BYZ_FRAC,
    )
    os.makedirs(os.path.dirname(CACHE), exist_ok=True)
    np.savez_compressed(
        CACHE,
        creator=grid.creator,
        index=grid.index,
        self_parent=grid.self_parent,
        other_parent=grid.other_parent,
        la=grid.last_ancestors,
        fd=grid.first_descendants,
        coin=grid.coin_bit,
    )
    return grid


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from babble_tpu.tpu.engine import run_passes
    from babble_tpu.tpu.frontier import (
        build_inv, chain_table, frontier_pipeline, level_lamport, sp_index_of,
    )

    grid = load_grid()

    dev = {
        k: jax.device_put(getattr(grid, k))
        for k in (
            "creator", "index", "last_ancestors", "first_descendants",
            "coin_bit",
        )
    }
    rows_by = chain_table(grid)
    dev["rows_by"] = jax.device_put(rows_by)
    dev["sp_index"] = jax.device_put(sp_index_of(grid))
    dev["lamport"] = jax.device_put(level_lamport(grid))
    inv = build_inv(dev["rows_by"], dev["last_ancestors"])

    # the fame/received round axis: at 1024 validators real round counts
    # are tiny (few events per chain), so a small N-independent axis wins
    # (see engine._adaptive_r_loop's floor note)
    r_fame = 16

    def run_batch():
        return frontier_pipeline(
            inv, dev["rows_by"], dev["creator"], dev["index"],
            dev["sp_index"], dev["last_ancestors"], dev["first_descendants"],
            dev["lamport"], dev["coin_bit"],
            grid.super_majority, grid.n, r_fame,
        )

    out = run_batch()
    while int(np.asarray(out.last_round)) + 2 > r_fame:
        r_fame *= 2
        out = run_batch()

    warm = jnp.int32(0)
    for _ in range(15):
        warm = warm + run_batch().last_round
    int(np.asarray(warm))

    iters = 20
    start = time.perf_counter()
    acc = jnp.int32(0)
    for _ in range(iters):
        out = run_batch()
        acc = acc + out.last_round + jnp.sum(out.received) + jnp.sum(out.rounds)
    int(np.asarray(acc))
    elapsed = (time.perf_counter() - start) / iters

    # optional phase breakdown (VERDICT r4 #6): time the walk / fame /
    # received stages as separate programs with the accumulate-then-fetch
    # discipline (per-fetch tunnel RTT ~200 ms would otherwise dominate)
    if os.environ.get("SCALE_PHASES"):
        from babble_tpu.tpu.frontier import frontier_rounds
        from babble_tpu.tpu.kernels import _decide_fame, _decide_round_received

        fame_jit = jax.jit(
            _decide_fame,
            static_argnames=("super_majority", "n_participants", "d_cap"),
        )
        recv_jit = jax.jit(_decide_round_received)

        def walk():
            return frontier_rounds(
                inv, dev["rows_by"], dev["creator"], dev["index"],
                dev["sp_index"], dev["first_descendants"],
                super_majority=grid.super_majority, r_cap=r_fame,
                la=dev["last_ancestors"],
            )

        fr = walk()

        def fame():
            return fame_jit(
                fr.witness_table, dev["last_ancestors"],
                dev["first_descendants"], dev["index"], dev["coin_bit"],
                fr.last_round, super_majority=grid.super_majority,
                n_participants=grid.n, d_cap=r_fame + 2,
            )

        fm = fame()

        def received():
            return recv_jit(
                fr.witness_table, dev["last_ancestors"], dev["index"],
                dev["creator"], fr.rounds, fm.decided, fm.famous,
                fm.rounds_decided, fr.last_round,
            )

        phases = {
            "walk": lambda: walk().last_round,
            "fame": lambda: jnp.sum(fame().rounds_decided),
            "received": lambda: jnp.sum(received()),
        }
        report = {}
        for name, fn in phases.items():
            acc = jnp.int32(0)
            for _ in range(5):
                acc = acc + fn()
            int(np.asarray(acc))  # warm
            t0 = time.perf_counter()
            acc = jnp.int32(0)
            for _ in range(iters):
                acc = acc + fn()
            int(np.asarray(acc))
            report[name] = round((time.perf_counter() - t0) / iters * 1e3, 2)
        print(json.dumps({"phase_ms": report, "config": LABEL, "r_fame": r_fame}))

    # bit-exactness gate vs the level-scan engine path
    res = run_passes(grid, adaptive_r=True)
    np.testing.assert_array_equal(np.asarray(out.rounds), res.rounds)
    np.testing.assert_array_equal(np.asarray(out.received), res.received)

    events_per_sec = grid.e / elapsed

    # obs-layer registry view of the run, embedded in the headline
    from babble_tpu.obs import Observability, log_buckets

    obs = Observability()
    obs.histogram(
        "babble_bench_iteration_seconds",
        "Per-iteration wall time of the frontier pipeline at scale",
        buckets=log_buckets(0.0001, 2.0, 20),
    ).observe(elapsed)
    obs.gauge(
        "babble_bench_events_per_second",
        "Benchmark throughput headline",
    ).set(events_per_sec)

    print(
        json.dumps(
            {
                "metric": (
                    "events ordered/sec, frontier pipeline, "
                    f"{N_VALIDATORS} validators ({LABEL}), "
                    f"{N_EVENTS} events, platform={jax.devices()[0].platform}"
                ),
                "value": round(events_per_sec, 1),
                "unit": "events/s",
                "vs_baseline": round(events_per_sec / 1_000_000.0, 3),
                "metrics": obs.registry.snapshot(),
            }
        )
    )


if __name__ == "__main__":
    main()
