"""Version string (reference: src/version/version.go).

The reference injects the git commit via ldflags; here an environment
override (BABBLE_TPU_GIT_COMMIT) plays that role for packaged builds.
"""

import os

MAJOR = 0
MINOR = 4
PATCH = 0

git_commit = os.environ.get("BABBLE_TPU_GIT_COMMIT", "")

version = f"{MAJOR}.{MINOR}.{PATCH}" + (f"-{git_commit[:8]}" if git_commit else "")
