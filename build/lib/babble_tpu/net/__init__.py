from .commands import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    SyncRequest,
    SyncResponse,
)
from .transport import RPC, RPCResponse, Transport, TransportError
from .inmem_transport import InmemTransport, new_inmem_addr
from .tcp_transport import TCPTransport

__all__ = [
    "SyncRequest",
    "SyncResponse",
    "EagerSyncRequest",
    "EagerSyncResponse",
    "FastForwardRequest",
    "FastForwardResponse",
    "RPC",
    "RPCResponse",
    "Transport",
    "TransportError",
    "InmemTransport",
    "new_inmem_addr",
    "TCPTransport",
]
