"""FNV-1a 32-bit hash used for peer IDs (reference: src/common/hash32.go:5-11)."""

from __future__ import annotations

_FNV_OFFSET = 2166136261
_FNV_PRIME = 16777619
_MASK = 0xFFFFFFFF


def hash32(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h
