"""Per-key RollingIndex map (reference: src/common/rolling_index_map.go:8-87)."""

from __future__ import annotations

from typing import Any, Dict, List

from .errors import StoreErr, StoreErrType
from .rolling_index import RollingIndex


class RollingIndexMap:
    def __init__(self, name: str, size: int, keys: List[int]):
        self.name = name
        self.size = size
        self.keys = list(keys)
        self.mapping: Dict[int, RollingIndex] = {
            k: RollingIndex(f"{name}[{k}]", size) for k in keys
        }

    def get(self, key: int, skip_index: int) -> List[Any]:
        if key not in self.mapping:
            raise StoreErr(self.name, StoreErrType.KEY_NOT_FOUND, str(key))
        return self.mapping[key].get(skip_index)

    def get_item(self, key: int, index: int) -> Any:
        return self.mapping[key].get_item(index)

    def get_last(self, key: int) -> Any:
        if key not in self.mapping:
            raise StoreErr(self.name, StoreErrType.KEY_NOT_FOUND, str(key))
        cached, _ = self.mapping[key].get_last_window()
        if not cached:
            raise StoreErr(self.name, StoreErrType.EMPTY, "")
        return cached[-1]

    def set(self, key: int, item: Any, index: int) -> None:
        if key not in self.mapping:
            self.mapping[key] = RollingIndex(f"{self.name}[{key}]", self.size)
        self.mapping[key].set(item, index)

    def known(self) -> Dict[int, int]:
        """[key] => last known absolute index."""
        return {k: ri.get_last_window()[1] for k, ri in self.mapping.items()}

    def reset(self) -> None:
        self.mapping = {k: RollingIndex(f"{self.name}[{k}]", self.size) for k in self.keys}
