"""Bounded LRU cache (reference: src/common/lru.go:11-156).

Python's OrderedDict gives us the recency list for free; the optional
eviction callback mirrors the reference API.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Optional


class LRU:
    def __init__(self, size: int, on_evict: Optional[Callable[[Any, Any], None]] = None):
        if size <= 0:
            raise ValueError("LRU size must be positive")
        self.size = size
        self.on_evict = on_evict
        self._items: OrderedDict = OrderedDict()

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key) -> bool:
        return key in self._items

    def get(self, key):
        """Returns (value, True) and refreshes recency, or (None, False)."""
        try:
            self._items.move_to_end(key)
        except KeyError:
            return None, False
        return self._items[key], True

    def peek(self, key):
        """Returns (value, True) without refreshing recency."""
        if key in self._items:
            return self._items[key], True
        return None, False

    def add(self, key, value) -> bool:
        """Adds a value; returns True if an eviction occurred."""
        if key in self._items:
            self._items.move_to_end(key)
            self._items[key] = value
            return False
        self._items[key] = value
        if len(self._items) > self.size:
            old_key, old_val = self._items.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(old_key, old_val)
            return True
        return False

    def remove(self, key) -> bool:
        if key in self._items:
            del self._items[key]
            return True
        return False

    def keys(self):
        """Keys oldest-to-newest."""
        return list(self._items.keys())

    def purge(self) -> None:
        if self.on_evict is not None:
            for k, v in self._items.items():
                self.on_evict(k, v)
        self._items.clear()
