"""Bounded sequential window (reference: src/common/rolling_index.go:5-98).

Holds up to 2*size gap-free items; when full, rolls by dropping the oldest
`size` items. Indexes are absolute (the producer's sequence numbers).
"""

from __future__ import annotations

from typing import Any, List, Tuple

from .errors import StoreErr, StoreErrType


class RollingIndex:
    def __init__(self, name: str, size: int):
        self.name = name
        self.size = size
        self.last_index = -1
        self.items: List[Any] = []

    def get_last_window(self) -> Tuple[List[Any], int]:
        return self.items, self.last_index

    def get(self, skip_index: int) -> List[Any]:
        """Items with absolute index > skip_index."""
        if skip_index > self.last_index:
            return []
        oldest_cached = self.last_index - len(self.items) + 1
        if skip_index + 1 < oldest_cached:
            raise StoreErr(self.name, StoreErrType.TOO_LATE, str(skip_index))
        start = skip_index - oldest_cached + 1
        return self.items[start:]

    def get_item(self, index: int) -> Any:
        oldest_cached = self.last_index - len(self.items) + 1
        if index < oldest_cached:
            raise StoreErr(self.name, StoreErrType.TOO_LATE, str(index))
        pos = index - oldest_cached
        if pos >= len(self.items):
            raise StoreErr(self.name, StoreErrType.KEY_NOT_FOUND, str(index))
        return self.items[pos]

    def set(self, item: Any, index: int) -> None:
        if 0 <= self.last_index and index > self.last_index + 1:
            raise StoreErr(self.name, StoreErrType.SKIPPED_INDEX, str(index))

        if self.last_index < 0 or index == self.last_index + 1:
            if len(self.items) >= 2 * self.size:
                self.roll()
            self.items.append(item)
            self.last_index = index
            return

        oldest_cached = self.last_index - len(self.items) + 1
        if index < oldest_cached:
            raise StoreErr(self.name, StoreErrType.TOO_LATE, str(index))
        self.items[index - oldest_cached] = item

    def roll(self) -> None:
        self.items = self.items[self.size:]
