"""Typed store errors (reference: src/common/errors.go:5-57)."""

from __future__ import annotations

import enum


class StoreErrType(enum.Enum):
    KEY_NOT_FOUND = "Not Found"
    TOO_LATE = "Too Late"
    PASSED_INDEX = "Passed Index"
    SKIPPED_INDEX = "Skipped Index"
    NO_ROOT = "No Root"
    UNKNOWN_PARTICIPANT = "Unknown Participant"
    EMPTY = "Empty"


class StoreErr(Exception):
    def __init__(self, data_type: str, err_type: StoreErrType, key: str = ""):
        self.data_type = data_type
        self.err_type = err_type
        self.key = key
        super().__init__(f"{data_type}, {key}, {err_type.value}")


def is_store_err(err: BaseException, err_type: StoreErrType) -> bool:
    return isinstance(err, StoreErr) and err.err_type is err_type
