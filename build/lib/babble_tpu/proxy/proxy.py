"""Application interface contracts (reference: src/proxy/proxy.go:7-12,
src/proxy/handlers.go:10-24).

AppProxy is the engine-side view of the application: a queue of submitted
transactions in, committed blocks (and snapshot/restore calls) out.
ProxyHandler is the application-side contract.
"""

from __future__ import annotations

import queue
from abc import ABC, abstractmethod

from ..hashgraph import Block


class AppProxy(ABC):
    @abstractmethod
    def submit_ch(self) -> "queue.Queue[bytes]":
        """Queue of raw transactions submitted by the app."""

    @abstractmethod
    def commit_block(self, block: Block) -> bytes:
        """Deliver a committed block to the app; returns the app state hash."""

    @abstractmethod
    def get_snapshot(self, block_index: int) -> bytes: ...

    @abstractmethod
    def restore(self, snapshot: bytes) -> bytes:
        """Restore app state from a snapshot; returns the resulting state hash."""


class ProxyHandler(ABC):
    @abstractmethod
    def commit_handler(self, block: Block) -> bytes: ...

    @abstractmethod
    def snapshot_handler(self, block_index: int) -> bytes: ...

    @abstractmethod
    def restore_handler(self, snapshot: bytes) -> bytes: ...
