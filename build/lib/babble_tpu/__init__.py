"""babble-tpu: a TPU-native BFT consensus framework.

A from-scratch rebuild of the capabilities of Babble (hashgraph consensus
middleware, reference: /root/reference) designed TPU-first: the host runtime
(gossip, DAG storage, blockchain projection, app proxy) is Python threads,
and the virtual-voting consensus core is expressed as dense batched array
kernels executed via JAX/XLA, swappable with a scalar CPU engine behind the
same `Hashgraph` API (reference: src/hashgraph/hashgraph.go).

Top-level surface: `Babble` (composition root + embedding API,
reference: src/babble/babble.go + src/mobile/node.go), `BabbleConfig`,
`keygen`, and `Service` (HTTP status endpoint).
"""

from .version import version as __version__  # noqa: F401

# the composition root pulls in every subsystem; import lazily so that
# `import babble_tpu.tpu.kernels` (device-only users) stays light
def __getattr__(name):
    if name in ("Babble", "BabbleConfig", "keygen", "default_data_dir"):
        from . import babble as _babble

        return getattr(_babble, name)
    if name == "Service":
        from .service import Service

        return Service
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
