"""Host:port address helpers shared by the TCP transport and JSON-RPC
proxies."""

from __future__ import annotations

from typing import Tuple

UNSPECIFIED_HOSTS = ("", "0.0.0.0", "::", "[::]")


def split_hostport(addr: str) -> Tuple[str, int]:
    """Split "host:port" into (host, port). Raises ValueError on a missing
    or non-numeric port."""
    host, _, port_s = addr.rpartition(":")
    if not host:
        raise ValueError(f"address {addr!r} has no host:port separator")
    return host, int(port_s)


def is_unspecified(host: str) -> bool:
    return host in UNSPECIFIED_HOSTS
