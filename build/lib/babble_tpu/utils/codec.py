"""Canonical deterministic encoding.

Consensus-critical hashes (event bodies, block bodies, frames, roots) must be
computed over a byte representation that every validator derives identically.
The reference leans on Go's encoding/json + ugorji canonical mode for this
(reference: src/hashgraph/root.go:108-126); we define a single canonical JSON
form used everywhere: sorted keys, compact separators, bytes as base64 text.
"""

from __future__ import annotations

import base64
import json
from typing import Any


def b64e(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def b64d(text: str) -> bytes:
    return base64.b64decode(text.encode("ascii"))


def _default(obj: Any):
    if isinstance(obj, (bytes, bytearray)):
        return {"__b64__": b64e(bytes(obj))}
    if hasattr(obj, "to_canonical"):
        return obj.to_canonical()
    raise TypeError(f"not canonically encodable: {type(obj)!r}")


def canonical_dumps(obj: Any) -> bytes:
    """Deterministic byte encoding of a JSON-able structure."""
    return json.dumps(
        obj,
        sort_keys=True,
        separators=(",", ":"),
        ensure_ascii=True,
        default=_default,
    ).encode("utf-8")


def _revive(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b64__"}:
            return b64d(obj["__b64__"])
        return {k: _revive(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_revive(v) for v in obj]
    return obj


def canonical_loads(data: bytes) -> Any:
    return _revive(json.loads(data.decode("utf-8")))
