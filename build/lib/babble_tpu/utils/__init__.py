from .codec import canonical_dumps, canonical_loads, b64e, b64d
from .netaddr import is_unspecified, split_hostport

__all__ = [
    "canonical_dumps",
    "canonical_loads",
    "b64e",
    "b64d",
    "split_hostport",
    "is_unspecified",
]
