"""SHA-256 and the simple Merkle fold (reference: src/crypto/hash.go:7-33)."""

from __future__ import annotations

import hashlib
from typing import List, Optional


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def simple_hash_from_two_hashes(left: bytes, right: bytes) -> bytes:
    h = hashlib.sha256()
    h.update(left)
    h.update(right)
    return h.digest()


def simple_hash_from_hashes(hashes: List[bytes]) -> Optional[bytes]:
    if len(hashes) == 0:
        return None
    if len(hashes) == 1:
        return hashes[0]
    mid = (len(hashes) + 1) // 2
    left = simple_hash_from_hashes(hashes[:mid])
    right = simple_hash_from_hashes(hashes[mid:])
    return simple_hash_from_two_hashes(left, right)
