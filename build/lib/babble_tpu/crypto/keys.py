"""ECDSA P-256 keys, signatures, and PEM I/O.

Mirrors the reference's choices (reference: src/crypto/utils.go:12-47,
src/crypto/pem_key.go:19-108): NIST P-256, uncompressed-point public keys
(0x04 || X || Y), signatures encoded as "r|s" in base-36 text (the r value
doubles as the Lamport tie-breaker in consensus ordering), and SEC1
"EC PRIVATE KEY" PEM files.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
    Prehashed,
)
from cryptography.exceptions import InvalidSignature

_CURVE = ec.SECP256R1()
_PREHASHED = Prehashed(hashes.SHA256())
# RFC 6979 deterministic nonces: same key + same digest => same (r, s).
# The reference signs with randomized nonces (src/crypto/utils.go:29-37),
# which standard verification accepts either way — but determinism is a
# strictly stronger contract this framework relies on: the signature's r
# value is the Lamport tie-breaker in consensus ordering (event.py), so a
# validator that re-signs an identical event body (crash replay, backend
# differential, process restart) must reproduce the same bytes or two
# otherwise bit-equal nodes order frames differently.
try:
    _SIGN_ALG = ec.ECDSA(_PREHASHED, deterministic_signing=True)
except TypeError as _e:  # cryptography < 42 lacks the keyword
    raise ImportError(
        "babble-tpu requires cryptography>=42.0 for RFC 6979 deterministic "
        "ECDSA (consensus ordering tie-breaks on signature bytes)"
    ) from _e

PEM_KEY_FILE = "priv_key.pem"

_B36_ALPHABET = "0123456789abcdefghijklmnopqrstuvwxyz"


def _int_to_base36(n: int) -> str:
    if n == 0:
        return "0"
    neg = n < 0
    n = abs(n)
    out = []
    while n:
        n, rem = divmod(n, 36)
        out.append(_B36_ALPHABET[rem])
    if neg:
        out.append("-")
    return "".join(reversed(out))


def generate_key() -> ec.EllipticCurvePrivateKey:
    return ec.generate_private_key(_CURVE)


def pub_key_bytes(key) -> bytes:
    """Uncompressed point encoding of the public key (65 bytes)."""
    pub = key.public_key() if isinstance(key, ec.EllipticCurvePrivateKey) else key
    return pub.public_bytes(
        serialization.Encoding.X962,
        serialization.PublicFormat.UncompressedPoint,
    )


def pub_key_from_bytes(data: bytes) -> Optional[ec.EllipticCurvePublicKey]:
    if not data:
        return None
    return ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, data)


def sign(key: ec.EllipticCurvePrivateKey, digest: bytes) -> Tuple[int, int]:
    """Sign a precomputed SHA-256 digest; returns (r, s). Deterministic
    (RFC 6979): signing the same digest with the same key reproduces the
    same signature bytes."""
    der = key.sign(digest, _SIGN_ALG)
    return decode_dss_signature(der)


def verify(pub: ec.EllipticCurvePublicKey, digest: bytes, r: int, s: int) -> bool:
    if pub is None:
        return False
    try:
        pub.verify(encode_dss_signature(r, s), digest, ec.ECDSA(_PREHASHED))
        return True
    except InvalidSignature:
        return False
    except ValueError:
        return False


def encode_signature(r: int, s: int) -> str:
    return f"{_int_to_base36(r)}|{_int_to_base36(s)}"


def decode_signature(sig: str) -> Tuple[int, int]:
    values = sig.split("|")
    if len(values) != 2:
        raise ValueError(f"wrong number of values in signature: got {len(values)}, want 2")
    return int(values[0], 36), int(values[1], 36)


def key_to_pem(key: ec.EllipticCurvePrivateKey) -> str:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,  # SEC1 "EC PRIVATE KEY"
        serialization.NoEncryption(),
    ).decode("ascii")


def key_from_pem(data: bytes) -> ec.EllipticCurvePrivateKey:
    return serialization.load_pem_private_key(data, password=None)


@dataclass
class PemDump:
    public_key: str
    private_key: str


def to_pem_dump(key: ec.EllipticCurvePrivateKey) -> PemDump:
    pub_hex = "0x" + pub_key_bytes(key).hex().upper()
    return PemDump(public_key=pub_hex, private_key=key_to_pem(key))


class PemKey:
    """Private-key file in a data directory (reference: src/crypto/pem_key.go)."""

    def __init__(self, base: str):
        self.path = os.path.join(base, PEM_KEY_FILE)

    def read_key(self) -> ec.EllipticCurvePrivateKey:
        with open(self.path, "rb") as f:
            return key_from_pem(f.read())

    def write_key(self, key: ec.EllipticCurvePrivateKey) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with open(self.path, "w") as f:
            f.write(key_to_pem(key))
