from .keys import (
    generate_key,
    pub_key_bytes,
    pub_key_from_bytes,
    sign,
    verify,
    encode_signature,
    decode_signature,
    key_to_pem,
    key_from_pem,
    to_pem_dump,
    PemDump,
    PemKey,
)
from .hashing import sha256, simple_hash_from_two_hashes, simple_hash_from_hashes

__all__ = [
    "generate_key",
    "pub_key_bytes",
    "pub_key_from_bytes",
    "sign",
    "verify",
    "encode_signature",
    "decode_signature",
    "key_to_pem",
    "key_from_pem",
    "to_pem_dump",
    "PemDump",
    "PemKey",
    "sha256",
    "simple_hash_from_two_hashes",
    "simple_hash_from_hashes",
]
