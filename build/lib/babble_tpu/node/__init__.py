from .config import Config, default_config, test_config
from .control_timer import ControlTimer, new_random_control_timer
from .core import Core
from .node import Node
from .peer_selector import PeerSelector, RandomPeerSelector
from .state import NodeState, NodeStateMachine

__all__ = [
    "Config",
    "default_config",
    "test_config",
    "ControlTimer",
    "new_random_control_timer",
    "Core",
    "Node",
    "PeerSelector",
    "RandomPeerSelector",
    "NodeState",
    "NodeStateMachine",
]
