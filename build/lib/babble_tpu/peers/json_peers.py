"""peers.json store (reference: src/peers/json_peers.go:13-72)."""

from __future__ import annotations

import json
import os
import threading
from typing import List, Optional

from .peer import Peer
from .peers import Peers

JSON_PEER_FILE = "peers.json"


class JSONPeers:
    def __init__(self, base: str):
        self.path = os.path.join(base, JSON_PEER_FILE)
        self._lock = threading.Lock()

    def peers(self) -> Optional[Peers]:
        with self._lock:
            with open(self.path, "rb") as f:
                buf = f.read()
            if not buf:
                return None
            peer_set = [Peer.from_json(d) for d in json.loads(buf)]
            return Peers.from_slice(peer_set)

    def set_peers(self, peers: List[Peer]) -> None:
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "w") as f:
                json.dump([p.to_json() for p in peers], f)
