from .peer import Peer, exclude_peer
from .peers import Peers
from .json_peers import JSONPeers

__all__ = ["Peer", "Peers", "JSONPeers", "exclude_peer"]
