"""Validator identity (reference: src/peers/peer.go:13-70).

A peer's ID is the FNV-1a 32-bit hash of its raw public-key bytes; IDs also
index the dense on-device grids via the peer's position in the sorted set.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

from ..common import hash32


@dataclass
class Peer:
    net_addr: str = ""
    pub_key_hex: str = ""
    id: int = field(default=0)

    def __post_init__(self):
        if self.id == 0 and self.pub_key_hex:
            self.compute_id()

    def pub_key_bytes(self) -> bytes:
        return bytes.fromhex(self.pub_key_hex[2:])

    def compute_id(self) -> None:
        self.id = hash32(self.pub_key_bytes())

    def to_json(self) -> dict:
        return {"NetAddr": self.net_addr, "PubKeyHex": self.pub_key_hex}

    @classmethod
    def from_json(cls, d: dict) -> "Peer":
        return cls(net_addr=d.get("NetAddr", ""), pub_key_hex=d.get("PubKeyHex", ""))


def exclude_peer(peers: List[Peer], addr: str) -> Tuple[int, List[Peer]]:
    """Remove the peer with the given net address; returns (index, remaining)."""
    index = -1
    others: List[Peer] = []
    for i, p in enumerate(peers):
        if p.net_addr != addr:
            others.append(p)
        else:
            index = i
    return index, others
