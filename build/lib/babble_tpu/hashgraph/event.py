"""Signed DAG vertex (reference: src/hashgraph/event.go).

An Event carries payload transactions, two parent hashes (self-parent first),
the creator's public key, the creator-sequence index, and block signatures.
The hash identifying an event is the SHA-256 of the canonical encoding of its
body; the wire form replaces parent hashes with dense (creatorID, index) int
pairs (reference: src/hashgraph/event.go:353-368) — which is also exactly the
coordinate encoding the TPU kernels consume.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .. import crypto
from ..utils.codec import canonical_dumps, b64e


def root_self_parent(participant_id: int) -> str:
    return f"Root{participant_id}"


@dataclass
class EventBody:
    transactions: List[bytes] = field(default_factory=list)
    parents: List[str] = field(default_factory=lambda: ["", ""])  # [self, other]
    creator: bytes = b""
    index: int = -1
    block_signatures: List["BlockSignature"] = field(default_factory=list)

    # wire info (not part of the canonical hash, like the reference's
    # unexported fields, reference: src/hashgraph/event.go:25-28)
    self_parent_index: int = -1
    other_parent_creator_id: int = -1
    other_parent_index: int = -1
    creator_id: int = -1

    def to_canonical(self) -> dict:
        return {
            "Transactions": [b64e(t) for t in self.transactions],
            "Parents": list(self.parents),
            "Creator": b64e(self.creator),
            "Index": self.index,
            "BlockSignatures": [bs.to_canonical() for bs in self.block_signatures],
        }

    def marshal(self) -> bytes:
        return canonical_dumps(self.to_canonical())

    def hash(self) -> bytes:
        return crypto.sha256(self.marshal())


class Event:
    __slots__ = (
        "body",
        "signature",
        "topological_index",
        "round",
        "lamport_timestamp",
        "round_received",
        "last_ancestors",
        "first_descendants",
        "_creator",
        "_hash",
        "_hex",
    )

    def __init__(
        self,
        transactions: Optional[List[bytes]] = None,
        block_signatures: Optional[List["BlockSignature"]] = None,
        parents: Optional[List[str]] = None,
        creator: bytes = b"",
        index: int = -1,
    ):
        self.body = EventBody(
            transactions=list(transactions or []),
            block_signatures=list(block_signatures or []),
            parents=list(parents or ["", ""]),
            creator=creator,
            index=index,
        )
        self.signature: str = ""
        self.topological_index: int = -1
        self.round: Optional[int] = None
        self.lamport_timestamp: Optional[int] = None
        self.round_received: Optional[int] = None
        # dense coordinate rows: [peer position] -> (index, hash) per creator;
        # the vector-clock-like structures making ancestry O(1)
        # (reference: src/hashgraph/event.go:115-116)
        self.last_ancestors: Optional[List[Tuple[int, str]]] = None
        self.first_descendants: Optional[List[Tuple[int, str]]] = None
        self._creator: str = ""
        self._hash: bytes = b""
        self._hex: str = ""

    # -- identity ----------------------------------------------------------

    def creator(self) -> str:
        if not self._creator:
            self._creator = "0x" + self.body.creator.hex().upper()
        return self._creator

    def self_parent(self) -> str:
        return self.body.parents[0]

    def other_parent(self) -> str:
        return self.body.parents[1]

    def transactions(self) -> List[bytes]:
        return self.body.transactions

    def index(self) -> int:
        return self.body.index

    def block_signatures(self) -> List["BlockSignature"]:
        return self.body.block_signatures

    def is_loaded(self) -> bool:
        """True if the event carries payload or is its creator's first event."""
        if self.body.index == 0:
            return True
        return bool(self.body.transactions)

    def hash(self) -> bytes:
        if not self._hash:
            self._hash = self.body.hash()
        return self._hash

    def hex(self) -> str:
        if not self._hex:
            self._hex = "0x" + self.hash().hex().upper()
        return self._hex

    # -- signature ---------------------------------------------------------

    def sign(self, key) -> None:
        r, s = crypto.sign(key, self.body.hash())
        self.signature = crypto.encode_signature(r, s)

    def verify(self) -> bool:
        pub = crypto.pub_key_from_bytes(self.body.creator)
        r, s = crypto.decode_signature(self.signature)
        return crypto.verify(pub, self.body.hash(), r, s)

    # -- consensus metadata ------------------------------------------------

    def set_round(self, r: int) -> None:
        self.round = r

    def set_lamport_timestamp(self, t: int) -> None:
        self.lamport_timestamp = t

    def set_round_received(self, rr: int) -> None:
        self.round_received = rr

    def set_wire_info(
        self,
        self_parent_index: int,
        other_parent_creator_id: int,
        other_parent_index: int,
        creator_id: int,
    ) -> None:
        self.body.self_parent_index = self_parent_index
        self.body.other_parent_creator_id = other_parent_creator_id
        self.body.other_parent_index = other_parent_index
        self.body.creator_id = creator_id

    # -- wire --------------------------------------------------------------

    def to_wire(self) -> "WireEvent":
        return WireEvent(
            body=WireBody(
                transactions=list(self.body.transactions),
                block_signatures=[bs.to_wire() for bs in self.body.block_signatures],
                self_parent_index=self.body.self_parent_index,
                other_parent_creator_id=self.body.other_parent_creator_id,
                other_parent_index=self.body.other_parent_index,
                creator_id=self.body.creator_id,
                index=self.body.index,
            ),
            signature=self.signature,
        )

    # -- serialization (store / frames) ------------------------------------

    def to_canonical(self) -> dict:
        return {"Body": self.body.to_canonical(), "Signature": self.signature}

    def to_json(self) -> dict:
        d = self.to_canonical()
        d["WireInfo"] = [
            self.body.self_parent_index,
            self.body.other_parent_creator_id,
            self.body.other_parent_index,
            self.body.creator_id,
        ]
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Event":
        from .block import BlockSignature
        from ..utils.codec import b64d

        body = d["Body"]
        ev = cls(
            transactions=[b64d(t) for t in body["Transactions"]],
            block_signatures=[BlockSignature.from_canonical(b) for b in body["BlockSignatures"]],
            parents=list(body["Parents"]),
            creator=b64d(body["Creator"]),
            index=body["Index"],
        )
        ev.signature = d.get("Signature", "")
        wi = d.get("WireInfo")
        if wi:
            ev.set_wire_info(wi[0], wi[1], wi[2], wi[3])
        return ev

    def to_store_json(self) -> dict:
        """Full serialization including consensus metadata and coordinate
        rows — used by persistent stores so a cache-evicted event read back
        from disk is indistinguishable from the live object. (The reference
        loses the unexported coordinate fields on a Badger read-back,
        reference: src/hashgraph/badger_store.go:343-360; restoring them
        here makes the persistent store safe under LRU eviction.)"""
        d = self.to_json()
        d["Meta"] = {
            "Topo": self.topological_index,
            "Round": self.round,
            "Lamport": self.lamport_timestamp,
            "RoundReceived": self.round_received,
            "LastAncestors": self.last_ancestors,
            "FirstDescendants": self.first_descendants,
        }
        return d

    @classmethod
    def from_store_json(cls, d: dict) -> "Event":
        ev = cls.from_json(d)
        meta = d.get("Meta")
        if meta:
            ev.topological_index = meta["Topo"]
            ev.round = meta["Round"]
            ev.lamport_timestamp = meta["Lamport"]
            ev.round_received = meta["RoundReceived"]
            if meta["LastAncestors"] is not None:
                ev.last_ancestors = [tuple(x) for x in meta["LastAncestors"]]
            if meta["FirstDescendants"] is not None:
                ev.first_descendants = [tuple(x) for x in meta["FirstDescendants"]]
        return ev

    def __repr__(self) -> str:
        return f"Event({self.creator()[:10]}..#{self.index()})"


def by_lamport_key(ev: Event) -> Tuple[int, int]:
    """Total-order sort key: Lamport timestamp, ties broken by the numeric
    value of the signature's r component (reference: src/hashgraph/event.go:328-347)."""
    lt = ev.lamport_timestamp if ev.lamport_timestamp is not None else -1
    try:
        r, _ = crypto.decode_signature(ev.signature)
    except (ValueError, IndexError):
        r = 0
    return (lt, r)


@dataclass
class WireBody:
    transactions: List[bytes] = field(default_factory=list)
    block_signatures: List["WireBlockSignature"] = field(default_factory=list)
    self_parent_index: int = -1
    other_parent_creator_id: int = -1
    other_parent_index: int = -1
    creator_id: int = -1
    index: int = -1


@dataclass
class WireEvent:
    body: WireBody
    signature: str = ""

    def block_signatures(self, validator: bytes) -> List["BlockSignature"]:
        from .block import BlockSignature

        return [
            BlockSignature(validator=validator, index=ws.index, signature=ws.signature)
            for ws in self.body.block_signatures
        ]

    def to_json(self) -> dict:
        return {
            "Body": {
                "Transactions": [b64e(t) for t in self.body.transactions],
                "BlockSignatures": [
                    {"Index": ws.index, "Signature": ws.signature}
                    for ws in self.body.block_signatures
                ],
                "SelfParentIndex": self.body.self_parent_index,
                "OtherParentCreatorID": self.body.other_parent_creator_id,
                "OtherParentIndex": self.body.other_parent_index,
                "CreatorID": self.body.creator_id,
                "Index": self.body.index,
            },
            "Signature": self.signature,
        }

    @classmethod
    def from_json(cls, d: dict) -> "WireEvent":
        from .block import WireBlockSignature
        from ..utils.codec import b64d

        b = d["Body"]
        return cls(
            body=WireBody(
                transactions=[b64d(t) for t in b["Transactions"]],
                block_signatures=[
                    WireBlockSignature(index=w["Index"], signature=w["Signature"])
                    for w in b["BlockSignatures"]
                ],
                self_parent_index=b["SelfParentIndex"],
                other_parent_creator_id=b["OtherParentCreatorID"],
                other_parent_index=b["OtherParentIndex"],
                creator_id=b["CreatorID"],
                index=b["Index"],
            ),
            signature=d.get("Signature", ""),
        )
