"""Blockchain projection of consensus rounds (reference: src/hashgraph/block.go).

A Block carries the ordered transactions of one consensus round, the frame
hash anchoring it to the DAG, the app's state hash, and a map of validator
signatures collected via the gossiped signature pool.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .. import crypto
from ..utils.codec import canonical_dumps, b64e, b64d


@dataclass
class WireBlockSignature:
    index: int = -1
    signature: str = ""


@dataclass
class BlockSignature:
    validator: bytes = b""
    index: int = -1
    signature: str = ""

    def validator_hex(self) -> str:
        return "0x" + self.validator.hex().upper()

    def to_wire(self) -> WireBlockSignature:
        return WireBlockSignature(index=self.index, signature=self.signature)

    def to_canonical(self) -> dict:
        return {"Validator": b64e(self.validator), "Index": self.index, "Signature": self.signature}

    @classmethod
    def from_canonical(cls, d: dict) -> "BlockSignature":
        return cls(validator=b64d(d["Validator"]), index=d["Index"], signature=d["Signature"])


@dataclass
class BlockBody:
    index: int = -1
    round_received: int = -1
    state_hash: bytes = b""
    frame_hash: bytes = b""
    transactions: List[bytes] = field(default_factory=list)

    def to_canonical(self) -> dict:
        return {
            "Index": self.index,
            "RoundReceived": self.round_received,
            "StateHash": b64e(self.state_hash),
            "FrameHash": b64e(self.frame_hash),
            "Transactions": [b64e(t) for t in self.transactions],
        }

    def marshal(self) -> bytes:
        return canonical_dumps(self.to_canonical())

    def hash(self) -> bytes:
        return crypto.sha256(self.marshal())


class Block:
    def __init__(
        self,
        index: int = -1,
        round_received: int = -1,
        frame_hash: bytes = b"",
        transactions: List[bytes] | None = None,
    ):
        self.body = BlockBody(
            index=index,
            round_received=round_received,
            frame_hash=frame_hash,
            transactions=list(transactions or []),
        )
        self.signatures: Dict[str, str] = {}  # [validator hex] => signature
        self._hash: bytes = b""

    def index(self) -> int:
        return self.body.index

    def transactions(self) -> List[bytes]:
        return self.body.transactions

    def round_received(self) -> int:
        return self.body.round_received

    def state_hash(self) -> bytes:
        return self.body.state_hash

    def frame_hash(self) -> bytes:
        return self.body.frame_hash

    def get_signatures(self) -> List[BlockSignature]:
        return [
            BlockSignature(
                validator=bytes.fromhex(val[2:]), index=self.index(), signature=sig
            )
            for val, sig in self.signatures.items()
        ]

    def get_signature(self, validator: str) -> BlockSignature:
        if validator not in self.signatures:
            raise KeyError("signature not found")
        return BlockSignature(
            validator=bytes.fromhex(validator[2:]),
            index=self.index(),
            signature=self.signatures[validator],
        )

    def append_transactions(self, txs: List[bytes]) -> None:
        self.body.transactions.extend(txs)

    def marshal(self) -> bytes:
        return canonical_dumps(self.to_json())

    def hash(self) -> bytes:
        # frozen on first call so a block's identity does not drift as
        # signatures are attached (reference: src/hashgraph/block.go:196-205)
        if not self._hash:
            self._hash = crypto.sha256(self.marshal())
        return self._hash

    def hex(self) -> str:
        return "0x" + self.hash().hex().upper()

    def sign(self, key) -> BlockSignature:
        r, s = crypto.sign(key, self.body.hash())
        return BlockSignature(
            validator=crypto.pub_key_bytes(key),
            index=self.index(),
            signature=crypto.encode_signature(r, s),
        )

    def set_signature(self, bs: BlockSignature) -> None:
        self.signatures[bs.validator_hex()] = bs.signature

    def verify(self, sig: BlockSignature) -> bool:
        pub = crypto.pub_key_from_bytes(sig.validator)
        try:
            r, s = crypto.decode_signature(sig.signature)
        except ValueError:
            return False
        return crypto.verify(pub, self.body.hash(), r, s)

    def to_json(self) -> dict:
        return {
            "Body": self.body.to_canonical(),
            "Signatures": dict(sorted(self.signatures.items())),
        }

    @classmethod
    def from_json(cls, d: dict) -> "Block":
        b = d["Body"]
        block = cls(
            index=b["Index"],
            round_received=b["RoundReceived"],
            frame_hash=b64d(b["FrameHash"]),
            transactions=[b64d(t) for t in b["Transactions"]],
        )
        block.body.state_hash = b64d(b["StateHash"])
        block.signatures = dict(d.get("Signatures", {}))
        return block

    def __repr__(self) -> str:
        return f"Block(#{self.index()}, rr={self.round_received()}, txs={len(self.transactions())})"


def new_block_from_frame(block_index: int, frame) -> Block:
    transactions: List[bytes] = []
    for e in frame.events:
        transactions.extend(e.transactions())
    return Block(
        index=block_index,
        round_received=frame.round,
        frame_hash=frame.hash(),
        transactions=transactions,
    )
