"""Roots: the base layer of a (possibly mid-history) hashgraph
(reference: src/hashgraph/root.go).

Each participant gets a Root; the first event a participant inserts must
attach to it. Roots enable Frame-based reset — initializing a hashgraph from
the middle of another one (fast-sync). Canonical encoding is
consensus-critical because root bytes feed the frame hash
(reference: src/hashgraph/root.go:108-126).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from .event import root_self_parent


@dataclass
class RootEvent:
    hash: str = ""
    creator_id: int = -1
    index: int = -1
    lamport_timestamp: int = -1
    round: int = -1

    def to_canonical(self) -> dict:
        return {
            "Hash": self.hash,
            "CreatorID": self.creator_id,
            "Index": self.index,
            "LamportTimestamp": self.lamport_timestamp,
            "Round": self.round,
        }

    @classmethod
    def from_canonical(cls, d: dict) -> "RootEvent":
        return cls(
            hash=d["Hash"],
            creator_id=d["CreatorID"],
            index=d["Index"],
            lamport_timestamp=d["LamportTimestamp"],
            round=d["Round"],
        )


def new_base_root_event(creator_id: int) -> RootEvent:
    return RootEvent(
        hash=root_self_parent(creator_id),
        creator_id=creator_id,
        index=-1,
        lamport_timestamp=-1,
        round=-1,
    )


@dataclass
class Root:
    next_round: int = 0
    self_parent: RootEvent = field(default_factory=RootEvent)
    others: Dict[str, RootEvent] = field(default_factory=dict)

    def to_canonical(self) -> dict:
        return {
            "NextRound": self.next_round,
            "SelfParent": self.self_parent.to_canonical(),
            "Others": {k: v.to_canonical() for k, v in sorted(self.others.items())},
        }

    @classmethod
    def from_canonical(cls, d: dict) -> "Root":
        return cls(
            next_round=d["NextRound"],
            self_parent=RootEvent.from_canonical(d["SelfParent"]),
            others={k: RootEvent.from_canonical(v) for k, v in d["Others"].items()},
        )


def new_base_root(creator_id: int) -> Root:
    return Root(next_round=0, self_parent=new_base_root_event(creator_id), others={})
