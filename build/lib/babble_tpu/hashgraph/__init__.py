from .event import Event, EventBody, WireEvent, WireBody, root_self_parent, by_lamport_key
from .root import Root, RootEvent, new_base_root, new_base_root_event
from .round_info import RoundInfo, RoundEvent, Trilean, PendingRound
from .frame import Frame
from .section import FrozenRef, Section
from .block import Block, BlockBody, BlockSignature, WireBlockSignature, new_block_from_frame
from .store import Store
from .inmem_store import InmemStore
from .caches import ParticipantEventsCache, ParticipantBlockSignaturesCache
from .hashgraph import Hashgraph
from .sqlite_store import SQLiteStore

__all__ = [
    "Event",
    "EventBody",
    "WireEvent",
    "WireBody",
    "root_self_parent",
    "by_lamport_key",
    "Root",
    "RootEvent",
    "new_base_root",
    "new_base_root_event",
    "RoundInfo",
    "RoundEvent",
    "Trilean",
    "PendingRound",
    "Frame",
    "FrozenRef",
    "Section",
    "Block",
    "BlockBody",
    "BlockSignature",
    "WireBlockSignature",
    "new_block_from_frame",
    "Store",
    "InmemStore",
    "SQLiteStore",
    "ParticipantEventsCache",
    "ParticipantBlockSignaturesCache",
    "Hashgraph",
]
