"""Storage abstraction for events, rounds, roots, blocks, and frames
(reference: src/hashgraph/store.go:5-34)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Tuple

from .block import Block
from .event import Event
from .frame import Frame
from .root import Root
from .round_info import RoundInfo


class Store(ABC):
    @abstractmethod
    def cache_size(self) -> int: ...

    @abstractmethod
    def participants(self): ...

    @abstractmethod
    def roots_by_self_parent(self) -> Dict[str, Root]: ...

    @abstractmethod
    def get_event(self, key: str) -> Event: ...

    @abstractmethod
    def set_event(self, event: Event) -> None: ...

    @abstractmethod
    def participant_events(self, participant: str, skip: int) -> List[str]: ...

    @abstractmethod
    def participant_event(self, participant: str, index: int) -> str: ...

    @abstractmethod
    def last_event_from(self, participant: str) -> Tuple[str, bool]: ...

    @abstractmethod
    def last_consensus_event_from(self, participant: str) -> Tuple[str, bool]: ...

    @abstractmethod
    def known_events(self) -> Dict[int, int]: ...

    @abstractmethod
    def consensus_events(self) -> List[str]: ...

    @abstractmethod
    def consensus_events_count(self) -> int: ...

    @abstractmethod
    def add_consensus_event(self, event: Event) -> None: ...

    @abstractmethod
    def seed_last_consensus_event(self, participant: str, event_hex: str) -> None:
        """Install a fast-sync baseline for last_consensus_event_from
        without counting a locally processed event (Hashgraph.apply_section)."""

    @abstractmethod
    def get_round(self, r: int) -> RoundInfo: ...

    @abstractmethod
    def set_round(self, r: int, round_info: RoundInfo) -> None: ...

    @abstractmethod
    def last_round(self) -> int: ...

    @abstractmethod
    def round_witnesses(self, r: int) -> List[str]: ...

    @abstractmethod
    def round_events(self, r: int) -> int: ...

    @abstractmethod
    def get_root(self, participant: str) -> Root: ...

    @abstractmethod
    def get_block(self, index: int) -> Block: ...

    @abstractmethod
    def set_block(self, block: Block) -> None: ...

    @abstractmethod
    def last_block_index(self) -> int: ...

    @abstractmethod
    def get_frame(self, index: int) -> Frame: ...

    @abstractmethod
    def set_frame(self, frame: Frame) -> None: ...

    @abstractmethod
    def reset(self, roots: Dict[str, Root]) -> None: ...

    @abstractmethod
    def close(self) -> None: ...

    @abstractmethod
    def need_bootstrap(self) -> bool: ...

    @abstractmethod
    def store_path(self) -> str: ...
