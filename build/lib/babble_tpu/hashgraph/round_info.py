"""Per-round event registry with witness/famous/consensus flags
(reference: src/hashgraph/roundInfo.go).

Unlike the reference's Go maps (whose iteration order is random — safe only
because the algorithm is order-independent), we keep insertion-ordered dicts,
giving deterministic iteration everywhere for free.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List


class Trilean(enum.IntEnum):
    UNDEFINED = 0
    TRUE = 1
    FALSE = 2

    def __str__(self) -> str:
        return {0: "Undefined", 1: "True", 2: "False"}[int(self)]


@dataclass
class PendingRound:
    index: int
    decided: bool = False


@dataclass
class RoundEvent:
    consensus: bool = False
    witness: bool = False
    famous: Trilean = Trilean.UNDEFINED


@dataclass
class RoundInfo:
    events: Dict[str, RoundEvent] = field(default_factory=dict)
    queued: bool = False

    def add_event(self, x: str, witness: bool) -> None:
        if x not in self.events:
            self.events[x] = RoundEvent(witness=witness)

    def set_consensus_event(self, x: str) -> None:
        e = self.events.setdefault(x, RoundEvent())
        e.consensus = True

    def set_fame(self, x: str, famous: bool) -> None:
        e = self.events.setdefault(x, RoundEvent(witness=True))
        e.famous = Trilean.TRUE if famous else Trilean.FALSE

    def witnesses_decided(self) -> bool:
        """True if no witness's fame is left undefined."""
        return all(
            not e.witness or e.famous != Trilean.UNDEFINED for e in self.events.values()
        )

    def witnesses(self) -> List[str]:
        return [x for x, e in self.events.items() if e.witness]

    def round_events(self) -> List[str]:
        return [x for x, e in self.events.items() if not e.consensus]

    def consensus_events(self) -> List[str]:
        return [x for x, e in self.events.items() if e.consensus]

    def famous_witnesses(self) -> List[str]:
        return [x for x, e in self.events.items() if e.witness and e.famous == Trilean.TRUE]

    def is_decided(self, witness: str) -> bool:
        e = self.events.get(witness)
        return e is not None and e.witness and e.famous != Trilean.UNDEFINED

    def to_json(self) -> dict:
        # `queued` is deliberately NOT serialized: it is node-local pipeline
        # state; a bootstrap replay must re-queue persisted rounds (the
        # reference keeps it unexported for the same effect,
        # reference: src/hashgraph/roundInfo.go:35)
        return {
            "Events": {
                x: {"Consensus": e.consensus, "Witness": e.witness, "Famous": int(e.famous)}
                for x, e in self.events.items()
            },
        }

    @classmethod
    def from_json(cls, d: dict) -> "RoundInfo":
        ri = cls(queued=False)
        for x, e in d.get("Events", {}).items():
            ri.events[x] = RoundEvent(
                consensus=e["Consensus"], witness=e["Witness"], famous=Trilean(e["Famous"])
            )
        return ri
