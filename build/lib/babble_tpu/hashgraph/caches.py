"""Per-participant rolling caches (reference: src/hashgraph/caches.go).

ParticipantEventsCache holds each validator's recent event hashes by
creator-sequence index — powering EventDiff and wire-ID resolution.
"""

from __future__ import annotations

from typing import Dict, List

from ..common import RollingIndexMap, StoreErr, StoreErrType
from ..peers import Peers
from .block import BlockSignature


class ParticipantEventsCache:
    def __init__(self, size: int, participants: Peers):
        self.participants = participants
        self.rim = RollingIndexMap("ParticipantEvents", size, participants.to_id_slice())

    def _participant_id(self, participant: str) -> int:
        peer = self.participants.by_pub_key.get(participant)
        if peer is None:
            raise StoreErr("ParticipantEvents", StoreErrType.UNKNOWN_PARTICIPANT, participant)
        return peer.id

    def get(self, participant: str, skip_index: int) -> List[str]:
        return list(self.rim.get(self._participant_id(participant), skip_index))

    def get_item(self, participant: str, index: int) -> str:
        return self.rim.get_item(self._participant_id(participant), index)

    def get_last(self, participant: str) -> str:
        return self.rim.get_last(self._participant_id(participant))

    def set(self, participant: str, hash_: str, index: int) -> None:
        self.rim.set(self._participant_id(participant), hash_, index)

    def known(self) -> Dict[int, int]:
        return self.rim.known()

    def reset(self) -> None:
        self.rim.reset()


class ParticipantBlockSignaturesCache:
    def __init__(self, size: int, participants: Peers):
        self.participants = participants
        self.rim = RollingIndexMap(
            "ParticipantBlockSignatures", size, participants.to_id_slice()
        )

    def _participant_id(self, participant: str) -> int:
        peer = self.participants.by_pub_key.get(participant)
        if peer is None:
            raise StoreErr(
                "ParticipantBlockSignatures", StoreErrType.UNKNOWN_PARTICIPANT, participant
            )
        return peer.id

    def get(self, participant: str, skip_index: int) -> List[BlockSignature]:
        return list(self.rim.get(self._participant_id(participant), skip_index))

    def get_item(self, participant: str, index: int) -> BlockSignature:
        return self.rim.get_item(self._participant_id(participant), index)

    def get_last(self, participant: str) -> BlockSignature:
        return self.rim.get_last(self._participant_id(participant))

    def set(self, participant: str, sig: BlockSignature) -> None:
        self.rim.set(self._participant_id(participant), sig, sig.index)

    def known(self) -> Dict[int, int]:
        return self.rim.known()

    def reset(self) -> None:
        self.rim.reset()
