"""Fast-sync live-DAG section — the part of fast-forward that goes beyond
the reference.

The reference's FastForward ships only the anchor block + one Frame
(the consensus events of the anchor round, reference:
src/net/commands.go:31-40, src/hashgraph/hashgraph.go:1125-1231). A joiner
must then *re-decide* every round above the anchor from a DAG whose
pre-frame region it cannot see. Its witness sets and strongly-see
relations around the anchor are incomplete, so its round numbers — and
therefore fame votes, round-received assignments, and block contents —
can diverge from the rest of the network (observed: byte-different
blocks right after a fast-forward; the reference has the same structural
gap and merely logs 'Invalid block signature').

The Section closes the gap by shipping the donor's *decided state* for
everything above the anchor cut:

- every event whose round-received is above the anchor round or still
  undetermined, with authoritative metadata (round, lamport, coordinate
  rows) via Event.to_store_json;
- RoundInfo snapshots for rounds above the anchor (witness flags, fame
  trileans, consensus membership);
- the already-built Frames for rounds (anchor, last-consensus] so the
  joiner replays byte-identical blocks instead of rebuilding them;
- FrozenRefs: (round, lamport, creator, index) for other-parents that sit
  below the cut — enough for root construction without the event bodies.

The joiner replays this state verbatim and only *continues* consensus
from the donor's frontier, which restores determinism: its subsequent
decisions use exactly the data every other node uses.

Trust model: like the reference's Frame minus the anchor-hash check —
the section is donor-trusted (event signatures are still verified;
metadata is not independently verifiable without the frozen region).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .block import Block
from .event import Event
from .frame import Frame
from .round_info import RoundInfo


@dataclass
class FrozenRef:
    """Identity of an event below the section cut, referenced as an
    other-parent by a section event (serves GetFrame root construction)."""

    hash: str
    creator_id: int
    index: int
    round: int
    lamport: int

    def to_json(self) -> dict:
        return {
            "Hash": self.hash,
            "CreatorID": self.creator_id,
            "Index": self.index,
            "Round": self.round,
            "Lamport": self.lamport,
        }

    @classmethod
    def from_json(cls, d: dict) -> "FrozenRef":
        return cls(
            hash=d["Hash"],
            creator_id=d["CreatorID"],
            index=d["Index"],
            round=d["Round"],
            lamport=d["Lamport"],
        )


@dataclass
class Section:
    """Donor state above the anchor cut."""

    anchor_round: int
    last_consensus_round: int
    events: List[Event] = field(default_factory=list)  # topo order, full meta
    rounds: Dict[int, RoundInfo] = field(default_factory=dict)
    frames: List[Frame] = field(default_factory=list)
    frozen_refs: List[FrozenRef] = field(default_factory=list)
    # authoritative (round, lamport) for the anchor frame's own events: the
    # joiner must not recompute them from its amnesiac base, or future
    # frame roots that reference them diverge (the Frame wire format itself
    # cannot carry this — its hash is pinned in the anchor block)
    base_meta: List[FrozenRef] = field(default_factory=list)
    # the donor's stored blocks (with their accumulated validator
    # signatures) per replayed block index: proof material that lets the
    # joiner verify the replayed chain against >1/3 of the validator set
    # before committing anything (Hashgraph.verify_section) — the
    # signatures cover the full block body (index, round, state hash,
    # frame hash, txs), so they must travel with the body they signed
    proof_blocks: Dict[int, Block] = field(default_factory=dict)
    # participant pubkey -> last consensus event hash as of the anchor
    # round: seeds the joiner's last-consensus-event bookkeeping so frame
    # roots for participants quiet since the anchor are built from the
    # same event on every node (divergent roots change the frame hash and
    # break block byte-equality)
    consensus_baseline: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "AnchorRound": self.anchor_round,
            "LastConsensusRound": self.last_consensus_round,
            "Events": [e.to_store_json() for e in self.events],
            "Rounds": {str(r): ri.to_json() for r, ri in self.rounds.items()},
            "Frames": [f.to_json() for f in self.frames],
            "FrozenRefs": [fr.to_json() for fr in self.frozen_refs],
            "BaseMeta": [fr.to_json() for fr in self.base_meta],
            "ProofBlocks": {
                str(i): b.to_json() for i, b in self.proof_blocks.items()
            },
            "ConsensusBaseline": dict(sorted(self.consensus_baseline.items())),
        }

    @classmethod
    def from_json(cls, d: dict) -> "Section":
        return cls(
            anchor_round=d["AnchorRound"],
            last_consensus_round=d["LastConsensusRound"],
            events=[Event.from_store_json(e) for e in d.get("Events", [])],
            rounds={
                int(r): RoundInfo.from_json(ri)
                for r, ri in d.get("Rounds", {}).items()
            },
            frames=[Frame.from_json(f) for f in d.get("Frames", [])],
            frozen_refs=[
                FrozenRef.from_json(fr) for fr in d.get("FrozenRefs", [])
            ],
            base_meta=[FrozenRef.from_json(fr) for fr in d.get("BaseMeta", [])],
            proof_blocks={
                int(i): Block.from_json(b)
                for i, b in d.get("ProofBlocks", {}).items()
            },
            consensus_baseline=dict(d.get("ConsensusBaseline", {})),
        )
