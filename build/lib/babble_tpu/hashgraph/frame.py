"""Frame: a self-contained DAG section used for fast-sync
(reference: src/hashgraph/frame.go, docs/fastsync.rst:52-75).

Hash is the SHA-256 of the canonical encoding; it is pinned into block
headers, so it must be byte-stable across validators.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from .. import crypto
from ..utils.codec import canonical_dumps
from .event import Event
from .root import Root


@dataclass
class Frame:
    round: int = -1  # the round received
    roots: List[Root] = field(default_factory=list)  # [peer position] => Root
    events: List[Event] = field(default_factory=list)
    # frozen on first computation: a frame is immutable once built (it is
    # stored and pinned into block headers), and the canonical marshal of
    # every contained event is expensive enough to dominate block
    # construction if recomputed (new_block_from_frame + the store both
    # ask for the hash)
    _hash: bytes = field(default=b"", repr=False, compare=False)

    def to_canonical(self) -> dict:
        return {
            "Round": self.round,
            "Roots": [r.to_canonical() for r in self.roots],
            "Events": [e.to_canonical() for e in self.events],
        }

    def marshal(self) -> bytes:
        return canonical_dumps(self.to_canonical())

    def hash(self) -> bytes:
        if not self._hash:
            self._hash = crypto.sha256(self.marshal())
        return self._hash

    def to_json(self) -> dict:
        return {
            "Round": self.round,
            "Roots": [r.to_canonical() for r in self.roots],
            "Events": [e.to_json() for e in self.events],
        }

    @classmethod
    def from_json(cls, d: dict) -> "Frame":
        return cls(
            round=d["Round"],
            roots=[Root.from_canonical(r) for r in d["Roots"]],
            events=[Event.from_json(e) for e in d["Events"]],
        )
