"""Dummy chat client CLI — the app side of the socket proxy split
(reference: cmd/dummy/main.go + cmd/dummy/commands/root.go:41-66).

Reads lines from stdin and submits "<name>: <line>" as transactions;
committed blocks are printed as they arrive through the commit handler.

    python -m babble_tpu.dummy_cli --name Alice \
        --client-listen 127.0.0.1:1339 --proxy-connect 127.0.0.1:1338
"""

from __future__ import annotations

import argparse
import logging
import sys

from .proxy.socket_babble import DummySocketClient


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dummy", description="Chat demo client")
    p.add_argument("--name", default="node", help="Name to prefix messages with")
    p.add_argument("--client-listen", default="127.0.0.1:1339",
                   help="Listen IP:Port for this client (babble connects here)")
    p.add_argument("--proxy-connect", default="127.0.0.1:1338",
                   help="IP:Port of babble's proxy listener")
    p.add_argument("--log", default="info", help="Log level")
    args = p.parse_args(argv)

    logging.basicConfig(level=getattr(logging, args.log.upper(), logging.INFO))
    logger = logging.getLogger("dummy")

    client = DummySocketClient(
        node_addr=args.proxy_connect,
        bind_addr=args.client_listen,
        logger=logger,
    )

    # print committed chat messages as they arrive
    base_commit = client.state.commit_handler

    def commit_and_print(block):
        for tx in block.transactions():
            print(f"\n[block {block.index()}] {tx.decode(errors='replace')}")
        return base_commit(block)

    client.state.commit_handler = commit_and_print  # type: ignore[method-assign]

    print("Enter your text: ", end="", flush=True)
    for line in sys.stdin:
        text = line.strip()
        if text:
            client.submit_tx(f"{args.name}: {text}".encode())
        print("Enter your text: ", end="", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
