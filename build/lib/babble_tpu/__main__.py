"""`python -m babble_tpu` — the CLI entry point."""

import sys

from .cli import main

sys.exit(main())
