from .grid import DagGrid, GridUnsupported, grid_from_hashgraph, synthetic_grid, build_levels
from .engine import PassResults, run_passes, run_consensus_device
from . import kernels

__all__ = [
    "DagGrid",
    "GridUnsupported",
    "grid_from_hashgraph",
    "synthetic_grid",
    "build_levels",
    "PassResults",
    "run_passes",
    "run_consensus_device",
    "kernels",
]
