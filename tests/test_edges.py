"""Edge coverage: per-type sqlite store round-trips, TCP-backed multi-node
gossip, control timer, and peer selector
(reference: src/hashgraph/badger_store_test.go:151-691,
src/net/tcp_transport_test.go, src/node/* unit behavior)."""

import os
import time

import pytest

from babble_tpu.crypto import generate_key, pub_key_bytes
from babble_tpu.hashgraph import (
    Block,
    Event,
    Frame,
    InmemStore,
    RoundInfo,
    SQLiteStore,
    root_self_parent,
)
from babble_tpu.net import TCPTransport
from babble_tpu.node import Config, Node
from babble_tpu.node.control_timer import new_random_control_timer
from babble_tpu.node.peer_selector import RandomPeerSelector
from babble_tpu.peers import Peer, Peers
from babble_tpu.proxy import InmemDummyClient

from test_node import bombard_and_wait, check_gossip, run_nodes, shutdown_nodes


def make_participants(n):
    keys = [generate_key() for _ in range(n)]
    participants = Peers()
    for i, key in enumerate(keys):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        participants.add_peer(Peer(net_addr=f"127.0.0.1:{7700 + i}", pub_key_hex=pub_hex))
    return participants, keys


# ---------------------------------------------------------------------------
# sqlite store round-trips per type (reference: badger_store_test.go:151-691)
# ---------------------------------------------------------------------------


def test_sqlite_event_roundtrip(tmp_path):
    participants, keys = make_participants(3)
    store = SQLiteStore.load_or_create(participants, 100, os.path.join(tmp_path, "s.db"))
    peer = participants.to_peer_slice()[0]
    key = next(
        k for k in keys
        if "0x" + pub_key_bytes(k).hex().upper() == peer.pub_key_hex
    )
    ev = Event(
        transactions=[b"tx1", b"tx2"],
        block_signatures=None,
        parents=[root_self_parent(peer.id), ""],
        creator=pub_key_bytes(key),
        index=0,
    )
    ev.sign(key)
    store.set_event(ev)
    got = store.get_event(ev.hex())
    assert got.hex() == ev.hex()
    assert got.transactions() == [b"tx1", b"tx2"]
    assert got.verify()
    # fresh store over the same db file must see the event on disk
    store.close()
    reopened = SQLiteStore.load_or_create(participants, 100, os.path.join(tmp_path, "s.db"))
    assert reopened.need_bootstrap()
    assert [e.hex() for e in reopened.db_topological_events()] == [ev.hex()]
    reopened.close()


def test_sqlite_round_block_frame_roundtrip(tmp_path):
    participants, keys = make_participants(3)
    path = os.path.join(tmp_path, "s.db")
    store = SQLiteStore.load_or_create(participants, 100, path)

    from babble_tpu.hashgraph import Trilean

    ri = RoundInfo()
    ri.add_event("0xAB", witness=True)
    ri.set_fame("0xAB", True)
    store.set_round(7, ri)
    got = store.get_round(7)
    assert got.witnesses() == ["0xAB"]
    assert got.events["0xAB"].famous == Trilean.TRUE
    assert store.last_round() == 7

    block = Block(index=3, round_received=7, frame_hash=b"fh", transactions=[b"a"])
    sig = block.sign(keys[0])
    block.set_signature(sig)
    store.set_block(block)
    got_b = store.get_block(3)
    assert got_b.body.marshal() == block.body.marshal()
    assert got_b.signatures == block.signatures
    assert store.last_block_index() == 3

    frame = Frame(round=7, roots=[], events=[])
    store.set_frame(frame)
    assert store.get_frame(7).hash() == frame.hash()

    store.close()
    # blocks survive reopen (read-through to disk)
    reopened = SQLiteStore.load_or_create(participants, 100, path)
    assert reopened.get_block(3).body.marshal() == block.body.marshal()
    reopened.close()


# ---------------------------------------------------------------------------
# TCP-backed multi-node gossip (reference: node tests run inmem only; the
# demo runs TCP — this pins the full node loop onto real sockets in-process)
# ---------------------------------------------------------------------------


def test_tcp_backed_gossip_three_nodes():
    conf = Config(heartbeat_timeout=0.01, tcp_timeout=1.0, cache_size=1000,
                  sync_limit=300)
    keys = [generate_key() for _ in range(3)]
    # bind ephemeral ports first, then build the peer set from what the
    # OS assigned
    transports = [TCPTransport("127.0.0.1:0", timeout=1.0) for _ in range(3)]
    participants = Peers()
    peers_of = {}
    for key, trans in zip(keys, transports):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr=trans.local_addr(), pub_key_hex=pub_hex)
        participants.add_peer(peer)
        peers_of[pub_hex] = trans

    nodes, proxies = [], []
    for key in keys:
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        trans = peers_of[pub_hex]
        prox = InmemDummyClient()
        node = Node(
            conf, participants.by_pub_key[pub_hex].id, key, participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes.append(node)
        proxies.append(prox)

    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=60)
        check_gossip(nodes, upto=2)
    finally:
        shutdown_nodes(nodes)


# ---------------------------------------------------------------------------
# control timer + peer selector
# ---------------------------------------------------------------------------


def test_control_timer_ticks_and_stops():
    """One-shot randomized timer: fires once per reset (the node re-arms it
    after each gossip tick, reference: src/node/control_timer.go:42-65)."""
    timer = new_random_control_timer(0.01)
    timer.run()
    try:
        for _ in range(3):
            timer.tick_ch.get(timeout=1.0)
            timer.reset()
        timer.tick_ch.get(timeout=1.0)
        timer.stop()
        # stopped + never reset => silence
        time.sleep(0.05)
        while not timer.tick_ch.empty():
            timer.tick_ch.get_nowait()
        time.sleep(0.1)
        assert timer.tick_ch.empty(), "timer kept ticking after stop"
        timer.reset()
        timer.tick_ch.get(timeout=1.0)  # ticks again after reset
    finally:
        timer.shutdown()


def test_random_peer_selector_excludes_self_and_last():
    participants, _ = make_participants(4)
    me = participants.to_peer_slice()[0].net_addr
    sel = RandomPeerSelector(participants, me)
    seen = set()
    last = None
    for _ in range(100):
        peer = sel.next()
        assert peer.net_addr != me, "selector returned self"
        if last is not None:
            assert peer.net_addr != last, "selector repeated last contact"
        sel.update_last(peer.net_addr)
        last = peer.net_addr
        seen.add(peer.net_addr)
    assert len(seen) == 3, "selector never visited some peers"
