"""Adversarial/robustness tests for the fast-sync and transport hardening:

- oversized frames from unauthenticated peers are rejected without taking
  the listener down (tcp_transport max_frame_size);
- a forged app snapshot from a malicious donor cannot leave the joiner's
  app on foreign state (the anchor block's >1/3-signed state hash gates
  the restore, node.fast_forward);
- chained fast-sync: a joiner can fast-forward FROM a donor that itself
  fast-synced (the donor's section forwards FrozenRefs for other-parents
  it only knows as refs — reference scenario: src/node/node_test.go:583
  extended with a forced second-generation donor).
"""

import pytest
import socket
import struct
import threading
import time

from babble_tpu.hashgraph import InmemStore
from babble_tpu.net import (
    InmemTransport,
    SyncRequest,
    SyncResponse,
    TCPTransport,
)
from babble_tpu.node import Node
from babble_tpu.node.state import NodeState
from babble_tpu.proxy import InmemDummyClient

from test_fastsync import (
    build_cluster,
    first_available_block,
    make_config,
)
from test_node import (
    bombard_and_wait,
    check_gossip,
    run_nodes,
    shutdown_nodes,
)


def test_tcp_oversized_frame_rejected():
    """A frame larger than max_frame_size must be refused before buffering
    and must not take down the accept loop (ADVICE r1: unbounded frames
    from unauthenticated peers)."""
    server = TCPTransport("127.0.0.1:0", max_frame_size=4096)
    try:
        host, port = server.local_addr().split(":")

        # responder for the one legitimate RPC sent below
        def respond():
            rpc = server.consumer().get(timeout=5)
            rpc.respond(SyncResponse(from_id=1, sync_limit=True,
                                     events=[], known={}))

        t = threading.Thread(target=respond, daemon=True)
        t.start()

        # oversized frame: header claims 1 MiB body. The server may reset
        # the connection at any point after reading the header, so the
        # body send races the close — both outcomes are the rejection
        # under test.
        bad = socket.create_connection((host, int(port)), timeout=2)
        bad.settimeout(2)
        try:
            bad.sendall(struct.pack(">BI", 0, 1 << 20))
            bad.sendall(b"x" * 65536)  # partial body; server should hang up
            data = bad.recv(1)
            assert data == b"", "server should close the connection"
        except (ConnectionError, socket.timeout, OSError):
            pass
        finally:
            bad.close()

        # the listener must still serve normal requests
        client = TCPTransport("127.0.0.1:0", max_frame_size=4096)
        try:
            resp = client.sync(
                server.local_addr(), SyncRequest(from_id=0, known={})
            )
            assert resp.sync_limit is True
        finally:
            client.close()
    finally:
        server.close()


def read_error_then_close(sock, what):
    """The server's contract on bad input: ONE JSON error line (so the
    peer can tell 'refused' from 'connection recycled'), then close. A
    timeout means it silently buffered/kept the connection — the exact
    regression this helper exists to catch."""
    import json

    buf = b""
    try:
        while b"\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                break
            buf += chunk
    except TimeoutError:
        raise AssertionError(f"server kept the {what} connection open") from None
    except ConnectionError:
        pass
    if buf:
        line, _, rest = buf.partition(b"\n")
        resp = json.loads(line)
        assert resp.get("error"), f"expected an error reply, got {resp!r}"
        assert rest == b""
    # after the (optional) error line the connection must be CLOSED
    try:
        tail = sock.recv(1)
    except TimeoutError:
        raise AssertionError(f"server kept the {what} connection open") from None
    except ConnectionError:
        tail = b""
    assert tail == b"", "server should close the connection"
    return buf


def test_jsonrpc_oversized_line_rejected():
    """A request line beyond max_line must be refused without buffering:
    the server answers with a JSON-RPC error (the line's id is unknowable,
    so id null) and closes, and keeps serving other clients."""
    from babble_tpu.proxy.jsonrpc import (
        JSONRPCClient, JSONRPCError, JSONRPCServer,
    )

    server = JSONRPCServer("127.0.0.1:0", max_line=4096)
    server.register("Echo.Ping", lambda x: x)
    server.start()
    try:
        host, port = server.addr.split(":")
        bad = socket.create_connection((host, int(port)), timeout=2)
        bad.settimeout(2)
        try:
            bad.sendall(b"x" * 8192)  # no newline, twice the limit
            reply = read_error_then_close(bad, "oversized")
            assert b"exceeds" in reply
        finally:
            bad.close()

        # valid-JSON-but-non-object lines get an error + hang-up too
        bad2 = socket.create_connection((host, int(port)), timeout=2)
        bad2.settimeout(2)
        try:
            bad2.sendall(b"5\n")
            read_error_then_close(bad2, "malformed")
        finally:
            bad2.close()

        client = JSONRPCClient(server.addr, max_line=4096)
        try:
            assert client.call("Echo.Ping", "ok") == "ok"
            # a client-side oversized request fails fast WITHOUT being
            # sent (no wasted transfer, no ambiguous half-executed call)
            try:
                client.call("Echo.Ping", "y" * 8192)
                raise AssertionError("oversized request was not refused")
            except JSONRPCError as e:
                assert "too large" in str(e)
            # and the connection remains usable
            assert client.call("Echo.Ping", "ok2") == "ok2"
        finally:
            client.close()
    finally:
        server.close()


def test_malicious_peer_garbage_rejected():
    """A non-validator peer pushing tampered wire events (junk
    signatures, unknown creators) must be rejected without disturbing the
    cluster, and pulls with absurd known-maps must answer, not crash."""
    from babble_tpu.hashgraph.event import WireBody, WireEvent
    from babble_tpu.net import EagerSyncRequest

    from test_node import init_nodes

    nodes, proxies = init_nodes(4)
    attacker = InmemTransport("127.0.0.1:6666", timeout=5.0)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=1)

        victim = nodes[0]
        attacker.connect(victim.local_addr, victim.trans)

        junk = WireEvent(
            body=WireBody(
                transactions=[b"evil"], block_signatures=[],
                self_parent_index=0, other_parent_creator_id=0,
                other_parent_index=0, creator_id=123456789, index=1,
            ),
            signature="deadbeef|deadbeef",
        )
        # rejection surfaces either as success=False or as an error reply
        # (raised client-side as TransportError) — both are refusals
        from babble_tpu.net import TransportError

        try:
            resp = attacker.eager_sync(
                victim.local_addr,
                EagerSyncRequest(from_id=123456789, events=[junk]),
            )
            assert resp.success is False
        except TransportError:
            pass

        # bogus pull: unknown participant ids in the known-map
        try:
            resp = attacker.sync(
                victim.local_addr,
                SyncRequest(from_id=123456789, known={111: 5, 222: -7}),
            )
            assert resp is not None  # answered, not crashed
        except TransportError:
            pass

        # the cluster keeps committing, byte-identically
        target = max(n.core.get_last_block_index() for n in nodes) + 2
        bombard_and_wait(nodes, proxies, target_block=target)
        check_gossip(nodes, upto=target)
    finally:
        attacker.close()
        shutdown_nodes(nodes)


class ForgingDummyClient(InmemDummyClient):
    """Dummy app whose snapshots can be switched to forgeries — the
    malicious-donor side of the fast-forward handshake."""

    def __init__(self):
        super().__init__()
        self.forge = False

    def get_snapshot(self, block_index: int) -> bytes:
        if self.forge:
            return b'{"forged": true}'
        return super().get_snapshot(block_index)


@pytest.mark.slow
def test_fast_forward_rejects_forged_snapshot():
    """While every reachable donor forges snapshots, a joiner must refuse
    to leave CatchingUp (the restored state hash cannot reproduce the
    anchor block's signed state hash); once a donor turns honest the
    joiner must catch up with byte-identical blocks."""
    conf = make_config()

    nodes, proxies, keys, peer_list, participants, transports = build_cluster(
        4, conf, proxy_factory=lambda i: ForgingDummyClient()
    )
    node4, prox4 = nodes[3], proxies[3]
    nodes3, proxies3 = nodes[:3], proxies[:3]
    try:
        run_nodes(nodes3)
        target = 3
        while True:
            bombard_and_wait(nodes3, proxies3, target_block=target,
                             timeout_s=180)
            total_events = sum(
                i + 1 for i in nodes3[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            target += 1

        # all donors forge
        for p in proxies3:
            p.forge = True
        node4.run_async(True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            assert node4.core.get_last_block_index() < 0, (
                "joiner committed blocks from a forged snapshot"
            )
            time.sleep(0.25)
        assert node4.get_state() == NodeState.CATCHING_UP

        # donors turn honest; the joiner must now catch up for real
        for p in proxies3:
            p.forge = False
        target = max(n.core.get_last_block_index() for n in nodes3) + 2
        bombard_and_wait(nodes, proxies, target_block=target, timeout_s=180)
        upto = min(n.core.get_last_block_index() for n in nodes)
        start = first_available_block(node4, upto)
        check_gossip(nodes, from_block=start, upto=upto)
    finally:
        shutdown_nodes(nodes)


@pytest.mark.slow
def test_chained_fast_sync_donor():
    """Second-generation fast-sync: node D joins via fast-forward; later
    node C rejoins with connectivity ONLY to D, so D — itself a product of
    fast-sync — must serve the anchor + section (forwarding FrozenRefs for
    other-parents it never held as events; ADVICE r1 item 1)."""
    conf = make_config()
    nodes, proxies, keys, peer_list, participants, transports = build_cluster(
        4, conf
    )
    try:
        # phase 1: run 0-2 past the sync limit, then start 3 -> fast-sync
        nodes3, proxies3 = nodes[:3], proxies[:3]
        run_nodes(nodes3)
        target = 3
        while True:
            bombard_and_wait(nodes3, proxies3, target_block=target,
                             timeout_s=180)
            total_events = sum(
                i + 1 for i in nodes3[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            target += 1
        nodes[3].run_async(True)
        target = max(n.core.get_last_block_index() for n in nodes[:3]) + 2
        bombard_and_wait(nodes, proxies, target_block=target, timeout_s=240)
        upto3 = min(n.core.get_last_block_index() for n in nodes)
        assert first_available_block(nodes[3], upto3) > 0, (
            "node 3 should have joined mid-history (fast-sync), not replayed"
        )

        # phase 2: kill node 2, run the rest past the sync limit again
        victim_addr = peer_list[2].net_addr
        nodes[2].shutdown()
        transports[2].disconnect_all()
        for t in (transports[0], transports[1], transports[3]):
            t.disconnect(victim_addr)
        alive = [nodes[0], nodes[1], nodes[3]]
        alive_prox = [proxies[0], proxies[1], proxies[3]]
        goal = max(n.core.get_last_block_index() for n in alive) + 3
        while True:
            bombard_and_wait(alive, alive_prox, target_block=goal,
                             timeout_s=240)
            total_events = sum(
                i + 1 for i in nodes[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            goal += 1

        # the donor (node 3) must hold an anchor block — fast-forward
        # serves from stored state, so it needs >n/3 signatures collected
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if nodes[3].core.hg.anchor_block is not None:
                break
            bombard_and_wait(
                alive, alive_prox,
                target_block=max(
                    n.core.get_last_block_index() for n in alive
                ) + 1, timeout_s=120,
            )
        assert nodes[3].core.hg.anchor_block is not None

        # phase 3: halt nodes 0 and 1 so the scenario is deterministic —
        # the ONLY live peer is node 3, itself a product of fast-sync.
        # Fast-forward needs no live consensus on the donor: the anchor,
        # frame and section come from its stores.
        donor_last = nodes[3].core.get_last_block_index()
        for i in (0, 1):
            nodes[i].shutdown()
            # unplug them from the mesh too: a dial to a dead-but-registered
            # inmem transport burns the full RPC timeout, and the donor
            # gossiping into that black hole piles up timed-out threads
            transports[i].disconnect_all()
            transports[3].disconnect(peer_list[i].net_addr)

        # recycle node 2 connected ONLY to node 3
        trans = InmemTransport(victim_addr, timeout=5.0)
        trans.connect(transports[3].local_addr(), transports[3])
        transports[3].connect(victim_addr, trans)
        transports[2] = trans
        prox = InmemDummyClient()
        store = InmemStore(participants, conf.cache_size)
        import copy as _copy

        node = Node(
            _copy.copy(conf), peer_list[2].id, keys[2], participants, store,
            trans, prox,
        )
        node.init()
        nodes[2] = node
        proxies[2] = prox
        node.run_async(True)

        # the joiner must fast-forward THROUGH node 3 alone, reaching at
        # least the donor's anchor region
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if node.core.get_last_block_index() >= 0:
                break
            time.sleep(0.25)
        joiner_last = node.core.get_last_block_index()
        assert joiner_last >= 0, (
            "joiner failed to fast-sync from a donor that itself fast-synced"
        )
        assert first_available_block(node, joiner_last) > 0, (
            "joiner replayed from genesis instead of fast-syncing"
        )

        # every block the joiner holds must be byte-identical to the
        # donor's copy
        upto = min(joiner_last, donor_last)
        start = first_available_block(node, upto)
        for i in range(start, upto + 1):
            assert (
                node.get_block(i).body.marshal()
                == nodes[3].get_block(i).body.marshal()
            ), f"block {i} diverged between joiner and donor"
    finally:
        shutdown_nodes(nodes)
