"""Adversarial consensus topologies: funky (out-of-order fame decisions +
coin rounds), sparse (participants skipping rounds), and forks
(reference: src/hashgraph/hashgraph_test.go:351, 2030-2260, 2482-2600).
"""

import pytest

from babble_tpu.hashgraph import Event, root_self_parent

from dsl import (
    init_funky_hashgraph,
    init_hashgraph_nodes,
    init_sparse_hashgraph,
    create_hashgraph,
)


def test_funky_fame():
    """Rounds 1 and 2 decide BEFORE round 0; pending queue order preserved
    (reference: TestFunkyHashgraphFame, hashgraph_test.go:2081-2152)."""
    h, index, _ = init_funky_hashgraph(full=False)
    h.divide_rounds()
    h.decide_fame()

    assert h.store.last_round() == 4

    expected = [(0, False), (1, True), (2, True), (3, False), (4, False)]
    got = [(pr.index, pr.decided) for pr in h.pending_rounds]
    assert got == expected

    # a decided round must never be processed before all previous rounds
    h.decide_round_received()
    h.process_decided_rounds()
    got = [(pr.index, pr.decided) for pr in h.pending_rounds]
    assert got == expected


def test_funky_blocks_and_coin_round():
    """The full funky graph decides rounds 0-3 and produces 3 blocks with
    the reference's exact tx counts; fame voting must have reached the
    coin-round branch (reference: TestFunkyHashgraphBlocks,
    hashgraph_test.go:2154-2225)."""
    h, index, _ = init_funky_hashgraph(full=True)
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    h.process_decided_rounds()

    assert h.store.last_round() == 5
    assert [(pr.index, pr.decided) for pr in h.pending_rounds] == [
        (4, False),
        (5, False),
    ]
    expected_tx_counts = {0: 6, 1: 7, 2: 7}
    for bi, want in expected_tx_counts.items():
        assert len(h.store.get_block(bi).transactions()) == want

    # the adversarial point of this topology: fame voting ran long enough
    # to hit a coin round (diff % n == 0)
    assert h.coin_rounds > 0, "funky fixture no longer reaches the coin branch"


def test_sparse_frames():
    """Sparse rounds still produce consistent blocks whose pinned frame
    hashes match rebuilt frames (reference: TestSparseHashgraphFrames)."""
    h, index, _ = init_sparse_hashgraph()
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    h.process_decided_rounds()

    assert h.store.last_block_index() >= 2
    for bi in range(3):
        block = h.store.get_block(bi)
        frame = h.get_frame(block.round_received())
        assert block.frame_hash() == frame.hash()


def test_fork_rejected():
    """Two events by one creator with the same self-parent = a fork; the
    second insert must be rejected (reference: TestFork,
    hashgraph_test.go:351-398)."""
    nodes, index, ordered, participants = init_hashgraph_nodes(3)
    for i, peer in enumerate(participants.to_peer_slice()):
        ev = Event(parents=[root_self_parent(peer.id), ""],
                   creator=nodes[i].pub, index=0)
        nodes[i].sign_and_add_event(ev, f"e{i}", index, ordered)
    h = create_hashgraph(ordered, participants)

    # legitimate extension
    good = Event(parents=[index["e0"], index["e1"]], creator=nodes[0].pub, index=1)
    good.sign(nodes[0].key)
    h.insert_event(good, True)

    # fork: same creator, same self-parent as `good`
    fork = Event(parents=[index["e0"], index["e2"]], creator=nodes[0].pub, index=1)
    fork.sign(nodes[0].key)
    with pytest.raises(ValueError):
        h.insert_event(fork, True)
