"""Socket proxy tests (reference: src/proxy/socket_proxy_test.go:56,99) —
both ends of the TCP JSON-RPC split exercised against the dummy State."""

import time

import pytest

from babble_tpu.crypto import simple_hash_from_two_hashes
from babble_tpu.hashgraph import Block
from babble_tpu.proxy import (
    DummySocketClient,
    JSONRPCError,
    SocketAppProxy,
    SocketBabbleProxy,
    State,
)


def make_pair():
    """Wire a node-side SocketAppProxy to an app-side SocketBabbleProxy.

    Both listen on ephemeral ports; the app dials the node's submit server
    and the node dials the app's state server.
    """
    state = State()
    app = SocketBabbleProxy("0:0", "127.0.0.1:0", state)  # node addr set later
    node = SocketAppProxy(app.bind_addr, "127.0.0.1:0")
    app.client.addr = node.bind_addr
    return node, app, state


def test_submit_tx_reaches_node_submit_ch():
    node, app, _ = make_pair()
    try:
        app.submit_tx(b"the test transaction")
        got = node.submit_ch().get(timeout=3)
        assert got == b"the test transaction"
    finally:
        node.close()
        app.close()


def test_commit_block_roundtrip():
    node, app, state = make_pair()
    try:
        block = Block(index=0, round_received=1, transactions=[b"tx 1", b"tx 2"])
        returned = node.commit_block(block)
        expected = simple_hash_from_two_hashes(b"", b"tx 1")
        expected = simple_hash_from_two_hashes(expected, b"tx 2")
        assert returned == expected
        assert state.get_committed_transactions() == [b"tx 1", b"tx 2"]
    finally:
        node.close()
        app.close()


def test_snapshot_and_restore():
    node, app, state = make_pair()
    try:
        block = Block(index=5, round_received=1, transactions=[b"a"])
        h = node.commit_block(block)
        assert node.get_snapshot(5) == h
        with pytest.raises(JSONRPCError):
            node.get_snapshot(99)
        restored = node.restore(b"\x01\x02")
        assert restored == b"\x01\x02"
        assert state.state_hash == b"\x01\x02"
    finally:
        node.close()
        app.close()


def test_dummy_socket_client():
    node = SocketAppProxy("127.0.0.1:1", "127.0.0.1:0")
    try:
        dummy = DummySocketClient(node.bind_addr, "127.0.0.1:0")
        node.client.addr = dummy.proxy.bind_addr
        try:
            dummy.submit_tx(b"hello")
            assert node.submit_ch().get(timeout=3) == b"hello"
            node.commit_block(Block(index=0, round_received=1, transactions=[b"hello"]))
            time.sleep(0.05)
            assert dummy.state.get_committed_transactions() == [b"hello"]
        finally:
            dummy.close()
    finally:
        node.close()
