"""Device-time ledger tests (ISSUE 19): contract-surface coverage of
ENTRY_INFO, seam cell/compile/retrace accounting over real jit
callables, the sim-clock determinism contract (byte-identical
fingerprints), the knob-flip recompile budget, the seeded-retrace
fixture that must trip the steady-state budget gate, the unified
host+device Chrome-trace timeline, and the bench-trend regression
attribution helper."""

import glob
import json
import os
import re
import sys

import jax
import jax.numpy as jnp

from babble_tpu.obs import (
    ENTRY_INFO,
    Observability,
    SLOEngine,
    build_timeline,
    ledger_call,
    retrace_baseline,
    retrace_delta,
)
from babble_tpu.common import SystemClock
from babble_tpu.sim import SimClock, run_one

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))


# ----------------------------------------------------------------------
# contract-surface coverage
# ----------------------------------------------------------------------

def test_entry_info_covers_kernel_contract_surface():
    """Every `# kernel-contract:` entry point in tpu/ has a ledger seam
    or a covered_by pointer — and nothing else does. A new staged kernel
    cannot land without joining the ledger's attribution map."""
    marked = set()
    for path in glob.glob(os.path.join(ROOT, "babble_tpu", "tpu", "*.py")):
        with open(path) as f:
            for line in f:
                m = re.search(r"#\s*kernel-contract:\s*(\w+)", line)
                if m:
                    marked.add(m.group(1))
    assert marked == set(ENTRY_INFO), (
        f"missing from ENTRY_INFO: {sorted(marked - set(ENTRY_INFO))}; "
        f"stale in ENTRY_INFO: {sorted(set(ENTRY_INFO) - marked)}"
    )
    # covered_by pointers must reference real seam entries
    for entry, (_rung, _pass, covered_by) in ENTRY_INFO.items():
        if covered_by is not None:
            assert covered_by in ENTRY_INFO, (entry, covered_by)
            assert ENTRY_INFO[covered_by][2] is None, (
                f"{entry} covered by {covered_by}, which is itself covered"
            )


# ----------------------------------------------------------------------
# seam accounting
# ----------------------------------------------------------------------

def test_seam_records_cells_compiles_and_metrics():
    obs = Observability()
    led = obs.devledger
    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.arange(8, dtype=jnp.int32)
    with led.activate("oneshot"):
        ledger_call("consensus_pipeline", f, x)
        ledger_call("consensus_pipeline", f, x)
    snap = led.snapshot()
    cells = snap["cells"]
    # first call compiled, second ran from cache
    assert cells["oneshot/pipeline/wide/compile"][0] == 1
    assert cells["oneshot/pipeline/wide/run"][0] == 1
    est = snap["entries"]["consensus_pipeline"]
    assert est["calls"] == 2
    assert est["compiles"] == 1
    assert est["retraces"] == 0
    assert est["bytes_in"] == 2 * 8 * 4
    # shares sum to 1 over the recorded cells
    assert abs(sum(snap["shares"].values()) - 1.0) < 1e-6
    # the typed metric surface materialized
    assert obs.registry.get("babble_kernel_pass_seconds") is not None
    c = obs.registry.get("babble_kernel_compiles_total")
    assert c.value(entry="consensus_pipeline") == 1.0


def test_uninstrumented_passthrough_without_activation():
    """ledger_call outside any activation is a pure passthrough — deep
    tpu/ call sites never need an obs handle to stay callable."""
    f = jax.jit(lambda x: x - 3)
    out = ledger_call("_step_full", f, jnp.int32(7))
    assert int(out) == 4


def test_lifecycle_component_cells():
    obs = Observability()
    led = obs.devledger
    led.component("mesh_queued", "stage", 0.25, layout="packed")
    led.component("mesh_queued", "fetch", 0.5, layout="packed")
    cells = led.snapshot()["cells"]
    assert cells["mesh_queued/dispatch/packed/stage"] == [1, 0.25]
    assert cells["mesh_queued/dispatch/packed/fetch"] == [1, 0.5]


# ----------------------------------------------------------------------
# determinism: the sim clock policy
# ----------------------------------------------------------------------

def _seamed_run(obs):
    led = obs.devledger
    f = jax.jit(lambda x: x + 1)
    with led.activate("oneshot"):
        for _ in range(3):
            ledger_call("consensus_pipeline", f, jnp.arange(4))
    led.component("oneshot", "integrate", 0.0)
    return led


def test_sim_clock_records_zero_and_identical_fingerprints():
    """Under any non-system clock every duration is identically 0.0 —
    the ledger never reads a virtual clock (SimClock is serve-thread
    only) and same-seed snapshots stay byte-identical."""
    a = _seamed_run(Observability(clock=SimClock()))
    b = _seamed_run(Observability(clock=SimClock()))
    snap = a.snapshot()
    assert snap["total_seconds"] == 0.0
    assert all(secs == 0.0 for _n, secs in snap["cells"].values())
    assert a.fingerprint() == b.fingerprint()
    # the real clock records nonzero time for the same run, under the
    # same cell names
    real = _seamed_run(Observability(clock=SystemClock()))
    assert set(real.snapshot()["cells"]) == set(snap["cells"])
    assert real.snapshot()["total_seconds"] > 0.0


def test_sim_cluster_ledger_fingerprint_deterministic():
    """ledger_fingerprint joins the SimCluster determinism contract:
    same seed+plan twice => byte-identical ledgers on every node."""
    a = run_one(5, plan="clean", n=4, until=None, target_block=2)
    b = run_one(5, plan="clean", n=4, until=None, target_block=2)
    assert a["ok"] and b["ok"]
    assert "ledger_fingerprint" in a
    assert a["ledger_fingerprint"] == b["ledger_fingerprint"]


# ----------------------------------------------------------------------
# knob-flip and retrace budgets
# ----------------------------------------------------------------------

def test_knob_flip_recompiles_without_retraces():
    """Flipping packed_voting mid-session changes the layout half of the
    seam signature: exactly one fresh compile per layout, zero silent
    retraces — the dispatch-time layout resolution (tpu/packed.py)
    exists to keep it that way."""
    obs = Observability()
    led = obs.devledger
    f = jax.jit(lambda x: jnp.sum(x))
    x = jnp.arange(16)
    for layout in ("wide", "packed", "wide", "packed"):
        with led.activate("sharded", layout=layout):
            ledger_call("local_fame", f, x)
    est = led.entry_stats("local_fame")
    assert est["compiles"] == 1  # one XLA executable serves both layouts
    assert est["retraces"] == 0
    cells = led.snapshot()["cells"]
    assert cells["sharded/fame/wide/compile"][0] == 1
    assert cells["sharded/fame/wide/run"][0] == 1
    assert cells["sharded/fame/packed/run"][0] == 2


def test_seeded_retrace_fixture_trips_budget_gate():
    """A fresh jit wrapper per call on an already-seen signature is the
    silent-retrace pathology: the ledger must count it, retrace_delta
    must name the entry, and the SLO-style budget gate must breach."""
    obs = Observability()
    led = obs.devledger

    def fresh_wrapper():
        return jax.jit(lambda x: x * 3)

    with led.activate("incremental"):
        ledger_call("_step_full", fresh_wrapper(), jnp.arange(4))
    base = retrace_baseline(obs)
    with led.activate("incremental"):
        for _ in range(2):
            ledger_call("_step_full", fresh_wrapper(), jnp.arange(4))
    delta = retrace_delta(obs, base)
    assert delta == {"_step_full": 2.0}
    # the gate a queued-mesh bench runs under --slo (bench_dispatch.py)
    obs.gauge(
        "babble_bench_retrace_delta",
        "Steady-state kernel retraces past the warmup baseline "
        "(budget: zero)",
    ).set(float(sum(delta.values())))
    obs.flightrec.record("dispatch.enqueue", events=4, depth=1)
    slo = SLOEngine(obs)
    slo.objective(
        "retrace_budget",
        series="babble_bench_retrace_delta",
        kind="below", threshold=1.0,
        description="steady-state kernel retraces past warmup stay at "
                    "zero",
    )
    slo.evaluate()
    assert slo.breached()
    # the flight ring the breach handler dumps is serializable and
    # carries the dispatch lifecycle context
    ring = json.dumps(obs.flightrec.to_json(), sort_keys=True)
    assert "dispatch.enqueue" in ring


# ----------------------------------------------------------------------
# unified timeline
# ----------------------------------------------------------------------

def test_timeline_is_valid_chrome_trace():
    obs = Observability()
    led = obs.devledger
    with obs.tracer.span("serve"):
        with led.activate("frontier"):
            ledger_call(
                "frontier_pipeline", jax.jit(lambda x: x + 1), jnp.arange(4)
            )
    obs.flightrec.record("dispatch.enqueue", events=4, depth=1)
    obs.flightrec.record("dispatch.integrate", blocked=0.01, depth=0)
    doc = build_timeline(obs)
    json.loads(json.dumps(doc))  # round-trips as JSON
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    for ev in evs:
        assert {"ph", "pid", "name"} <= set(ev), ev
        if ev["ph"] in ("X", "i", "C"):
            assert "ts" in ev, ev
        if ev["ph"] == "X":
            assert "dur" in ev, ev
    # host lane, device pass lane, and queue lane all present
    assert any(e["ph"] == "X" and e["name"] == "serve" for e in evs)
    device = [
        e for e in evs
        if e["ph"] == "X" and e["name"] == "frontier_pipeline[wide]"
    ]
    assert device and device[0]["args"]["compiles"] >= 1
    lanes = {
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "device:frontier/pipeline" in lanes
    assert any(e["ph"] == "i" for e in evs)  # dispatch instants
    assert any(
        e["ph"] == "C" and e["name"] == "queue_depth" for e in evs
    )


def test_service_serves_timeline_and_ledger_stats():
    """GET /debug/timeline returns the merged Chrome-trace document over
    a live node, and /stats carries the ledger adapter keys once device
    passes have been ledgered."""
    import urllib.request

    from babble_tpu.service import Service

    from test_node import init_nodes, run_nodes, shutdown_nodes

    nodes, _proxies = init_nodes(2)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"
        # ledger a pass directly — the endpoint contract is independent
        # of whether this node's workload reached a device rung
        led = nodes[0].obs.devledger
        with led.activate("oneshot"):
            ledger_call(
                "consensus_pipeline", jax.jit(lambda x: x + 1),
                jnp.arange(4),
            )
        led.component("oneshot", "integrate", 0.001)
        with urllib.request.urlopen(base + "/debug/timeline", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["displayTimeUnit"] == "ms"
        assert any(
            e["ph"] == "M" and e["args"].get("name")
            == "device:oneshot/pipeline"
            for e in doc["traceEvents"]
        )
        with urllib.request.urlopen(base + "/stats", timeout=5) as r:
            stats = json.loads(r.read())
        assert "ledger_ms_oneshot_pipeline" in stats
        assert stats["kernel_compiles"] == "1"
        assert stats["kernel_retraces"] == "0"
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)


# ----------------------------------------------------------------------
# trend attribution
# ----------------------------------------------------------------------

def _artifact(value, shares):
    headline = {
        "value": value, "unit": "ms/call",
        "ledger": {"shares": shares},
    }
    return {"rc": 0, "ok": True, "tail": "noise\n" + json.dumps(headline)}


def test_trend_attribution_names_moved_pass():
    """A synthetic 20% regression whose extra milliseconds sit in the
    queued rung's run phase must be attributed to exactly that (rung,
    pass) by the bench_trend helper."""
    import bench_trend

    prior = _artifact(50.0, {
        "mesh_queued/walk/wide": 0.50,
        "mesh_queued/fame/wide": 0.30,
        "mesh_queued/rounds/wide": 0.20,
    })
    latest = _artifact(60.0, {  # 20% worse, walk's share ballooned
        "mesh_queued/walk/wide": 0.65,
        "mesh_queued/fame/wide": 0.22,
        "mesh_queued/rounds/wide": 0.13,
    })
    attr = bench_trend.attribute_regression(latest, prior)
    assert attr is not None
    key, delta, latest_share, prior_share = attr
    assert key == "mesh_queued/walk/wide"
    assert delta > 0.10
    assert latest_share == 0.65 and prior_share == 0.50
    # rounds that predate the ledger degrade to None, not a crash
    assert bench_trend.attribute_regression(
        latest, {"rc": 0, "tail": json.dumps({"value": 1.0})}
    ) is None
    assert bench_trend.ledger_shares(prior) == {
        "mesh_queued/walk/wide": 0.50,
        "mesh_queued/fame/wide": 0.30,
        "mesh_queued/rounds/wide": 0.20,
    }
