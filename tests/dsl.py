"""The `play` DSL: scripted DAG construction for consensus tests.

Port of the reference's load-bearing test harness (reference:
src/hashgraph/hashgraph_test.go:69-157): events are described as
{to, index, selfParent, otherParent, name, txPayload, sigPayload} tuples
against a name->hash index, then inserted into a fresh hashgraph.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from babble_tpu import crypto
from babble_tpu.common import hash32
from babble_tpu.hashgraph import (
    BlockSignature,
    Event,
    Hashgraph,
    InmemStore,
    root_self_parent,
)
from babble_tpu.peers import Peer, Peers

CACHE_SIZE = 100


@dataclass
class Play:
    to: int
    index: int
    self_parent: str
    other_parent: str
    name: str
    tx_payload: Optional[List[bytes]] = None
    sig_payload: Optional[List[BlockSignature]] = None


class TestNode:
    def __init__(self, key):
        self.key = key
        self.pub = crypto.pub_key_bytes(key)
        self.id = hash32(self.pub)
        self.pub_hex = "0x" + self.pub.hex().upper()
        self.events: List[Event] = []

    def sign_and_add_event(self, event: Event, name: str, index: Dict[str, str], ordered):
        event.sign(self.key)
        self.events.append(event)
        index[name] = event.hex()
        ordered.append(event)


def init_hashgraph_nodes(n: int) -> Tuple[List[TestNode], Dict[str, str], List[Event], Peers]:
    index: Dict[str, str] = {}
    ordered: List[Event] = []
    keys = {}
    participants = Peers()
    for _ in range(n):
        key = crypto.generate_key()
        pub_hex = "0x" + crypto.pub_key_bytes(key).hex().upper()
        participants.add_peer(Peer(pub_key_hex=pub_hex, net_addr=""))
        keys[pub_hex] = key

    nodes = [TestNode(keys[p.pub_key_hex]) for p in participants.to_peer_slice()]
    return nodes, index, ordered, participants


def play_events(plays: List[Play], nodes, index, ordered) -> None:
    for p in plays:
        e = Event(
            transactions=p.tx_payload,
            block_signatures=p.sig_payload,
            parents=[index.get(p.self_parent, ""), index.get(p.other_parent, "")],
            creator=nodes[p.to].pub,
            index=p.index,
        )
        nodes[p.to].sign_and_add_event(e, p.name, index, ordered)


def create_hashgraph(ordered, participants, store=None) -> Hashgraph:
    store = store or InmemStore(participants, CACHE_SIZE)
    h = Hashgraph(participants, store)
    for ev in ordered:
        h.insert_event(ev, True)
    return h


def init_hashgraph_full(plays: List[Play], n: int, store_factory=None):
    nodes, index, ordered, participants = init_hashgraph_nodes(n)

    # first events attach to each sorted peer's root
    for i, peer in enumerate(participants.to_peer_slice()):
        ev = Event(parents=[root_self_parent(peer.id), ""], creator=nodes[i].pub, index=0)
        nodes[i].sign_and_add_event(ev, f"e{i}", index, ordered)

    play_events(plays, nodes, index, ordered)

    store = store_factory(participants) if store_factory else None
    h = create_hashgraph(ordered, participants, store)
    return h, index, ordered


# ---------------------------------------------------------------------------
# named topologies (reference: src/hashgraph/hashgraph_test.go)
# ---------------------------------------------------------------------------

def init_simple_hashgraph(store_factory=None):
    """reference: hashgraph_test.go:161-201.

    |  e12  |
    |   | \\ |
    |  s10 e20
    |   | / |
    |   /   |
    | / |   |
    s00 |  s20
    |   |   |
    e01 |   |
    | \\ |   |
    e0  e1  e2
    0   1   2
    """
    plays = [
        Play(0, 1, "e0", "e1", "e01"),
        Play(2, 1, "e2", "", "s20"),
        Play(1, 1, "e1", "", "s10"),
        Play(0, 2, "e01", "", "s00"),
        Play(2, 2, "s20", "s00", "e20"),
        Play(1, 2, "s10", "e20", "e12"),
    ]
    return init_hashgraph_full(plays, 3, store_factory)


def init_round_hashgraph(store_factory=None):
    """reference: hashgraph_test.go:400-434.

    |  s11  |
    |   |   |
    |   f1  |
    |  /|   |
    | / s10 |
    |/  |   |
    e02 |   |
    | \\ |   |
    |   \\   |
    |   | \\ |
    s00 |  e21
    |   | / |
    |  e10  s20
    | / |   |
    e0  e1  e2
    """
    plays = [
        Play(1, 1, "e1", "e0", "e10"),
        Play(2, 1, "e2", "", "s20"),
        Play(0, 1, "e0", "", "s00"),
        Play(2, 2, "s20", "e10", "e21"),
        Play(0, 2, "s00", "e21", "e02"),
        Play(1, 2, "e10", "", "s10"),
        Play(1, 3, "s10", "e02", "f1"),
        Play(1, 4, "f1", "", "s11", [b"abc"]),
    ]
    return init_hashgraph_full(plays, 3, store_factory)


def init_consensus_hashgraph(store_factory=None):
    """reference: hashgraph_test.go:1170-1205 — runs to round 4, decides
    rounds 0-2, commits 2 blocks."""
    plays = [
        Play(1, 1, "e1", "e0", "e10"),
        Play(2, 1, "e2", "e10", "e21", [b"e21"]),
        Play(2, 2, "e21", "", "e21b"),
        Play(0, 1, "e0", "e21b", "e02"),
        Play(1, 2, "e10", "e02", "f1"),
        Play(1, 3, "f1", "", "f1b", [b"f1b"]),
        Play(0, 2, "e02", "f1b", "f0"),
        Play(2, 3, "e21b", "f1b", "f2"),
        Play(1, 4, "f1b", "f0", "f10"),
        Play(0, 3, "f0", "e21", "f0x"),
        Play(2, 4, "f2", "f10", "f21"),
        Play(0, 4, "f0x", "f21", "f02"),
        Play(0, 5, "f02", "", "f02b", [b"f02b"]),
        Play(1, 5, "f10", "f02b", "g1"),
        Play(0, 6, "f02b", "g1", "g0"),
        Play(2, 5, "f21", "g1", "g2"),
        Play(1, 6, "g1", "g0", "g10", [b"g10"]),
        Play(2, 6, "g2", "g10", "g21"),
        Play(0, 7, "g0", "g21", "g02", [b"g02"]),
        Play(1, 7, "g10", "g02", "h1"),
        Play(0, 8, "g02", "h1", "h0"),
        Play(2, 7, "g21", "h1", "h2"),
        Play(1, 8, "h1", "h0", "h10"),
        Play(2, 8, "h2", "h10", "h21"),
        Play(0, 9, "h0", "h21", "h02"),
        Play(1, 9, "h10", "h02", "i1"),
        Play(0, 10, "h02", "i1", "i0"),
        Play(2, 9, "h21", "i1", "i2"),
    ]
    return init_hashgraph_full(plays, 3, store_factory)


def get_name(index: Dict[str, str], hash_: str) -> str:
    for name, h in index.items():
        if h == hash_:
            return name
    return f"unknown event {hash_}"


def _init_with_tx_firsts(n: int):
    """Fixture style where the first events carry their own name as tx
    payload (reference funky/sparse builders, hashgraph_test.go:2030,2482)."""
    nodes, index, ordered, participants = init_hashgraph_nodes(n)
    for i, peer in enumerate(participants.to_peer_slice()):
        name = f"w0{i}"
        ev = Event(
            transactions=[name.encode()],
            block_signatures=None,
            parents=[root_self_parent(peer.id), ""],
            creator=nodes[i].pub,
            index=0,
        )
        nodes[i].sign_and_add_event(ev, name, index, ordered)
    return nodes, index, ordered, participants


def _named_plays(raw):
    """(to, index, self_parent, other_parent, name) tuples where the name is
    also the tx payload — the funky/sparse play style."""
    return [Play(t, i, sp, op, nm, [nm.encode()]) for t, i, sp, op, nm in raw]


def init_funky_hashgraph(full: bool = False, store_factory=None):
    """Adversarial 4-node topology where later rounds decide fame BEFORE
    earlier ones and the coin-round branch of DecideFame is reached
    (reference: hashgraph_test.go:2030-2080)."""
    nodes, index, ordered, participants = _init_with_tx_firsts(4)
    plays = _named_plays([
        (2, 1, "w02", "w03", "a23"),
        (1, 1, "w01", "a23", "a12"),
        (0, 1, "w00", "", "a00"),
        (1, 2, "a12", "a00", "a10"),
        (2, 2, "a23", "a12", "a21"),
        (3, 1, "w03", "a21", "w13"),
        (2, 3, "a21", "w13", "w12"),
        (1, 3, "a10", "w12", "w11"),
        (0, 2, "a00", "w11", "w10"),
        (2, 4, "w12", "w11", "b21"),
        (3, 2, "w13", "b21", "w23"),
        (1, 4, "w11", "w23", "w21"),
        (0, 3, "w10", "", "b00"),
        (1, 5, "w21", "b00", "c10"),
        (2, 5, "b21", "c10", "w22"),
        (0, 4, "b00", "w22", "w20"),
        (1, 6, "c10", "w20", "w31"),
        (2, 6, "w22", "w31", "w32"),
        (0, 5, "w20", "w32", "w30"),
        (3, 3, "w23", "w32", "w33"),
        (1, 7, "w31", "w33", "d13"),
        (0, 6, "w30", "d13", "w40"),
        (1, 8, "d13", "w40", "w41"),
        (2, 7, "w32", "w41", "w42"),
        (3, 4, "w33", "w42", "w43"),
    ])
    if full:
        plays += _named_plays([
            (2, 8, "w42", "w43", "e23"),
            (1, 9, "w41", "e23", "w51"),
        ])
    play_events(plays, nodes, index, ordered)
    store = store_factory(participants) if store_factory else None
    h = create_hashgraph(ordered, participants, store)
    return h, index, ordered


def init_sparse_hashgraph(store_factory=None):
    """4-node topology with rounds whose witness sets are sparse — some
    participants skip rounds entirely (reference: hashgraph_test.go:2482)."""
    nodes, index, ordered, participants = _init_with_tx_firsts(4)
    plays = _named_plays([
        (1, 1, "w01", "w00", "e10"),
        (2, 1, "w02", "e10", "e21"),
        (3, 1, "w03", "e21", "e32"),
        (0, 1, "w00", "e32", "w10"),
        (1, 2, "e10", "w10", "w11"),
        (0, 2, "w10", "w11", "f01"),
        (2, 2, "e21", "f01", "w12"),
        (3, 2, "e32", "w12", "w13"),
        (1, 3, "w11", "w13", "w21"),
        (2, 3, "w12", "w21", "w22"),
        (3, 3, "w13", "w22", "w23"),
        (1, 4, "w21", "w23", "g13"),
        (2, 4, "w22", "g13", "w32"),
        (3, 4, "w23", "w32", "w33"),
        (1, 5, "g13", "w33", "w31"),
        (2, 5, "w32", "w31", "h21"),
        (3, 5, "w33", "h21", "w43"),
        (1, 6, "w31", "w43", "w41"),
        (2, 6, "h21", "w41", "w42"),
        (3, 6, "w43", "w42", "i32"),
        (1, 7, "w41", "i32", "w51"),
    ])
    play_events(plays, nodes, index, ordered)
    store = store_factory(participants) if store_factory else None
    h = create_hashgraph(ordered, participants, store)
    return h, index, ordered
