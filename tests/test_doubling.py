"""Differential oracle for the log-diameter cold path (tpu/doubling.py):
pointer-doubling closure + contracted frontier walk must match the
level-scan kernel bit-exactly — rounds, witness flags, lamports, fame and
round-received — on every DAG it accepts: the frontier test fixtures,
deep Zipf-skewed grids, and post-reset section grids (where the frontier
walk itself refuses). Device pass counts are asserted logarithmic in
depth; the CPU hashgraph stays the engine-selection oracle via the
forced-crossover integration test."""

import math
import os

import numpy as np
import pytest

from babble_tpu.tpu import synthetic_grid
from babble_tpu.tpu.doubling import (
    doubling_crossover,
    run_doubling_passes,
    use_doubling,
)
from babble_tpu.tpu.engine import run_frontier_passes, run_passes
from babble_tpu.tpu.grid import (
    GridUnsupported,
    section_grid,
    synthetic_deep_grid,
)


def assert_matches(res, ref, what=""):
    for f in ("rounds", "witness", "lamport", "received"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, f)), np.asarray(getattr(ref, f)),
            err_msg=f"{what}: {f}",
        )
    assert int(res.last_round) == int(ref.last_round), what
    # the (R, N) tables are indexed by round - round_offset (PassResults
    # contract; the doubling path rebases, the plain scan does not):
    # align both on the absolute round axis before comparing
    oa, ob = int(res.round_offset), int(ref.round_offset)
    lo = max(oa, ob)
    for f in ("fame_decided", "famous", "rounds_decided"):
        va = np.asarray(getattr(res, f))
        vb = np.asarray(getattr(ref, f))
        hi = min(oa + va.shape[0], ob + vb.shape[0])
        np.testing.assert_array_equal(
            va[lo - oa:hi - oa], vb[lo - ob:hi - ob], err_msg=f"{what}: {f}"
        )
        assert not va[:lo - oa].any() and not vb[:lo - ob].any(), (
            f"{what}: {f} head"
        )
        assert not va[hi - oa:].any() and not vb[hi - ob:].any(), (
            f"{what}: {f} tail"
        )


def assert_log_passes(stats, depth):
    cap = 3 * math.log2(max(depth, 2)) + 16
    assert stats["passes"] <= cap, (
        f"{stats['passes']} device passes at depth {depth} breaks the "
        f"log bound ({cap:.0f})"
    )


_slow = pytest.mark.slow


# the frontier suite's exact fixture matrix (tests/test_frontier.py);
# rows that exercise no new shape-bucket or topology class are
# slow-marked to keep tier-1 lean
@pytest.mark.parametrize("n,e,seed,zipf,byz", [
    (4, 64, 1, 0.0, 0.0),
    pytest.param(8, 256, 2, 0.0, 0.0, marks=_slow),
    (8, 512, 3, 1.1, 0.0),
    (16, 1024, 4, 1.1, 0.0),
    pytest.param(8, 300, 7, 2.0, 0.0, marks=_slow),
    pytest.param(32, 768, 9, 1.1, 0.0, marks=_slow),
    (32, 1024, 11, 1.05, 1.0 / 3.0),
    (64, 2048, 13, 1.05, 1.0 / 3.0),
])
def test_doubling_matches_scan(n, e, seed, zipf, byz):
    grid = synthetic_grid(n, e, seed=seed, zipf_a=zipf, byzantine_frac=byz)
    stats = {}
    res = run_doubling_passes(grid, stats=stats)
    ref = run_passes(grid)
    assert_matches(res, ref, f"n={n} e={e} seed={seed}")
    assert_log_passes(stats, grid.num_levels)


@pytest.mark.slow
def test_doubling_matches_frontier_deep():
    grid = synthetic_deep_grid(8, 1024, seed=0, zipf_a=1.2)
    stats = {}
    res = run_doubling_passes(grid, stats=stats)
    assert_matches(res, run_frontier_passes(grid), "deep base 1024")
    assert_log_passes(stats, grid.num_levels)


@pytest.mark.parametrize("cut_frac,pin", [
    (1.0 / 3.0, True),
    pytest.param(1.0 / 2.0, True, marks=pytest.mark.slow),
    (1.0 / 2.0, False),
])
def test_doubling_section_matches_scan(cut_frac, pin):
    """Post-reset / fast-sync frame shapes: the grid's top section with
    the cut's parent metadata externalized. pin=True mirrors a real reset
    (the frame pins boundary rounds); pin=False is the amnesiac variant
    whose chain-first rows are non-witness frontier rows — the sharpest
    exercise of the walk's first_nw witness mask."""
    grid = synthetic_deep_grid(6, 256, seed=2, zipf_a=1.0)
    full = run_passes(grid)
    cut = int(grid.num_levels * cut_frac)
    sec = section_grid(grid, full, cut, pin_cut=pin)
    ref = run_passes(sec)
    stats = {}
    res = run_doubling_passes(sec, stats=stats)
    assert_matches(res, ref, f"section cut={cut} pin={pin}")
    assert_log_passes(stats, sec.num_levels)


def test_doubling_rejects_empty_and_falls_back():
    import dataclasses

    grid = synthetic_grid(4, 16, seed=5)
    empty = dataclasses.replace(grid, e=0)
    with pytest.raises(GridUnsupported):
        run_doubling_passes(empty)
    assert not use_doubling(empty)


def test_crossover_env_override(monkeypatch):
    monkeypatch.setenv("BABBLE_DOUBLING_CROSSOVER", "7")
    assert doubling_crossover(False) == 7
    assert doubling_crossover(True) == 7
    grid = synthetic_deep_grid(8, 64, seed=1, zipf_a=1.2)
    assert use_doubling(grid)
    monkeypatch.delenv("BABBLE_DOUBLING_CROSSOVER")
    assert doubling_crossover(False) >= doubling_crossover(True)


def test_engine_selects_doubling_and_matches_cpu(monkeypatch):
    """End-to-end ladder check against the CPU hashgraph oracle: with the
    crossover forced to 1, run_consensus_device routes every deep-enough
    grid through the doubling kernels, and every stamped round / lamport /
    fame verdict / reception must still match the host engine verbatim."""
    from test_tpu_differential import assert_equivalent, build_hashgraph_from_grid

    monkeypatch.setenv("BABBLE_DOUBLING_CROSSOVER", "1")
    grid = synthetic_grid(4, 96, seed=11, zipf_a=1.1)
    assert use_doubling(grid)
    hg, _ = build_hashgraph_from_grid(grid)
    assert_equivalent(hg)


def test_sharded_doubling_matches():
    from test_multichip import make_mesh

    from babble_tpu.tpu.sharded import sharded_doubling_passes

    mesh = make_mesh(8)
    grid = synthetic_grid(8, 400, seed=1, zipf_a=1.2)
    stats = {}
    res = sharded_doubling_passes(mesh, grid, stats=stats)
    assert_matches(res, run_passes(grid), "sharded base")
    assert stats["passes"] > 0

    deep = synthetic_deep_grid(8, 128, seed=0, zipf_a=1.2)
    full = run_passes(deep)
    sec = section_grid(deep, full, deep.num_levels // 3)
    res = sharded_doubling_passes(mesh, sec)
    assert_matches(res, run_passes(sec), "sharded section")


def test_bootstrap_frontier_state_matches_oneshot():
    """The cold-started incremental frontier state must carry exactly the
    decision tables the one-shot pipeline computes, with every divergence
    latch clear — i.e. a deep joining node can adopt the live engine
    without replaying append trains."""
    from babble_tpu.tpu.frontier_live import bootstrap_frontier_state

    grid = synthetic_grid(8, 600, seed=4, zipf_a=1.1)
    ref = run_frontier_passes(grid)
    st = bootstrap_frontier_state(
        grid, e_cap=grid.e + 64, l_cap=int(grid.index.max()) + 32,
        r_cap=256, n_participants=grid.n,
    )
    np.testing.assert_array_equal(np.asarray(st.rounds)[:grid.e], ref.rounds)
    np.testing.assert_array_equal(np.asarray(st.witness)[:grid.e], ref.witness)
    np.testing.assert_array_equal(np.asarray(st.received)[:grid.e], ref.received)
    assert int(st.last_round) == int(ref.last_round)
    assert int(st.count) == grid.e
    assert not bool(st.l_over) and not bool(st.r_over)
    assert not bool(st.frozen_violation)


def test_bootstrap_frontier_state_rejects_seeded():
    from babble_tpu.tpu.frontier_live import bootstrap_frontier_state

    grid = synthetic_deep_grid(6, 96, seed=2, zipf_a=1.0)
    sec = section_grid(grid, run_passes(grid), grid.num_levels // 2)
    with pytest.raises(GridUnsupported):
        bootstrap_frontier_state(
            sec, e_cap=sec.e + 64, l_cap=4096, r_cap=256, n_participants=6,
        )


def test_observe_catchup_emits_record_and_series():
    from babble_tpu.obs import Observability
    from babble_tpu.tpu.doubling import observe_catchup

    obs = Observability()
    observe_catchup(obs, {"depth": 123, "passes": 9}, 0.25)
    snap = obs.registry.snapshot()
    hist = snap["babble_catchup_replay_seconds"]["series"][""]
    assert hist["count"] == 1
    recs = [r for r in obs.flightrec.records() if r.name == "catchup.replay"]
    assert recs
    assert recs[-1].fields["depth"] == 123
    assert recs[-1].fields["passes"] == 9


@pytest.mark.slow
def test_doubling_deep_4096():
    grid = synthetic_deep_grid(8, 4096, seed=0, zipf_a=1.2)
    full = run_frontier_passes(grid)
    stats = {}
    res = run_doubling_passes(grid, stats=stats)
    assert_matches(res, full, "deep base 4096")
    assert_log_passes(stats, grid.num_levels)

    sec = section_grid(grid, full, grid.num_levels // 2)
    ref = run_passes(sec)
    stats = {}
    res = run_doubling_passes(sec, stats=stats)
    assert_matches(res, ref, "deep section 4096")
    assert_log_passes(stats, sec.num_levels)
