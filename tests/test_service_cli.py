"""HTTP status service and CLI tests: /stats and /block over a live
node (reference: src/service/service.go:28-63), keygen datadir output
(cmd/babble/commands/keygen.go), and the flag/config-file merge
precedence (run.go:93-155)."""

import json
import os
import urllib.error
import urllib.request

from babble_tpu.cli import _merge_config_file, build_parser, keygen_command
from babble_tpu.service import Service

from test_node import bombard_and_wait, init_nodes, run_nodes, shutdown_nodes

REFERENCE_STAT_KEYS = {
    "last_consensus_round", "last_block_index", "consensus_events",
    "consensus_transactions", "undetermined_events", "transaction_pool",
    "num_peers", "sync_rate", "events_per_second", "rounds_per_second",
    "round_events", "id", "state",
}


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def test_service_stats_and_block():
    nodes, proxies = init_nodes(4)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"

        stats = _get(base + "/stats")
        # parity: every reference metric present (node.go:660-695), plus
        # the backend extensions
        assert REFERENCE_STAT_KEYS <= set(stats)
        assert stats["consensus_backend"] in ("cpu", "tpu")
        assert stats["num_peers"] == "4"

        bombard_and_wait(nodes, proxies, target_block=1)
        blk = _get(base + "/block/0")
        assert blk["Body"]["Index"] == 0
        assert isinstance(blk["Body"]["Transactions"], list)

        # round_events is actually maintained here (the reference declares
        # but never updates it): events in the round before the last
        # consensus round
        stats = _get(base + "/stats")
        assert int(stats["round_events"]) > 0

        # missing block -> HTTP error, service stays up
        try:
            _get(base + "/block/99999")
            raise AssertionError("expected HTTP error for missing block")
        except urllib.error.HTTPError as e:
            assert e.code in (404, 500)
        assert _get(base + "/stats")["num_peers"] == "4"
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)


def test_keygen_writes_pem(tmp_path):
    class Args:
        datadir = str(tmp_path)

    assert keygen_command(Args()) == 0
    pem = os.path.join(str(tmp_path), "priv_key.pem")
    assert os.path.exists(pem)
    assert b"EC PRIVATE KEY" in open(pem, "rb").read()
    # refuses to overwrite an existing key
    assert keygen_command(Args()) == 1


def test_config_file_merge_flags_win(tmp_path):
    (tmp_path / "babble.json").write_text(json.dumps({
        "heartbeat": 0.25,
        "sync-limit": 42,
        "consensus-backend": "tpu",
    }))
    # file fills defaults...
    argv = ["run", "--datadir", str(tmp_path)]
    args = build_parser().parse_args(argv)
    _merge_config_file(args, argv)
    assert args.heartbeat == 0.25
    assert args.sync_limit == 42
    assert args.consensus_backend == "tpu"
    # ...but explicit flags win over the file
    argv = ["run", "--datadir", str(tmp_path), "--heartbeat", "0.5",
            "--consensus-backend", "cpu"]
    args = build_parser().parse_args(argv)
    _merge_config_file(args, argv)
    assert args.heartbeat == 0.5
    assert args.consensus_backend == "cpu"
    assert args.sync_limit == 42  # still from the file

    # argparse's glued short options and prefix abbreviations also count
    # as explicit (argparse itself does the accounting)
    (tmp_path / "babble.json").write_text(json.dumps({
        "timeout": 3.0, "heartbeat": 9.0,
    }))
    argv = ["run", "--datadir", str(tmp_path), "-t5", "--heart", "2"]
    args = build_parser().parse_args(argv)
    _merge_config_file(args, argv)
    assert args.timeout == 5.0
    assert args.heartbeat == 2.0


def test_service_metrics_and_trace():
    """GET /metrics (Prometheus text exposition from the node's registry)
    and GET /debug/trace (Chrome trace-event JSON from the span ring) —
    the scrape/trace surface of ISSUE 4."""
    nodes, proxies = init_nodes(2)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"
        bombard_and_wait(nodes, proxies, target_block=1)

        req = urllib.request.urlopen(base + "/metrics", timeout=5)
        assert req.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4"
        )
        text = req.read().decode()
        # headline + subsystem histograms declared, with valid shape
        for name in (
            "babble_commit_latency_seconds",
            "babble_sync_duration_seconds",
            "babble_consensus_pass_duration_seconds",
            "babble_device_dispatch_seconds",
            "babble_device_fetch_seconds",
        ):
            assert f"# TYPE {name} histogram" in text, name
        assert "# TYPE babble_blocks_committed_total counter" in text
        assert "# TYPE babble_last_block_index gauge" in text
        # the commit actually landed in the headline histogram
        count_lines = [
            ln for ln in text.splitlines()
            if ln.startswith("babble_commit_latency_seconds_count")
        ]
        assert count_lines and int(count_lines[0].split()[-1]) >= 1
        assert 'le="+Inf"' in text
        # consensus passes ran and were labeled by phase
        assert (
            'babble_consensus_pass_duration_seconds_count'
            '{phase="divide_rounds"}'
        ) in text

        trace = _get(base + "/debug/trace")
        assert trace["displayTimeUnit"] == "ms"
        evs = trace["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        assert xs, "no spans recorded during a committing run"
        names = {e["name"] for e in xs}
        assert "commit" in names
        assert any(n.startswith("consensus.") for n in names)
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in evs)
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)


def test_service_debug_endpoints():
    """/debug/stacks (thread dump) and /debug/profile (all-thread stack
    sampler) — the profiling channel of the reference's
    pprof-on-the-service-mux (reference: cmd/babble/main.go:4). The
    profile must cover the NODE's threads, not just the HTTP handler: a
    gossiping node's loops live in node.py, which must show up among the
    sampled frames."""
    import urllib.request

    nodes, proxies = init_nodes(2)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"

        with urllib.request.urlopen(base + "/debug/stacks", timeout=10) as r:
            stacks = r.read().decode()
        assert "thread" in stacks and "File" in stacks

        with urllib.request.urlopen(
            base + "/debug/profile?seconds=0.5", timeout=30
        ) as r:
            prof = r.read().decode()
        assert "hottest frames" in prof
        assert "node.py" in prof, "profile missed the node's own threads"

        # collapsed (folded-stack) output: `frame;frame;... count` lines,
        # root-first, ready for flamegraph.pl / speedscope
        with urllib.request.urlopen(
            base + "/debug/profile?seconds=0.5&format=collapsed", timeout=30
        ) as r:
            folded = r.read().decode()
        lines = [ln for ln in folded.splitlines() if ln]
        assert lines
        for ln in lines:
            stack, count = ln.rsplit(" ", 1)
            assert int(count) >= 1
            assert stack
        assert any(";" in ln for ln in lines), "no multi-frame stacks"
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)


def test_service_flightrec_and_slo_endpoints():
    """GET /debug/flightrec (the flight recorder's full state: ring,
    counters, fingerprint) and GET /debug/slo (a fresh SLO evaluation) —
    the triage surface of ISSUE 7."""
    nodes, proxies = init_nodes(2)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"
        bombard_and_wait(nodes, proxies, target_block=1)

        fr = _get(base + "/debug/flightrec")
        assert fr["node"] == nodes[0].id
        assert fr["capacity"] >= 1
        assert isinstance(fr["records"], list)
        assert len(fr["fingerprint"]) == 64  # sha256 hex
        for key in ("dropped", "dumps", "dumps_suppressed"):
            assert fr[key] >= 0

        slo = _get(base + "/debug/slo")
        assert slo["windows"] == ["60s", "300s"]
        names = {o["name"] for o in slo["objectives"]}
        assert {"submit_commit_p99", "round_advance"} <= names
        for obj in slo["objectives"]:
            assert set(obj["burn"]) == {"60s", "300s"}
            assert isinstance(obj["breached"], bool)
        # a healthy committing run breaches nothing
        commit = next(o for o in slo["objectives"]
                      if o["name"] == "submit_commit_p99")
        assert commit["breached"] is False
        # the SLO gauges reached the scrape surface
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "# TYPE babble_slo_breached gauge" in text
        assert "# TYPE babble_flightrec_records gauge" in text
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)
