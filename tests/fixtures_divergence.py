"""Seeded consensus divergence for the bisector tests (ISSUE 14).

`broken_fame_passes` is DELIBERATELY wrong: behind its flag it runs the
real device engine and then flips exactly one decided famous verdict —
the synthetic "miscompiled kernel step" the first-divergence bisector
exists to localize. It lives under tests/ (outside the lint scope, like
fixtures_races.py) so the real tree stays clean, and exists to prove
the bisector localizes an injected defect to its exact
(pass, table, round, witness) cell.

Do not fix it.
"""

from __future__ import annotations

import random
from dataclasses import replace

import numpy as np


def broken_fame_passes(grid, flip: bool = True, seed: int = 0):
    """Run the real engine on `grid`; when `flip`, corrupt one decided
    famous bit chosen by a seeded PRNG. Returns
    ``(res, injected)`` where `injected` is the corrupted cell as
    ``(absolute_round, witness_hash)`` — or None when `flip` is False
    (the clean control arm)."""
    from babble_tpu.obs.provenance import grid_cell_keys
    from babble_tpu.tpu.engine import run_passes

    res = run_passes(grid)
    if not flip:
        return res, None
    candidates = []
    round_offset = int(getattr(res, "round_offset", 0))
    for ti in range(res.witness_table.shape[0]):
        for c in range(res.witness_table.shape[1]):
            wrow = int(res.witness_table[ti, c])
            if wrow >= 0 and bool(res.fame_decided[ti, c]):
                candidates.append((ti, c, wrow))
    assert candidates, "fixture grid decided no fame at all"
    rng = random.Random(seed)
    ti, c, wrow = candidates[rng.randrange(len(candidates))]
    famous = np.array(res.famous, copy=True)
    famous[ti, c] = not bool(famous[ti, c])
    return (
        replace(res, famous=famous),
        (ti + round_offset, grid_cell_keys(grid)[wrow]),
    )
