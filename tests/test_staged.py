"""Staged-kernel contract checker tests (babble_tpu/analysis/staged.py,
docs/analysis.md "Kernel contracts").

One seeded-defect scratch-copy fixture per rule family — each appends a
defective staged function to a copy of the REAL kernel module and asserts
exactly its intended rule fires (the PR 8/17 pattern) — plus the standing
acceptance gates: the real tree at zero findings with the shipped (empty)
baseline, byte-identical finding streams across runs, every engine rung
carrying a checked contract, and the docs/tpu.md contract-table embed in
sync with the generator.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from babble_tpu.analysis.core import SourceFile
from babble_tpu.analysis.runner import main as lint_main, run_lint
from babble_tpu.analysis.staged import (
    check_staged,
    collect_contracts,
    kernel_baseline_entries,
    render_contract_table,
)

REPO_ROOT = str(Path(__file__).resolve().parents[1])

KERNELS = Path(REPO_ROOT) / "babble_tpu" / "tpu" / "kernels.py"
SHARDED = Path(REPO_ROOT) / "babble_tpu" / "tpu" / "sharded.py"


def _seed(tmp_path: Path, real: Path, extra: str) -> Path:
    """Scratch copy of a REAL tpu module with a seeded defect appended."""
    p = tmp_path / "babble_tpu" / "tpu" / real.name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(real.read_text() + textwrap.dedent(extra))
    return p


def _staged_lint(root) -> list:
    return run_lint(str(root), baseline_path=None, staged=True).new


# ---------------------------------------------------------------------------
# one seeded-defect fixture per rule family
# ---------------------------------------------------------------------------


def test_seeded_layout_mix_fires_exactly_its_rule(tmp_path):
    """A packed uint32 word table flowing into a traced select against the
    wide table it was packed from is the layout-mix hazard."""
    real_lines = len(KERNELS.read_text().splitlines())
    _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_layout_mix
        #   in: votes:bool[2]:wide
        #   rung: one-shot
        #   out: seeded
        @jax.jit
        def _seeded_layout_mix(votes):
            pv = pack_bits(votes)
            return jnp.where(votes, pv, votes)
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-layout-mix", "_seeded_layout_mix")
    ]
    assert found[0].line > real_lines


def test_seeded_donate_reuse_fires_exactly_its_rule(tmp_path):
    """Reading a buffer after donating it to a staged call is the
    use-after-donate hazard — XLA may have overwritten it in place."""
    _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_donated
        #   in: buf:i32[2]
        #   donate: buf
        #   rung: one-shot
        #   out: seeded
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _seeded_donated(buf):
            return buf + 1


        def _seeded_driver(buf):
            out = _seeded_donated(buf)
            return out + buf.sum()
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-donate-reuse", "_seeded_driver")
    ]
    assert "donated to the staged call" in found[0].message


def test_seeded_wrong_psum_axis_fires_exactly_its_rule(tmp_path):
    """A collective naming an axis outside the contract's declared mesh
    axes is the dead-axis hazard."""
    _seed(tmp_path, SHARDED, """

        @functools.lru_cache(maxsize=2)
        def _seeded_mesh_factory(mesh, axis):
            # kernel-contract: _seeded_mesh_local
            #   in: x:i32[1]
            #   mesh: axis
            #   rung: sharded
            #   out: seeded
            def _seeded_mesh_local(x):
                return jax.lax.psum(x, "dead_axis")
            return jax.jit(_shard_map(
                _seeded_mesh_local, mesh=mesh, in_specs=(P(axis),),
                out_specs=P(axis),
            ))
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-mesh-axis", "_seeded_mesh_local")
    ]
    assert "dead_axis" in found[0].message


def test_seeded_retrace_hazard_fires_exactly_its_rule(tmp_path):
    """A shard_map factory without lru_cache re-traces per call — every
    invocation builds a fresh Python closure and fragments the
    executable cache."""
    _seed(tmp_path, SHARDED, """

        def _seeded_retrace_factory(mesh, axis):
            # kernel-contract: _seeded_retrace_local
            #   in: x:i32[1]
            #   mesh: axis
            #   rung: sharded
            #   out: seeded
            def _seeded_retrace_local(x):
                return x
            return jax.jit(_shard_map(
                _seeded_retrace_local, mesh=mesh, in_specs=(P(axis),),
                out_specs=P(axis),
            ))
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-retrace-hazard", "_seeded_retrace_local")
    ]
    assert "lru_cached" in found[0].message


def test_seeded_carry_drift_fires_exactly_its_rule(tmp_path):
    """A scan whose body returns a carry with a different abstract dtype
    than the init is the carry-drift hazard (XLA would reject it at trace
    time with an opaque error; the checker names the drifting slot)."""
    _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_carry
        #   in: x:i32[1]
        #   rung: one-shot
        #   out: seeded
        @jax.jit
        def _seeded_carry(x):
            def body(c, _):
                return c.astype(jnp.float32), None
            out, _ = jax.lax.scan(body, x, None, length=4)
            return out
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-carry-shape", "_seeded_carry")
    ]


# ---------------------------------------------------------------------------
# contract bookkeeping rules
# ---------------------------------------------------------------------------


def test_missing_contract_is_flagged(tmp_path):
    _seed(tmp_path, KERNELS, """

        @jax.jit
        def _seeded_uncontracted(x):
            return x + 1
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-contract", "_seeded_uncontracted")
    ]


def test_stale_contract_is_flagged(tmp_path):
    _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_gone
        #   in: x:i32[1]
        #   rung: one-shot
        #   out: stale
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.symbol) for f in found] == [
        ("kernel-contract", "_seeded_gone")
    ]
    assert "stale" in found[0].message


def test_kernel_ok_waiver_suppresses_and_is_audited(tmp_path):
    """kernel-ok on the offending line suppresses the finding; with
    --staged active an unconsumed kernel-ok is itself a dead waiver."""
    _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_waived
        #   in: votes:bool[2]:wide
        #   rung: one-shot
        #   out: seeded
        @jax.jit
        def _seeded_waived(votes):
            pv = pack_bits(votes)
            # kernel-ok: fixture proves waiver suppression
            return jnp.where(votes, pv, votes)
    """)
    assert _staged_lint(tmp_path) == []

    dead = _seed(tmp_path, KERNELS, """

        # kernel-contract: _seeded_clean
        #   in: x:i32[1]
        #   rung: one-shot
        #   out: seeded
        @jax.jit
        def _seeded_clean(x):
            # kernel-ok: nothing here needs waiving
            return x + 1
    """)
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.path) for f in found] == [
        ("lint-dead-waiver", "babble_tpu/tpu/kernels.py")
    ]
    assert dead.exists()


def test_contract_outside_staged_scope_is_dead_annotation(tmp_path):
    """A kernel-contract in a module the staged checker never analyzes
    can't be audited — under --staged it is flagged as dead."""
    p = tmp_path / "babble_tpu" / "node" / "fixture.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent("""\
        # kernel-contract: nothing_here
        #   in: x:i32[1]
        def nothing_here(x):
            return x
    """))
    assert run_lint(str(tmp_path), baseline_path=None).new == []
    found = _staged_lint(tmp_path)
    assert [(f.rule, f.line) for f in found] == [("lint-dead-waiver", 1)]
    assert "outside the staged-analysis scope" in found[0].message


# ---------------------------------------------------------------------------
# acceptance gates: real tree clean, deterministic, rungs covered
# ---------------------------------------------------------------------------


def test_real_tree_zero_findings_with_empty_baseline():
    result = run_lint(REPO_ROOT, baseline_path=None, staged=True)
    assert result.errors == []
    assert [f.location() for f in result.new] == []
    assert kernel_baseline_entries() == []


def test_two_runs_emit_byte_identical_finding_streams(tmp_path):
    """Determinism of the finding stream itself, on a tree that actually
    produces findings (a clean tree is trivially identical)."""
    from babble_tpu.analysis.runner import format_report

    _seed(tmp_path, KERNELS, """

        @jax.jit
        def _seeded_uncontracted(x):
            return x + 1
    """)
    first = format_report(run_lint(str(tmp_path), baseline_path=None,
                                   staged=True))
    second = format_report(run_lint(str(tmp_path), baseline_path=None,
                                    staged=True))
    assert first.encode() == second.encode()


def test_every_engine_rung_carries_checked_contracts():
    """One-shot, frontier, doubling, sharded, incremental and the live
    serve path each declare contracts; the queued-dispatch rung stages
    the sharded/doubling kernels (tpu/dispatch.py holds no staged defs of
    its own — docs/tpu.md 'Kernel contracts'). Both voting layouts are
    covered: the sharded fame loop declares dual (wide+packed) carries
    and every fame kernel declares the `packed` layout static."""
    rows = collect_contracts(REPO_ROOT)
    rungs = {c.rung for _rel, _rec, c in rows}
    assert {"one-shot", "frontier", "doubling", "sharded",
            "incremental", "live"} <= rungs
    by_name = {rec.name: c for _rel, rec, c in rows}
    assert len(by_name) == 23
    duals = {
        name for name, c in by_name.items()
        if any(v.layout == "dual" for v in c.args.values())
    }
    assert "local_fame" in duals
    packed_statics = {
        name for name, c in by_name.items() if "packed" in c.statics
    }
    assert {"consensus_pipeline", "frontier_pipeline", "_fame_received",
            "_step_full", "multi_step", "train_step", "multi_train",
            "frontier_train_step", "frontier_multi_train",
            "_decide"} <= packed_statics
    donated = {name for name, c in by_name.items() if c.donate}
    assert {"local_fame", "local_received", "_step_full", "train_step",
            "multi_step", "multi_train", "frontier_train_step",
            "frontier_multi_train"} <= donated


def test_contract_table_embed_in_sync_with_docs():
    table = render_contract_table(REPO_ROOT)
    doc = (Path(REPO_ROOT) / "docs" / "tpu.md").read_text()
    begin, end = "<!-- contract-table:begin -->", "<!-- contract-table:end -->"
    assert begin in doc and end in doc
    embedded = doc.split(begin, 1)[1].split(end, 1)[0].strip()
    assert embedded == table.strip(), (
        "docs/tpu.md contract table is stale — regenerate with "
        "`babble-tpu lint --contract-table`"
    )


def test_cli_staged_flag_and_contract_table(capsys):
    assert lint_main(["--staged", "--no-baseline"], root=REPO_ROOT) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
    assert "lint wall-time:" in out
    assert "staged-kernel contracts included" in out

    assert lint_main(["--contract-table"], root=REPO_ROOT) == 0
    out = capsys.readouterr().out
    assert "| rung | staged function |" in out
    assert "local_fame" in out


def test_kernel_baseline_entries_filters_kernel_rules(tmp_path):
    import json

    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [
        {"rule": "det-wallclock", "path": "a.py", "symbol": "f", "text": "x"},
        {"rule": "kernel-layout-mix", "path": "b.py", "symbol": "g",
         "text": "y"},
    ]}))
    entries = kernel_baseline_entries(str(bl))
    assert [e["rule"] for e in entries] == ["kernel-layout-mix"]


def test_checker_consumes_real_contract_lines():
    """Every contract directive line in the real sharded module is marked
    used by the checker (none would survive the dead-annotation audit)."""
    sf = SourceFile.parse(str(SHARDED), "babble_tpu/tpu/sharded.py")
    findings = list(check_staged(sf))
    assert findings == []
    contract_lines = [
        ln for ln, text in sf.comments.items()
        if text.startswith("kernel-contract:") or any(
            text.startswith(d)
            for d in ("in:", "static:", "donate:", "mesh:", "rung:", "out:")
        )
    ]
    assert contract_lines
    assert set(contract_lines) <= sf.used_waiver_lines


def test_packed_surfaces_refuse_on_stale_kernel_baseline(monkeypatch, capsys):
    """bench_mesh_scale --headline packed and scripts/packed_smoke.py must
    refuse (clear error, exit 2) while the lint baseline carries any
    kernel-* entry: a packed headline over unproven kernels is a green
    number on unchecked code (ISSUE 18 bugfix)."""
    import importlib.util

    from babble_tpu.analysis import staged as staged_mod

    fake = [{"rule": "kernel-layout-mix",
             "path": "babble_tpu/tpu/kernels.py",
             "symbol": "consensus_pipeline", "text": "x"}]
    monkeypatch.setattr(
        staged_mod, "kernel_baseline_entries", lambda *a, **k: fake)

    spec = importlib.util.spec_from_file_location(
        "bench_mesh_scale_guard",
        str(Path(REPO_ROOT) / "bench_mesh_scale.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.main(["--headline", "packed", "--validators", "8"]) == 2
    err = capsys.readouterr().err
    assert "REFUSING" in err and "kernel-layout-mix" in err

    spec = importlib.util.spec_from_file_location(
        "packed_smoke_guard",
        str(Path(REPO_ROOT) / "scripts" / "packed_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    assert smoke.main() == 2
    err = capsys.readouterr().err
    assert "REFUSING" in err and "lint --staged" in err
