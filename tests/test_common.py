"""Foundation tests (reference: src/common/lru_test.go, rolling_index_test.go)."""

import pytest

from babble_tpu.common import (
    LRU,
    RollingIndex,
    RollingIndexMap,
    StoreErr,
    StoreErrType,
    hash32,
    is_store_err,
)


class TestLRU:
    def test_add_get(self):
        lru = LRU(2)
        lru.add("a", 1)
        lru.add("b", 2)
        v, ok = lru.get("a")
        assert ok and v == 1

    def test_eviction(self):
        evicted = []
        lru = LRU(2, on_evict=lambda k, v: evicted.append(k))
        lru.add("a", 1)
        lru.add("b", 2)
        lru.add("c", 3)  # evicts a
        _, ok = lru.get("a")
        assert not ok
        assert evicted == ["a"]

    def test_recency(self):
        lru = LRU(2)
        lru.add("a", 1)
        lru.add("b", 2)
        lru.get("a")  # refresh a
        lru.add("c", 3)  # evicts b
        _, ok = lru.get("b")
        assert not ok
        _, ok = lru.get("a")
        assert ok

    def test_keys_order(self):
        lru = LRU(3)
        for k in "abc":
            lru.add(k, k)
        assert lru.keys() == ["a", "b", "c"]


class TestRollingIndex:
    def test_sequential_set_get(self):
        ri = RollingIndex("test", 10)
        items = [f"item{i}" for i in range(9)]
        for i, it in enumerate(items):
            ri.set(it, i)
        cached, last = ri.get_last_window()
        assert last == 8
        assert list(cached) == items
        assert ri.get(4) == items[5:]

    def test_skipped_index(self):
        ri = RollingIndex("test", 10)
        ri.set("item0", 0)
        with pytest.raises(StoreErr) as ei:
            ri.set("item2", 2)
        assert is_store_err(ei.value, StoreErrType.SKIPPED_INDEX)

    def test_roll(self):
        size = 10
        ri = RollingIndex("test", size)
        for i in range(2 * size + 1):  # one past the window: triggers roll
            ri.set(f"item{i}", i)
        cached, last = ri.get_last_window()
        assert last == 2 * size
        assert len(cached) == size + 1
        assert cached[0] == f"item{size}"
        # old items are TooLate
        with pytest.raises(StoreErr) as ei:
            ri.get_item(size - 1)
        assert is_store_err(ei.value, StoreErrType.TOO_LATE)
        assert ri.get_item(size) == f"item{size}"

    def test_get_item(self):
        ri = RollingIndex("test", 10)
        for i in range(5):
            ri.set(i * 100, i)
        assert ri.get_item(3) == 300
        with pytest.raises(StoreErr) as ei:
            ri.get_item(9)
        assert is_store_err(ei.value, StoreErrType.KEY_NOT_FOUND)

    def test_replace_existing(self):
        ri = RollingIndex("test", 10)
        for i in range(5):
            ri.set(i, i)
        ri.set(99, 3)
        assert ri.get_item(3) == 99


class TestRollingIndexMap:
    def test_basic(self):
        rim = RollingIndexMap("test", 5, [1, 2, 3])
        rim.set(1, "a", 0)
        rim.set(2, "b", 0)
        assert rim.get_last(1) == "a"
        known = rim.known()
        assert known == {1: 0, 2: 0, 3: -1}
        with pytest.raises(StoreErr) as ei:
            rim.get_last(3)
        assert is_store_err(ei.value, StoreErrType.EMPTY)

    def test_reset(self):
        rim = RollingIndexMap("test", 5, [1])
        rim.set(1, "a", 0)
        rim.reset()
        assert rim.known() == {1: -1}


def test_hash32_known_vectors():
    # FNV-1a 32-bit reference vectors
    assert hash32(b"") == 2166136261
    assert hash32(b"a") == 0xE40C292C
    assert hash32(b"foobar") == 0xBF9CF968
