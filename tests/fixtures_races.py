"""Seeded concurrency defects for the race-certification tests (ISSUE 12).

These classes are DELIBERATELY wrong. They live under tests/ — outside
the lint scope — so the real tree stays clean, and exist to prove the
detectors actually fire:

- `UnguardedBox`  — an annotated field written without its lock (the
  dynamic lockset detector must report `race.candidate`) plus an
  unannotated shared field (the static inference pass must report
  `lock-unannotated`).
- `InvertedPair`  — an A→B / B→A lock-order inversion (the lock-order
  analyzer must report `lockorder.cycle`).

Do not fix them.
"""

from __future__ import annotations

import threading


class UnguardedBox:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock
        # seeded defect: shared, mutated below, no annotation, no waiver
        self._tally = 0

    def locked_bump(self) -> None:
        with self._lock:
            self._count += 1

    def unguarded_bump(self) -> None:
        # seeded defect: guarded field written without holding the lock
        self._count += 1

    def tally_bump(self) -> None:
        self._tally += 1

    def snapshot(self) -> int:
        with self._lock:
            return self._count


class InvertedPair:
    def __init__(self) -> None:
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self) -> None:
        with self._a:
            with self._b:
                pass

    def ba(self) -> None:
        # seeded defect: inverted acquisition order vs ab()
        with self._b:
            with self._a:
                pass
