"""Multi-node integration tests: full Nodes wired through InmemTransport and
InmemDummyClient apps (reference: src/node/node_test.go).

`check_gossip` is the consensus-correctness oracle: byte-equality of every
block body across all nodes (reference: src/node/node_test.go:741-771).
"""

import os
import random
import time

import pytest

from babble_tpu.crypto import generate_key, pub_key_bytes
from babble_tpu.hashgraph import InmemStore
from babble_tpu.net import InmemTransport
from babble_tpu.node import Config, Node
from babble_tpu.peers import Peer, Peers
from babble_tpu.proxy import InmemDummyClient


def make_config():
    return Config(heartbeat_timeout=0.005, tcp_timeout=1.0, cache_size=1000, sync_limit=300)


def init_nodes(n, conf=None):
    conf = conf or make_config()
    keys = [generate_key() for _ in range(n)]
    participants = Peers()
    peer_of_key = []
    for i, key in enumerate(keys):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr=f"127.0.0.1:{9990 + i}", pub_key_hex=pub_hex)
        participants.add_peer(peer)
        peer_of_key.append(peer)

    nodes, transports, proxies = [], [], []
    for i, key in enumerate(keys):
        trans = InmemTransport(peer_of_key[i].net_addr)
        prox = InmemDummyClient()
        node = Node(
            conf,
            peer_of_key[i].id,
            key,
            participants,
            InmemStore(participants, conf.cache_size),
            trans,
            prox,
        )
        node.init()
        nodes.append(node)
        transports.append(trans)
        proxies.append(prox)

    # full-mesh connect the fake network
    for t in transports:
        for u in transports:
            if t is not u:
                t.connect(u.local_addr(), u)

    return nodes, proxies


def run_nodes(nodes, gossip=True):
    for node in nodes:
        node.run_async(gossip)


def shutdown_nodes(nodes):
    for node in nodes:
        node.shutdown()


def load_scale() -> float:
    """Deadline multiplier for a loaded machine: wall-clock budgets sized
    for an idle box flake when the suite shares CPUs with other work
    (VERDICT r2 weak #6 — test_catch_up failed under contention, passed
    alone). Clamped so a pathological load average cannot make a genuine
    deadlock take an hour to report."""
    try:
        per_cpu = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:
        return 1.0
    return min(max(per_cpu, 1.0), 4.0)


def bombard_and_wait(nodes, proxies, target_block, timeout_s=30.0):
    """Random tx generator + poll until all nodes reach the target block
    with a state hash (reference: src/node/node_test.go:703-739).

    The deadline is progress-aware, not wall-clock-absolute: the budget is
    load-scaled, and as long as the slowest node keeps committing blocks
    the wait extends — slowness is not failure; only a genuine stall
    (no minimum-index progress for a full budget) is.

    Submission is CLOSED-LOOP (VERDICT r4 #7): a node whose transaction
    pool is already backed up gets no more traffic until consensus drains
    it. The old fixed-rate blast (150 tx/s regardless of backlog) was what
    saturated core locks, starved joiners' FastForwardRequests, and filled
    passing runs with "command timed out" spam."""
    budget = timeout_s * load_scale()
    stop = time.monotonic() + budget
    tx_counter = 0
    best_min = -2
    while time.monotonic() < stop:
        # submit a few random transactions through random nodes, skipping
        # nodes that have not integrated the last burst yet
        for _ in range(3):
            i = random.randrange(len(proxies))
            if i < len(nodes) and len(nodes[i].core.transaction_pool) >= 50:
                continue  # backpressure: let consensus drain first
            proxies[i].submit_tx(f"tx {tx_counter} from {i}".encode())
            tx_counter += 1
        done = True
        for node in nodes:
            if node.core.get_last_block_index() < target_block:
                done = False
                break
            try:
                block = node.get_block(target_block)
            except Exception:  # noqa: BLE001 — joined above the target:
                continue  # its replayed history starts past target_block
            if not block.state_hash():
                done = False
                break
        if done:
            return
        cur_min = min(n.core.get_last_block_index() for n in nodes)
        if cur_min > best_min:
            best_min = cur_min
            stop = max(stop, time.monotonic() + budget)
        time.sleep(0.02)
    # post-mortem for the wedge: whatever thread is hogging a core_lock
    # right now is the reason progress stopped
    import faulthandler
    import sys

    faulthandler.dump_traceback(file=sys.stderr)
    states = []
    for n in nodes:
        try:
            states.append(_node_state(n))
        except Exception as e:  # noqa: BLE001 — the dump must never
            states.append({"id": n.id, "dump_error": str(e)})  # eat the
    raise AssertionError(  # real assertion
        f"no progress for {budget:.0f}s waiting for block {target_block}; "
        f"indices={[n.core.get_last_block_index() for n in nodes]}\n"
        f"node states: {states}"
    )


def _node_state(n):
    return {
        "id": n.id,
        "state": str(n.get_state()),
        "block": n.core.get_last_block_index(),
        "inflight": getattr(n, "_gossip_inflight", None),
        "timer_set": n.control_timer.set,
        "starting": n.is_starting(),
        "syncs": n.sync_requests,
        "sync_errors": n.sync_errors,
        "bounces": n.fast_forward_bounces,
        "tx_pool": len(n.core.transaction_pool),
        "need_gossip": n.core.need_gossip(),
        "lcr": n.core.hg.last_consensus_round,
        "pending": [
            (pr.index, pr.decided) for pr in n.core.hg.pending_rounds[:8]
        ],
        "undetermined": len(n.core.hg.undetermined_events),
        "round_dist": _round_dist(n.core.hg),
        "witness_state": _witness_state(n.core.hg),
        "last_round": n.core.hg.store.last_round(),
        "blocks": _dump_blocks(
            [n],
            max(0, n.core.get_last_block_index() - 3),
            n.core.get_last_block_index(),
        )[0][2],
    }


def _round_dist(hg):
    """Round distribution of (a sample of) the undetermined backlog — a
    frozen pipeline shows everything piled into one round."""
    from collections import Counter

    rc = Counter()
    for h in hg.undetermined_events[:4000]:
        try:
            rc[hg.store.get_event(h).round] += 1
        except Exception:  # noqa: BLE001
            rc["err"] += 1
    return dict(rc)


def _witness_state(hg):
    """(witness count, fame-decided count) for the last three rounds."""
    out = {}
    last = hg.store.last_round()
    for r in range(max(0, last - 2), last + 1):
        try:
            ri = hg.store.get_round(r)
            ws = ri.witnesses()
            out[r] = (len(ws), sum(1 for w in ws if ri.is_decided(w)))
        except Exception as e:  # noqa: BLE001
            out[r] = str(e)
    return out


def check_gossip(nodes, from_block=0, upto=None):
    """Every block body must be byte-identical across nodes. Comparison is
    capped at `upto` (the block all nodes were verified to have committed,
    state hash included) — later blocks may still be mid-commit on some
    nodes when this runs."""
    min_last = min(n.core.get_last_block_index() for n in nodes)
    assert min_last >= from_block
    if upto is not None:
        min_last = min(min_last, upto)
    for i in range(from_block, min_last + 1):
        ref = nodes[0].get_block(i)
        settled = bool(ref.state_hash())
        for node in nodes[1:]:
            other = node.get_block(i)
            if not other.state_hash():
                settled = False
        if not settled:
            # a block without its state hash is still mid-commit on that
            # node (the commit channel is asynchronous); everything at and
            # above it is not yet comparable
            break
        for node in nodes[1:]:
            other = node.get_block(i)
            assert other.body.marshal() == ref.body.marshal(), (
                f"block {i} differs between node {nodes[0].id} and node "
                f"{node.id}:\n  {ref.body.marshal()!r}\n  vs\n"
                f"  {other.body.marshal()!r}\n"
                f"  positions={[(p, n.id) for p, n in enumerate(nodes)]}\n"
                f"  dump={_dump_blocks(nodes, from_block, min_last)}\n"
                f"  frame_diff={_frame_diff(nodes[0], node, ref.round_received())}"
            )


def _frame_diff(a, b, rr):
    """Which parts of two nodes' frames at round `rr` differ: per-position
    root mismatches (full canonical dicts) and event-list identity."""
    try:
        fa = a.core.hg.get_frame(rr)
        fb = b.core.hg.get_frame(rr)
    except Exception as e:  # noqa: BLE001
        return f"unavailable: {e}"
    ca, cb = fa.to_canonical(), fb.to_canonical()
    out = []
    ea = [e["Body"]["Index"] for e in ca["Events"]]
    eb = [e["Body"]["Index"] for e in cb["Events"]]
    if ca["Events"] != cb["Events"]:
        out.append(("events", ea, eb))
    for pos, (ra, rb) in enumerate(zip(ca["Roots"], cb["Roots"])):
        if ra != rb:
            out.append(("root", pos, ra, rb))
    return out


def _dump_blocks(nodes, lo, hi):
    """Post-mortem: per node (position, id), each block's (index,
    round_received, frame-hash prefix, tx count) over [lo, hi]."""
    out = []
    for p, n in enumerate(nodes):
        rows = []
        for i in range(lo, hi + 1):
            try:
                b = n.get_block(i)
                rows.append(
                    (i, b.round_received(), b.frame_hash().hex()[:8],
                     len(b.transactions()))
                )
            except Exception as e:  # noqa: BLE001
                rows.append((i, str(e)))
        out.append((p, n.id, rows))
    return out


def gossip(nodes, proxies, target_block, shutdown=True, timeout_s=30.0):
    run_nodes(nodes)
    try:
        bombard_and_wait(nodes, proxies, target_block, timeout_s)
    finally:
        if shutdown:
            shutdown_nodes(nodes)


def test_gossip_4_nodes():
    nodes, proxies = init_nodes(4)
    gossip(nodes, proxies, target_block=3)
    check_gossip(nodes, upto=3)


def test_missing_node_gossip():
    """Gossip must proceed with one node dark (reference:
    src/node/node_test.go:439-453)."""
    nodes, proxies = init_nodes(4)
    try:
        run_nodes(nodes[1:])
        bombard_and_wait(nodes[1:], proxies[1:], target_block=3)
        check_gossip(nodes[1:], upto=3)
    finally:
        shutdown_nodes(nodes[1:])
        nodes[0].shutdown()


def test_state_hashes_match():
    nodes, proxies = init_nodes(4)
    gossip(nodes, proxies, target_block=3)
    check_gossip(nodes, upto=3)
    # app state hashes at each block must agree
    for i in range(3 + 1):
        hashes = {n.get_block(i).state_hash() for n in nodes}
        assert len(hashes) == 1


def test_shutdown_stops_gossip():
    nodes, proxies = init_nodes(2)
    run_nodes(nodes)
    nodes[0].shutdown()
    time.sleep(0.1)
    nodes[1].shutdown()
    assert str(nodes[0].get_state()) == "Shutdown"
    assert str(nodes[1].get_state()) == "Shutdown"
