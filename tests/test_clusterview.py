"""Cluster health plane tests (ISSUE 20, babble_tpu/obs/clusterview.py,
docs/observability.md):

- digest federation mechanics: versioned-entry validation, newest-t-wins
  merge, own-addr exclusion, opaque unknown keys, MAX_FLEET bound;
- failure-kind classification and the contact ledger (silence
  accumulates, refusal and success clear);
- partition inference on the sim fabric: the partition_heal preset must
  trip `cluster.partition_suspected` with the exact ground-truth
  components on majority-side nodes (the isolated minority never
  self-suspects), emit `cluster.partition_healed` after the heal, and
  replay byte-identically across same-seed runs; lossy and crash plans
  must never trip (false-positive guard);
- the out-of-band piggyback contract: a cluster_health=False run commits
  the byte-identical digest of an enabled run (wire payloads unchanged
  when the "Cluster" key is empty, the Traces differential argument);
- determinism of result()["cluster_health"] / cluster_health_fingerprint
  for CPU-only and mixed CPU + queued-mesh clusters;
- the live TCP surfaces: GET /health/digest + GET /debug/cluster on a
  real Service over a gossiping cluster, the `babble-tpu status`
  renderer over that document, and the commit-frontier gauges serving
  digest, /stats and observatory from one source of truth.
"""

import json
import time
import urllib.request

import pytest

from babble_tpu.cli import render_status
from babble_tpu.obs import Observability, failure_kind
from babble_tpu.obs.clusterview import MAX_FLEET, MIN_SILENT_FAILS
from babble_tpu.service import Service
from babble_tpu.sim import SimCluster, SimClock, preset_plan, run_one

from test_node import (
    bombard_and_wait,
    init_nodes,
    run_nodes,
    shutdown_nodes,
)

# partition_heal preset geometry (sim/faults.py): minority {sim-0} cut
# from {sim-1, sim-2, sim-3} over virtual [1.0, 4.0)
PARTITION_START = 1.0
PARTITION_END = 4.0
GROUND_TRUTH = [["sim-0"], ["sim-1", "sim-2", "sim-3"]]


def _partition_records(cluster):
    """[(node_name, record_name, fields)] for every cluster.partition_*
    flight record across the cluster's live nodes."""
    out = []
    for sn in cluster.sns:
        if sn.node is None:
            continue
        for r in sn.node.obs.flightrec.to_json()["records"]:
            if r["name"].startswith("cluster.partition"):
                out.append((sn.name, r["name"], r["fields"], r["t"]))
    return out


# ----------------------------------------------------------------------
# unit: failure classification + contact ledger
# ----------------------------------------------------------------------

def test_failure_kind_classification():
    # silence: the far side never answered
    assert failure_kind("partitioned: sim-0 -/- sim-1") == "silence"
    assert failure_kind("dropped: sim-2 -> sim-0") == "silence"
    assert failure_kind("command timed out") == "silence"
    assert failure_kind(TimeoutError("connect timeout")) == "silence"
    # refusal: the path answered with an error — proves reachability
    assert failure_kind("peer down") == "refusal"
    assert failure_kind("node not ready") == "refusal"
    assert failure_kind(ConnectionRefusedError("refused")) == "refusal"
    assert failure_kind(None) == "refusal"


def _bound_observatory(clock, addr="n0", block=5, deadline=1.0):
    obs = Observability(clock=clock)
    cv = obs.clusterview
    cv.bind_local(
        addr, digest_fn=lambda: {"block": block, "round": 3},
        staleness_deadline=deadline,
    )
    return obs, cv


def _digest(addr, t, block, **extra):
    d = {"v": 1, "addr": addr, "t": t, "block": block}
    d.update(extra)
    return d


def test_absorb_validates_and_merges_newest_t_wins():
    clock = SimClock()
    _, cv = _bound_observatory(clock)
    # invalid entries: dropped wholesale (compat rule)
    cv.absorb([
        "not a dict",
        {"addr": "n1", "t": 1.0, "block": 2},          # no v
        _digest("n1", 1.0, 2, v=0),                     # v < 1
        {"v": 1, "t": 1.0, "block": 2},                 # no addr
        {"v": 1, "addr": "n1", "block": 2},             # no t
        {"v": 1, "addr": "n1", "t": 1.0},               # no block
        _digest("n0", 1.0, 2),                          # own addr
    ])
    assert set(cv.fleet()) == {"n0"}
    # valid entry lands; unknown keys ride opaquely; newest-t wins
    cv.absorb([_digest("n1", 1.0, 2, future_field="kept")])
    assert cv.fleet()["n1"]["future_field"] == "kept"
    cv.absorb([_digest("n1", 0.5, 9)])  # older t: ignored
    assert cv.fleet()["n1"]["block"] == 2
    cv.absorb([_digest("n1", 2.0, 3)])
    assert cv.fleet()["n1"]["block"] == 3
    # a v=2 digest from a newer node is accepted field-wise
    cv.absorb([_digest("n2", 1.0, 7, v=2)])
    assert cv.fleet()["n2"]["v"] == 2


def test_absorb_bounds_fleet_table():
    clock = SimClock()
    _, cv = _bound_observatory(clock)
    cv.fleet()  # stores the own digest, as every gossip exchange does
    cv.absorb([_digest(f"p{i}", 1.0, i) for i in range(MAX_FLEET + 10)])
    assert len(cv.fleet()) == MAX_FLEET  # own + MAX_FLEET-1 others
    # known origins still update when the table is full
    survivor = sorted(a for a in cv.fleet() if a != "n0")[0]
    cv.absorb([_digest(survivor, 2.0, 99)])
    assert cv.fleet()[survivor]["block"] == 99


def test_note_contact_refusal_and_success_clear_silence():
    clock = SimClock()
    _, cv = _bound_observatory(clock)
    for _ in range(MIN_SILENT_FAILS):
        cv.note_contact("n1", False, t_start=clock.now, err="timed out")
    c = cv._contacts["n1"]
    assert c.silent_since is not None
    assert c.silent_fails == MIN_SILENT_FAILS
    # a refusal proves the path answers: silence state resets
    cv.note_contact("n1", False, err="peer down")
    assert c.silent_since is None and c.silent_fails == 0
    # rebuild silence, then a success clears it and stamps last_ok
    cv.note_contact("n1", False, t_start=clock.now, err="timed out")
    cv.note_contact("n1", True)
    assert c.silent_since is None and c.last_ok == clock.now


def test_suspicion_state_machine_edges():
    """Unit-level rising/falling edge: a silent peer whose digest also
    went stale, plus fresh counter-evidence postdating the silence,
    trips suspicion; the silent peer answering heals it."""
    clock = SimClock()
    obs, cv = _bound_observatory(clock, deadline=1.0)
    cv.absorb([_digest("n1", 0.0, 1), _digest("n2", 0.0, 1)])
    # n1 goes silent at t=0.5; n2 keeps answering (fresh digest + ok)
    clock.now = 0.5
    cv.note_contact("n1", False, t_start=0.5, err="timed out")
    clock.now = 1.0
    cv.note_contact("n1", False, t_start=0.9, err="timed out")
    clock.now = 1.6  # silence span 1.1 >= deadline; n1 digest age 1.6
    cv.absorb([_digest("n2", 1.5, 2)])
    cv.note_contact("n2", True)
    cv.check()
    s = cv.suspicion()
    assert s["suspected"] is True
    assert s["components"] == [["n0", "n2"], ["n1"]]
    assert cv.series_value("babble_cluster_partition_suspected") == 1.0
    names = [
        r["name"] for r in obs.flightrec.to_json()["records"]
        if r["name"].startswith("cluster.")
    ]
    assert names == ["cluster.partition_suspected"]
    # falling edge: the silent peer answers again
    cv.note_contact("n1", True)
    cv.check()
    assert cv.suspicion()["suspected"] is False
    names = [
        r["name"] for r in obs.flightrec.to_json()["records"]
        if r["name"].startswith("cluster.")
    ]
    assert names == [
        "cluster.partition_suspected", "cluster.partition_healed",
    ]


def test_no_suspicion_without_fresh_counter_evidence():
    """A fully isolated node sees every path silent and NO fresh peers
    — it must never self-diagnose a partition (that is the watchdog's
    stall, not a partition verdict)."""
    clock = SimClock()
    _, cv = _bound_observatory(clock, deadline=1.0)
    for peer in ("n1", "n2"):
        cv.note_contact(peer, False, t_start=0.0, err="timed out")
        cv.note_contact(peer, False, t_start=0.1, err="timed out")
    clock.now = 2.0
    cv.check()
    assert cv.suspicion()["suspected"] is False


# ----------------------------------------------------------------------
# sim: partition inference end to end
# ----------------------------------------------------------------------

def test_partition_heal_trips_exact_components_then_heals():
    cluster = SimCluster(
        n=4, seed=0, plan=preset_plan("partition_heal", 4),
        cluster_staleness=1.5,
    )
    try:
        res = cluster.run(until=30.0, target_block=8)
        assert res["net"]["severed"] > 0
        recs = _partition_records(cluster)
    finally:
        cluster.shutdown()
    suspects = [r for r in recs if r[1] == "cluster.partition_suspected"]
    heals = [r for r in recs if r[1] == "cluster.partition_healed"]
    assert suspects, "no node suspected the partition"
    by_node = {r[0] for r in suspects}
    # the isolated minority (sim-0 = node0) must never self-suspect
    assert "node0" not in by_node
    for _node, _name, fields, t in suspects:
        assert json.loads(fields["components"]) == GROUND_TRUTH
        # detected while the partition was live, not retroactively
        assert PARTITION_START < t < PARTITION_END
    # every suspicion episode healed once the partition lifted
    assert {r[0] for r in heals} == by_node
    for _node, _name, _fields, t in heals:
        assert t >= PARTITION_END


def test_partition_inference_byte_identical_same_seed():
    def one():
        cluster = SimCluster(
            n=4, seed=0, plan=preset_plan("partition_heal", 4),
            cluster_staleness=1.5,
        )
        try:
            res = cluster.run(until=30.0, target_block=8)
            return (
                json.dumps(_partition_records(cluster), sort_keys=True),
                json.dumps(res["cluster_health"], sort_keys=True),
                res["cluster_health_fingerprint"],
            )
        finally:
            cluster.shutdown()

    a, b = one(), one()
    assert a[0] == b[0]  # every partition record, byte for byte
    assert a[1] == b[1]
    assert a[2] == b[2]


@pytest.mark.parametrize("plan_name", ["lossy", "crash_restart"])
def test_lossy_and_crash_plans_never_trip(plan_name):
    """False-positive guard: loss leaves the peer's digest flowing via
    relays, a crash fails with refusals — neither is a partition."""
    for seed in (0, 1):
        cluster = SimCluster(
            n=4, seed=seed, plan=preset_plan(plan_name, 4),
            cluster_staleness=1.5,
        )
        try:
            cluster.run(until=30.0, target_block=6)
            recs = _partition_records(cluster)
        finally:
            cluster.shutdown()
        assert recs == [], f"{plan_name} seed {seed} tripped: {recs}"


# ----------------------------------------------------------------------
# sim: piggyback differential + determinism fingerprint
# ----------------------------------------------------------------------

def test_disabling_health_plane_leaves_commit_digest_unchanged():
    """The Traces argument, applied to the "Cluster" wire key: digests
    ride out-of-band, so a health-plane-disabled cluster must commit the
    byte-identical history of an enabled one for the same seed."""
    a = run_one(5, plan="clean", n=4, until=None, target_block=3,
                cluster_health=True)
    b = run_one(5, plan="clean", n=4, until=None, target_block=3,
                cluster_health=False)
    assert a["ok"] and b["ok"], (a["error"], b["error"])
    assert a["digest"] == b["digest"]
    assert a["events_run"] == b["events_run"]
    assert a["virtual_time"] == b["virtual_time"]
    # the disabled run reports the plane as absent, not as zeroes
    assert a["cluster_health"]["nodes"]
    assert b["cluster_health"]["nodes"] == {}


def test_cluster_health_deterministic_cpu_and_mixed_mesh():
    cases = {
        "cpu": dict(plan="clean", n=4, until=None, target_block=3),
        "mixed": dict(
            plan="clean", n=4, backend=("cpu", "cpu", "tpu", "tpu"),
            mesh_devices=2, dispatch_queue_depth=4,
            dispatch_batch_deadline=0.2, until=None, target_block=2,
        ),
    }
    for label, kwargs in cases.items():
        a = run_one(7, **kwargs)
        b = run_one(7, **kwargs)
        assert a["ok"] and b["ok"], (label, a["error"], b["error"])
        assert (
            a["cluster_health_fingerprint"]
            == b["cluster_health_fingerprint"]
        ), label
        assert json.dumps(a["cluster_health"], sort_keys=True) == (
            json.dumps(b["cluster_health"], sort_keys=True)
        ), label
        summary = a["cluster_health"]["summary"]
        assert summary["min_frontier_agreement"] == 1.0, label
        assert summary["partitions_suspected"] == 0, label


def test_sweep_summary_carries_cluster_health_row():
    from babble_tpu.sim import run_sweep

    summary = run_sweep(range(2), plan="clean", n=4, until=None,
                        target_block=2)
    assert summary["failed"] == 0
    row = summary["cluster_health"]
    assert row["min_frontier_agreement"] == 1.0
    assert row["partitions_suspected"] == 0
    assert row["suspected_components"] == []
    assert row["max_commit_skew_blocks"] >= 0.0


# ----------------------------------------------------------------------
# live TCP: /health/digest, /debug/cluster, the status renderer
# ----------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def test_live_service_cluster_endpoints_and_renderer():
    nodes, proxies = init_nodes(3)
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        run_nodes(nodes)
        svc.serve()
        base = f"http://{svc.local_addr()}"
        bombard_and_wait(nodes, proxies, target_block=1)
        # let the frontier settle (no new txs -> no new blocks) so the
        # digest/gauge/stats triple is read from a stable index
        import time

        idx = -2
        for _ in range(200):
            cur = nodes[0].core.get_last_block_index()
            if cur == idx:
                break
            idx = cur
            time.sleep(0.05)

        digest = _get(base + "/health/digest")
        assert digest["addr"] == nodes[0].local_addr
        assert digest["v"] >= 1
        assert isinstance(digest["block"], int) and digest["block"] >= 1
        assert digest["rung"] in (
            "cpu", "cpu_fallback", "one_shot", "live", "mesh",
            "mesh_queued",
        )

        # one source of truth: digest block == frontier gauge == /stats
        stats = _get(base + "/stats")
        g = nodes[0].obs.registry.get("babble_commit_frontier_block")
        assert int(stats["commit_frontier_block"]) == digest["block"]
        assert int(g.value()) == digest["block"]
        assert int(stats["commit_frontier_round"]) == digest["round"]

        # gossip has run to a committed block, so the fleet table
        # federates promptly — but digest piggyback rides on exchanges
        # node 0 happens to make, so poll briefly rather than snapshot
        doc = _get(base + "/debug/cluster")
        for _ in range(200):
            if len(doc["fleet"]) == 3:
                break
            time.sleep(0.05)
            doc = _get(base + "/debug/cluster")
        assert doc["enabled"] is True
        assert doc["addr"] == nodes[0].local_addr
        assert len(doc["fleet"]) == 3
        assert doc["suspicion"]["suspected"] is False
        assert (
            doc["derived"]["babble_cluster_frontier_agreement"] == 1.0
        )

        out = render_status(doc)
        assert "babble-tpu cluster status" in out
        assert nodes[0].local_addr in out
        assert "partition: none suspected" in out
    finally:
        svc.shutdown()
        shutdown_nodes(nodes)


def test_render_status_flags_disagreement_and_partition():
    doc = {
        "addr": "a:1",
        "fleet": {
            "a:1": {"block": 5, "round": 7, "rung": "cpu", "undecided": 0,
                    "txs": 0, "sigs": 0, "ingress": 0, "forks": 0,
                    "age": 0.0},
            "b:2": {"block": 3, "round": 6, "rung": "mesh_queued",
                    "undecided": 2, "txs": 1, "sigs": 0, "ingress": 4,
                    "forks": 0, "age": 1.2},
        },
        "derived": {
            "babble_cluster_commit_skew_blocks": 2.0,
            "babble_cluster_round_skew": 1.0,
            "babble_cluster_frontier_agreement": 0.5,
            "babble_cluster_fame_latency_rounds": 2.0,
        },
        "suspicion": {"suspected": True,
                      "components": [["a:1"], ["b:2"]]},
    }
    out = render_status(doc)
    assert "2 nodes" in out
    assert "commit skew: 2 blocks" in out
    assert "FRONTIER DISAGREEMENT" in out
    assert "PARTITION SUSPECTED" in out
    assert "mesh_queued" in out


# ----------------------------------------------------------------------
# watchdog satellite: local lag vs cluster-wide stall
# ----------------------------------------------------------------------

def test_watchdog_cluster_context_classifies_lag():
    clock = SimClock()
    obs, cv = _bound_observatory(clock, block=3)
    from babble_tpu.node.watchdog import LivenessWatchdog

    wd = LivenessWatchdog(
        clock, obs, __import__("logging").getLogger("t"),
        deadline=1.0, round_fn=lambda: 1, pending_fn=lambda: 1,
    )
    # no observatory bound: neutral context
    assert wd._cluster_context() == (0.0, [])
    wd.clusterview = cv
    # peers ahead of our frontier -> local lag, named peers
    cv.absorb([_digest("n1", 0.1, 9), _digest("n2", 0.1, 3)])
    skew, ahead = wd._cluster_context()
    assert skew == 6.0
    assert ahead == ["n1"]
