"""Concurrency certification tests (ISSUE 12): the dynamic lockset race
detector, the lock-order analyzer, the static guarded-by inference pass,
the dead-waiver audit, baseline hygiene, and the metrics-registry
get-or-create races the certification exists to prevent."""

import json
import os
import sys
import textwrap
import threading
from pathlib import Path

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from babble_tpu.analysis.core import SourceFile  # noqa: E402
from babble_tpu.analysis.lockruntime import (  # noqa: E402
    DEFAULT_MODULES,
    InstrumentedLock,
    RaceCertificationError,
    active_certifier,
    certify,
    run_race_certification,
)
from babble_tpu.analysis.locks import check_locks  # noqa: E402
from babble_tpu.analysis.races import (  # noqa: E402
    RULE_DEAD_WAIVER,
    RULE_MISMATCH,
    RULE_UNANNOTATED,
    check_dead_waivers,
    check_races,
)
from babble_tpu.analysis.runner import run_lint  # noqa: E402
from babble_tpu.obs.flightrec import FlightRecorder  # noqa: E402
from babble_tpu.obs.metrics import MAX_LABEL_SETS, MetricsRegistry  # noqa: E402

import fixtures_races  # noqa: E402
from fixtures_races import InvertedPair, UnguardedBox  # noqa: E402

REPO_ROOT = str(Path(__file__).resolve().parents[1])
FIXTURES = ("fixtures_races",)


def _certify_fixtures(**kw):
    return certify(modules=FIXTURES, global_locks=(), **kw)


# ---------------------------------------------------------------------------
# dynamic lockset (Eraser) detection
# ---------------------------------------------------------------------------


def test_dynamic_detector_flags_seeded_unguarded_write():
    """The seeded defect MUST be flagged: one locked cross-thread access
    establishes the candidate lockset, the unguarded access empties it."""
    with _certify_fixtures() as cert:
        box = UnguardedBox()
        t = threading.Thread(target=box.locked_bump)
        t.start()
        t.join()
        box.unguarded_bump()  # main thread, no lock held
        races = [f for f in cert.findings if f["kind"] == "race.candidate"]
        assert races, "seeded unguarded write was not flagged"
        assert races[0]["cls"] == "UnguardedBox"
        assert races[0]["field"] == "_count"
        assert races[0]["lock"] == "_lock"


def test_dynamic_detector_is_quiet_on_disciplined_access():
    with _certify_fixtures() as cert:
        box = UnguardedBox()
        threads = [
            threading.Thread(target=box.locked_bump) for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert box.snapshot() == 4
        assert cert.findings == []
    assert cert.findings == []  # scope exit added no cycle findings


def test_dynamic_detector_deduplicates_per_class_field():
    with _certify_fixtures() as cert:
        box = UnguardedBox()
        t = threading.Thread(target=box.locked_bump)
        t.start()
        t.join()
        for _ in range(5):
            box.unguarded_bump()
        races = [f for f in cert.findings if f["kind"] == "race.candidate"]
        assert len(races) == 1


def test_single_thread_use_never_reports():
    """Eraser's exclusive state: unlocked single-thread access is fine."""
    with _certify_fixtures() as cert:
        box = UnguardedBox()
        for _ in range(10):
            box.unguarded_bump()
        assert cert.findings == []


def test_statically_waived_fields_are_skipped_dynamically(tmp_path):
    """A field with an `# unguarded-ok:` site is certified statically
    only: the dynamic pass must not re-flag what the waiver excused."""
    mod = tmp_path / "waived_fixture.py"
    mod.write_text(textwrap.dedent("""\
        import threading


        class WaivedBox:
            def __init__(self):
                self._lock = threading.Lock()
                self._flag = False  # guarded-by: _lock

            def set_locked(self):
                with self._lock:
                    self._flag = True

            def probe(self):
                # unguarded-ok: racy boolean probe; staleness tolerated
                return self._flag
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        with certify(modules=("waived_fixture",), global_locks=()) as cert:
            import waived_fixture

            box = waived_fixture.WaivedBox()
            t = threading.Thread(target=box.set_locked)
            t.start()
            t.join()
            for _ in range(3):
                box.probe()
            box._flag = False  # even a raw write stays untracked
            assert cert.findings == []
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("waived_fixture", None)


# ---------------------------------------------------------------------------
# lock-order (deadlock) analysis
# ---------------------------------------------------------------------------


def test_lock_order_analyzer_flags_seeded_inversion():
    with _certify_fixtures() as cert:
        pair = InvertedPair()
        pair.ab()
        t = threading.Thread(target=pair.ba)
        t.start()
        t.join()
        new = cert.check_lock_order()
        assert new, "seeded AB/BA inversion was not flagged"
        assert new[0]["kind"] == "lockorder.cycle"
        assert "InvertedPair._a" in new[0]["cycle"]
        assert "InvertedPair._b" in new[0]["cycle"]
        # idempotent: re-checking does not duplicate the cycle
        assert cert.check_lock_order() == []


def test_lock_order_consistent_nesting_is_acyclic():
    with _certify_fixtures() as cert:
        pair = InvertedPair()
        for _ in range(3):
            pair.ab()
        assert cert.check_lock_order() == []
        edges = cert.lock_order_edges()
        assert edges == {"InvertedPair._a": ["InvertedPair._b"]}


def test_lock_order_ignores_same_role_different_instances():
    """Nesting the same lock ROLE across two instances must not read as
    a self-cycle (documented limitation: per-instance ordering)."""
    with _certify_fixtures() as cert:
        a, b = UnguardedBox(), UnguardedBox()
        with a._lock:
            with b._lock:
                pass
        assert cert.check_lock_order() == []
        assert cert.lock_order_edges() == {}


def test_strict_scope_raises_on_findings():
    with pytest.raises(RaceCertificationError, match="lockorder.cycle"):
        with _certify_fixtures(strict=True):
            pair = InvertedPair()
            pair.ab()
            pair.ba()


# ---------------------------------------------------------------------------
# instrumentation lifecycle
# ---------------------------------------------------------------------------


def test_certify_patches_are_restored_on_exit():
    assert "__setattr__" not in UnguardedBox.__dict__
    with _certify_fixtures() as cert:
        assert "__setattr__" in UnguardedBox.__dict__
        assert "__getattribute__" in UnguardedBox.__dict__
        assert active_certifier() is cert
        box = UnguardedBox()
        assert isinstance(box._lock, InstrumentedLock)
    assert "__setattr__" not in UnguardedBox.__dict__
    assert "__getattribute__" not in UnguardedBox.__dict__
    # `is not cert`, not `is None`: under BABBLE_RACE_CERTIFY the
    # session-wide scope is still active underneath
    assert active_certifier() is not cert
    # objects born after the scope get plain locks again
    assert not isinstance(UnguardedBox()._lock, InstrumentedLock)


def test_certify_scopes_nest():
    with _certify_fixtures() as outer:
        with _certify_fixtures() as inner:
            assert active_certifier() is inner
        assert active_certifier() is outer


def test_module_level_locks_are_wrapped_and_restored():
    import babble_tpu.tpu.dispatch as dispatch

    raw = dispatch._MESH_EXEC_LOCK
    with certify(modules=("babble_tpu.tpu.dispatch",)):
        assert isinstance(dispatch._MESH_EXEC_LOCK, InstrumentedLock)
    assert dispatch._MESH_EXEC_LOCK is raw


def test_pre_scope_instances_are_ignored_not_misreported():
    """Objects built before certify() carry raw locks the certifier
    cannot see; their accesses must be skipped, not reported."""
    box = UnguardedBox()
    with _certify_fixtures() as cert:
        t = threading.Thread(target=box.locked_bump)
        t.start()
        t.join()
        box.unguarded_bump()
        assert cert.findings == []


def test_findings_feed_flight_recorder():
    rec = FlightRecorder(node_id=7)
    with _certify_fixtures(recorders=(rec,)) as cert:
        box = UnguardedBox()
        t = threading.Thread(target=box.locked_bump)
        t.start()
        t.join()
        box.unguarded_bump()
        pair = InvertedPair()
        pair.ab()
        pair.ba()
        cert.check_lock_order()
    names = [r.name for r in rec.records()]
    assert "race.candidate" in names
    assert "lockorder.cycle" in names
    race = next(r for r in rec.records() if r.name == "race.candidate")
    # deterministic fields only: names, never thread identity
    assert race.fields == {
        "cls": "UnguardedBox", "field": "_count",
        "lock": "_lock", "access": "read",
    }


# ---------------------------------------------------------------------------
# static inference on the seeded fixtures + the real tree
# ---------------------------------------------------------------------------


def _fixture_sf():
    path = fixtures_races.__file__
    return SourceFile.parse(path, "tests/fixtures_races.py")


def test_inference_flags_seeded_unannotated_field():
    findings = list(check_races(_fixture_sf()))
    unannotated = [f for f in findings if f.rule == RULE_UNANNOTATED]
    assert unannotated, "seeded unannotated field was not flagged"
    assert any("_tally" in f.message for f in unannotated)


def test_lock_checker_flags_seeded_unguarded_write():
    findings = list(check_locks(_fixture_sf()))
    assert any(
        f.rule == "lock-guarded-by" and "_count" in f.message
        for f in findings
    ), "seeded unguarded write was not flagged statically"


def test_inference_flags_annotation_that_lies(tmp_path):
    findings = []
    src = textwrap.dedent("""\
        import threading


        class Liar:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0  # guarded-by: _a

            def bump(self):
                with self._b:
                    self._x += 1
    """)
    p = tmp_path / "liar.py"
    p.write_text(src)
    sf = SourceFile.parse(str(p), "liar.py")
    findings = list(check_races(sf))
    mism = [f for f in findings if f.rule == RULE_MISMATCH]
    assert mism and "_b" in mism[0].message


def test_default_modules_cover_the_lock_scope():
    """Every module the dynamic pass certifies must import cleanly and be
    real; the lock-convention trio from the issue is explicitly in."""
    assert "babble_tpu.tpu.dispatch" in DEFAULT_MODULES
    assert "babble_tpu.node.node" in DEFAULT_MODULES
    assert "babble_tpu.obs.metrics" in DEFAULT_MODULES
    # ISSUE 17: the packed-layout module rides every engine rung the two
    # lines above certify, so it joins the race-certification scope too
    assert "babble_tpu.tpu.packed" in DEFAULT_MODULES
    import importlib

    for mod in DEFAULT_MODULES:
        assert importlib.import_module(mod) is not None


def test_real_tree_dynamic_certification_is_clean():
    """Acceptance: a seeded sim under full instrumentation produces zero
    race candidates and an acyclic lock graph (the 50-seed sweep runs in
    `make race`; one seed here keeps tier-1 honest and fast)."""
    lines = []
    rc = run_race_certification(
        seeds=1, target_block=3, until=60.0,
        artifact_dir="/tmp/babble-race-test", out=lines.append,
    )
    assert rc == 0, "\n".join(lines)
    assert any("0 cycle(s)" in ln for ln in lines)


# ---------------------------------------------------------------------------
# dead-waiver audit (satellite: lint-dead-waiver)
# ---------------------------------------------------------------------------


def test_dead_waiver_flags_unused_suppression(tmp_path):
    p = tmp_path / "dead.py"
    p.write_text(textwrap.dedent("""\
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    self.value += 1

            def helper(self):
                # unguarded-ok: stale excuse for nothing
                return 42
    """))
    sf = SourceFile.parse(str(p), "dead.py")
    list(check_locks(sf))
    list(check_races(sf))
    dead = list(check_dead_waivers(sf, lock_scope=True))
    # the guarded-by decl is live (bump uses it); the unguarded-ok that
    # excuses nothing is dead
    assert len(dead) == 1
    assert dead[0].rule == RULE_DEAD_WAIVER
    assert "unguarded-ok" in dead[0].message


def test_dead_waiver_flags_guarded_by_outside_scope(tmp_path):
    p = tmp_path / "outside.py"
    p.write_text("x = 1  # guarded-by: _lock\n")
    sf = SourceFile.parse(str(p), "outside.py")
    dead = list(check_dead_waivers(sf, lock_scope=False))
    assert len(dead) == 1 and "outside the" in dead[0].message


# ---------------------------------------------------------------------------
# baseline hygiene (satellite: sorted + deduplicated)
# ---------------------------------------------------------------------------


def _hygiene_tree(tmp_path):
    src = tmp_path / "babble_tpu" / "node" / "fx.py"
    src.parent.mkdir(parents=True)
    src.write_text(textwrap.dedent("""\
        import time


        def f():
            return time.monotonic()


        def g():
            return time.time()
    """))
    baseline = tmp_path / "baseline.json"
    run_lint(str(tmp_path), baseline_path=str(baseline),
             update_baseline=True)
    return baseline


def test_baseline_must_be_sorted(tmp_path):
    baseline = _hygiene_tree(tmp_path)
    doc = json.loads(baseline.read_text())
    assert len(doc["findings"]) == 2
    assert run_lint(str(tmp_path), baseline_path=str(baseline)).errors == []

    doc["findings"].reverse()
    baseline.write_text(json.dumps(doc))
    result = run_lint(str(tmp_path), baseline_path=str(baseline))
    assert any("not sorted" in e for e in result.errors)


def test_baseline_must_be_deduplicated(tmp_path):
    baseline = _hygiene_tree(tmp_path)
    doc = json.loads(baseline.read_text())
    doc["findings"] = sorted(
        doc["findings"] + [doc["findings"][0]],
        key=lambda e: (e["rule"], e["path"], e["symbol"], e["text"]),
    )
    baseline.write_text(json.dumps(doc))
    result = run_lint(str(tmp_path), baseline_path=str(baseline))
    assert any("duplicate" in e for e in result.errors)


# ---------------------------------------------------------------------------
# metrics registry under concurrent first-callers (satellite 2)
# ---------------------------------------------------------------------------


def test_registry_get_or_create_is_atomic_under_hammer():
    reg = MetricsRegistry()
    n_threads = 16
    barrier = threading.Barrier(n_threads)
    got = []
    errors = []

    def worker():
        barrier.wait()
        try:
            for i in range(50):
                c = reg.counter("hammer_total", "t", labels=("k",))
                c.labels(k=str(i % 4)).inc()
                got.append(c)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    # every concurrent first-caller got the SAME metric object
    assert len({id(c) for c in got}) == 1
    snap = reg.snapshot()["hammer_total"]["series"]
    assert sum(snap.values()) == n_threads * 50


def test_label_cardinality_bounded_under_concurrent_novel_labels():
    reg = MetricsRegistry()
    c = reg.counter("cardinality_total", "t", labels=("k",))
    n_threads = 8
    barrier = threading.Barrier(n_threads)

    def worker(base):
        barrier.wait()
        for i in range(MAX_LABEL_SETS):
            c.labels(k=f"{base}-{i}").inc()

    threads = [
        threading.Thread(target=worker, args=(b,)) for b in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # admission is atomic with insertion: exactly MAX_LABEL_SETS real
    # series plus the single `other` overflow series, even when every
    # caller is a novel-label first-caller
    assert len(c._series) == MAX_LABEL_SETS + 1
    snap = reg.snapshot()["cardinality_total"]["series"]
    assert "other" in snap
    assert sum(snap.values()) == n_threads * MAX_LABEL_SETS


def test_registry_hammer_is_race_certified():
    """The satellite-2 fix under the tentpole's microscope: the same
    hammer, instrumented — no candidates, no cycles."""
    with certify(modules=("babble_tpu.obs.metrics",),
                 global_locks=()) as cert:
        reg = MetricsRegistry()
        barrier = threading.Barrier(8)

        def worker():
            barrier.wait()
            for i in range(30):
                reg.counter("certified_total", "t", labels=("k",)).labels(
                    k=str(i)
                ).inc()

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert cert.findings == []
        assert cert.check_lock_order() == []
