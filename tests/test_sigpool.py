"""Block-signature pool tests: signatures ride in events, accumulate in
the sig pool at insert, and ProcessSigPool attaches the valid ones to
stored blocks (reference: src/hashgraph/hashgraph_test.go:954-1109
TestInsertEventsWithBlockSignatures + initBlockHashgraph)."""

from babble_tpu import crypto
from babble_tpu.hashgraph import (
    Block,
    BlockSignature,
    Event,
    Hashgraph,
    InmemStore,
    root_self_parent,
)

from dsl import CACHE_SIZE, Play, init_hashgraph_nodes, play_events


def init_block_hashgraph():
    """Three root-attached events + a manually stored block 0
    (reference: hashgraph_test.go:954-978)."""
    nodes, index, ordered, participants = init_hashgraph_nodes(3)
    for i, peer in enumerate(participants.to_peer_slice()):
        ev = Event(
            parents=[root_self_parent(peer.id), ""],
            creator=nodes[i].pub, index=0,
        )
        nodes[i].sign_and_add_event(ev, f"e{i}", index, ordered)

    h = Hashgraph(participants, InmemStore(participants, CACHE_SIZE))
    block = Block(0, 1, b"framehash", [b"block tx"])
    h.store.set_block(block)
    for ev in ordered:
        h.insert_event(ev, True)
    return h, nodes, index, ordered


def test_insert_events_with_block_signatures():
    h, nodes, index, ordered = init_block_hashgraph()
    block = h.store.get_block(0)
    block_sigs = [block.sign(n.key) for n in nodes]

    # --- valid signatures ride in events and attach to block 0 ----------
    plays = [
        Play(1, 1, "e1", "e0", "e10", None, [block_sigs[1]]),
        Play(2, 1, "e2", "", "s20", None, [block_sigs[2]]),
        Play(0, 1, "e0", "", "s00", None, [block_sigs[0]]),
    ]
    play_events(plays, nodes, index, ordered)
    for ev in ordered[3:]:
        h.insert_event(ev, True)

    assert len(h.sig_pool) == 3
    h.process_sig_pool()
    assert len(h.store.get_block(0).signatures) == 3
    assert len(h.sig_pool) == 0

    # --- signature of an unknown block: event inserted, sig kept pending
    block1 = Block(1, 2, b"framehash", [])
    sig1 = block1.sign(nodes[2].key)
    unknown = BlockSignature(
        validator=nodes[2].pub, index=1, signature=sig1.signature
    )
    p = Play(2, 2, "s20", "e10", "e21", None, [unknown])
    play_events([p], nodes, index, ordered)
    h.insert_event(ordered[-1], True)
    h.store.get_event(index["e21"])  # recorded
    h.process_sig_pool()
    # the block is unknown, so the signature stays pending for later
    # (in the per-index backlog: future-block signatures cost nothing
    # per pass until their block exists)
    assert h.pending_signatures() == 1
    assert len(h._sig_backlog.get(1, [])) == 1
    assert len(h.store.get_block(0).signatures) == 3

    # --- signature from a non-participant validator: ignored ------------
    bad_key = crypto.generate_key()
    bad_sig = h.store.get_block(0).sign(bad_key)
    p = Play(0, 2, "s00", "e21", "e02", None, [bad_sig])
    play_events([p], nodes, index, ordered)
    h.insert_event(ordered[-1], True)
    h.store.get_event(index["e02"])  # recorded
    h.process_sig_pool()
    assert len(h.store.get_block(0).signatures) == 3

    # --- tampered signature from a real participant: rejected -----------
    forged = BlockSignature(
        validator=nodes[1].pub, index=0,
        signature=block_sigs[0].signature,  # node0's sig, node1's identity
    )
    h.sig_pool.append(forged)
    h.process_sig_pool()
    block0 = h.store.get_block(0)
    assert len(block0.signatures) == 3
    for n in nodes:
        assert block0.verify(block0.get_signature(
            "0x" + n.pub.hex().upper()
        ))


def test_sig_backlog_bounded():
    """The per-block signature backlog is bounded two ways: buckets past
    the horizon above the committed height are dropped outright, and even
    within the horizon the farthest-future buckets are evicted beyond a
    hard bucket cap (a byzantine peer flooding fictitious block indices
    must not grow memory without bound). Nearest-future buckets survive —
    they are the next to attach and advance the anchor."""
    h, nodes, index, ordered = init_block_hashgraph()
    # shrink the bounds so the test exercises both evictions cheaply
    h.SIG_BACKLOG_HORIZON = 100
    h.SIG_BACKLOG_MAX_BUCKETS = 10

    future = Block(1, 2, b"framehash", [])
    beyond_horizon = future.sign(nodes[0].key)
    beyond_horizon.index = 500  # last_block=0, horizon=100: evicted
    h.sig_pool.append(beyond_horizon)
    for i in range(2, 52):  # 50 buckets inside the horizon
        bs = future.sign(nodes[0].key)
        bs.index = i
        h.sig_pool.append(bs)

    h.process_sig_pool()

    assert 500 not in h._sig_backlog
    assert len(h._sig_backlog) == 10
    # eviction removed the FARTHEST-future buckets, kept the nearest
    assert min(h._sig_backlog) == 2
    assert max(h._sig_backlog) == 11
