"""Peers tests (reference: src/peers/peer_test.go, json_peers_test.go)."""

from babble_tpu import crypto
from babble_tpu.common import hash32
from babble_tpu.peers import JSONPeers, Peer, Peers, exclude_peer


def _make_peer(addr="addr") -> Peer:
    key = crypto.generate_key()
    pub_hex = "0x" + crypto.pub_key_bytes(key).hex().upper()
    return Peer(net_addr=addr, pub_key_hex=pub_hex)


def test_peer_id_is_fnv_of_pubkey():
    p = _make_peer()
    assert p.id == hash32(p.pub_key_bytes())


def test_peers_sorted_by_id():
    ps = [_make_peer(f"addr{i}") for i in range(5)]
    peers = Peers.from_slice(ps)
    ids = peers.to_id_slice()
    assert ids == sorted(ids)
    assert len(peers) == 5


def test_peers_add_remove():
    peers = Peers.from_slice([_make_peer("a"), _make_peer("b")])
    extra = _make_peer("c")
    peers.add_peer(extra)
    assert len(peers) == 3
    peers.remove_peer_by_pub_key(extra.pub_key_hex)
    assert len(peers) == 2
    assert extra.pub_key_hex not in peers.by_pub_key


def test_exclude_peer():
    ps = [_make_peer("a"), _make_peer("b"), _make_peer("c")]
    idx, rest = exclude_peer(ps, "b")
    assert idx == 1
    assert [p.net_addr for p in rest] == ["a", "c"]


def test_json_peers_roundtrip(tmp_path):
    store = JSONPeers(str(tmp_path))
    ps = [_make_peer(f"addr{i}") for i in range(3)]
    store.set_peers(ps)
    loaded = store.peers()
    assert len(loaded) == 3
    assert set(loaded.by_pub_key.keys()) == {p.pub_key_hex for p in ps}
