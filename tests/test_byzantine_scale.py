"""BASELINE config #4: byzantine-scale differential — 256 validators with
an adversarial third (withheld-then-flushed chains, equivocation attempts,
Zipf-skewed fan-out), host engine vs device pipeline bit-exact.

What "adversarial" can and cannot mean at this scale, per the voting rule
(reference: src/hashgraph/hashgraph.go:859-947; here hashgraph.py
decide_fame): the coin branch fires only when a fame vote survives
``diff % n_participants == 0`` voting rounds undecided — at n=256 that
means 256 consecutive undecided ballots, which no gossip DAG reaches (each
extra ballot requires a full extra round of witnesses). Coin rounds are a
SMALL-n phenomenon by construction; they are pinned at n=4 by the funky
fixtures (tests/test_adversarial.py, test_tpu_differential.py). The
scale-version of "contested fame" is a fame decision that misses the first
ballot (depth >= 3, i.e. split votes forced extra voting rounds) — counted
by Hashgraph.max_fame_depth and asserted here.
"""

import numpy as np

from babble_tpu.hashgraph import Event, root_self_parent

from dsl import init_hashgraph_nodes, create_hashgraph
from test_tpu_differential import assert_equivalent


def build_byzantine_hashgraph(n=256, e_count=4096, seed=17, zipf_a=1.05,
                              withhold_span=24):
    """Gossip-shaped DAG through the HOST insert path with an adversarial
    third:

    - the first f = (n-1)//3 validators are byzantine: they run
      withhold/flush cycles — during a withhold span their new events are
      invisible to honest partner choice (nobody references their head,
      and their own other-parents go stale), then the chain is flushed
      (an honest validator references the hidden head) all at once.
      Withholding is staggered (at most n//8 validators hidden at once):
      if the full third hides simultaneously, the visible validator set
      drops below the supermajority and rounds stop advancing entirely —
      a liveness loss, which is the ATTACK WORKING, but a differential
      over a DAG with no fame decisions tests nothing;
    - honest fan-out is Zipf-skewed (config #3's heavy-tail gossip);
    - one byzantine validator attempts an equivocation mid-build: a second
      signed event on an already-used self-parent, which the hashgraph
      must reject at insert (fork guard).

    Returns (hg, n_rejected_forks)."""
    rng = np.random.default_rng(seed)
    f = (n - 1) // 3
    nodes, index, ordered, participants = init_hashgraph_nodes(n)

    heads = [""] * n          # event hash of each validator's head
    visible_head = [""] * n   # what honest partner choice sees
    next_index = [0] * n
    withholding = [False] * n
    hidden_since = [0] * n

    weights = 1.0 / np.arange(1, n + 1) ** zipf_a
    weights /= weights.sum()

    forks_rejected = 0
    fork_attempted = False
    fork_events = []

    def emit(c, other_parent):
        ev = Event(
            transactions=[f"e{len(ordered)}".encode()],
            block_signatures=None,
            parents=[
                heads[c] if heads[c] else root_self_parent(
                    participants.to_peer_slice()[c].id
                ),
                other_parent,
            ],
            creator=nodes[c].pub,
            index=next_index[c],
        )
        nodes[c].sign_and_add_event(ev, f"e{c}.{next_index[c]}", index, ordered)
        heads[c] = ev.hex()
        next_index[c] += 1
        if not withholding[c]:
            visible_head[c] = heads[c]
        return ev

    # bootstrap: one root-attached event per validator
    for c in range(n):
        emit(c, "")

    for i in range(n, e_count):
        c = int(rng.integers(n))
        if c < f:
            # byzantine lifecycle: flip withhold state on span boundaries
            # (staggered — see docstring)
            if (
                not withholding[c]
                and sum(withholding) < max(n // 8, 1)
                and rng.random() < 1.0 / withhold_span
            ):
                withholding[c] = True
                hidden_since[c] = next_index[c]
            elif withholding[c] and next_index[c] - hidden_since[c] >= withhold_span:
                # flush: chain becomes visible; an honest validator
                # immediately references the revealed head
                withholding[c] = False
                visible_head[c] = heads[c]
                h = f + int(rng.integers(n - f))
                emit(h, visible_head[c])
                continue
        # everyone gossips against the VISIBLE heads only
        partner = int(rng.choice(n, p=weights))
        while partner == c or not visible_head[partner]:
            partner = int(rng.integers(n))
        emit(c, visible_head[partner])

        if not fork_attempted and c < f and next_index[c] >= 3:
            # equivocation: a second signed event on an already-used
            # self-parent (the head's own self-parent), same index
            fork_attempted = True
            forked = Event(
                transactions=[b"equivocation"],
                block_signatures=None,
                parents=[ordered[-1].self_parent(), visible_head[(c + 1) % n]],
                creator=nodes[c].pub,
                index=next_index[c] - 1,
            )
            forked.sign(nodes[c].key)
            fork_events.append(forked)

    from babble_tpu.hashgraph import InmemStore

    hg = create_hashgraph(
        ordered, participants, InmemStore(participants, e_count + 128)
    )
    for forked in fork_events:
        try:
            hg.insert_event(forked, True)
            raise AssertionError("fork accepted at insert")
        except ValueError:
            forks_rejected += 1
    return hg, forks_rejected


def test_byzantine_256_differential():
    """256 validators, 1/3 byzantine (withhold/flush), Zipf fan-out:
    device pipeline == host engine on every round / witness flag /
    lamport / reception, with the equivocation rejected at insert.

    Information mixing is the scale bottleneck, not compute: at n=256 a
    round advance needs events strongly-seeing 171 witnesses, which takes
    ~30 gossip syncs per validator per round — at the suite-budget 16
    events/validator this DAG holds only the earliest rounds with fame
    still voting, so
    this test pins round/witness structure at scale; fame-depth behavior
    is pinned by the contested-fame test below (and coin rounds by the
    n=4 funky fixtures, see module docstring)."""
    hg, forks_rejected = build_byzantine_hashgraph()
    assert forks_rejected == 1
    assert_equivalent(hg)


def test_byzantine_contested_fame_differential():
    """1/3-byzantine withhold/flush cycles at n=32 force SPLIT fame votes:
    a witness hidden from part of the next round's witnesses misses its
    first-ballot supermajority, so fame decides rounds late
    (max_fame_depth >= 3) — and the device engine must agree bit-exactly
    on every late verdict and the receptions behind it."""
    hg, forks_rejected = build_byzantine_hashgraph(
        n=32, e_count=3200, seed=3, withhold_span=16, zipf_a=1.1
    )
    assert forks_rejected == 1
    cpu = assert_equivalent(hg)
    assert cpu.max_fame_depth >= 3, (
        f"byzantine fixture no longer contests fame "
        f"(max depth {cpu.max_fame_depth})"
    )
    assert len(cpu.store.consensus_events()) > 500


def test_byzantine_small_differential():
    """Same adversarial generator at a quick-suite scale (n=16)."""
    hg, forks_rejected = build_byzantine_hashgraph(
        n=16, e_count=400, seed=3, withhold_span=10
    )
    assert forks_rejected == 1
    assert_equivalent(hg)
