"""Unit-level Reset-from-Frame test: rebuild a hashgraph mid-history from
a (block, frame) checkpoint and verify it reproduces the original's
rounds, witnesses and consensus — then keep going with the remaining
events (reference: src/hashgraph/hashgraph_test.go:1711-1907
TestResetFromFrame)."""

from babble_tpu.hashgraph import Event, Frame, Hashgraph, InmemStore

from dsl import CACHE_SIZE, get_name, init_consensus_hashgraph


def test_reset_from_frame():
    h, index, _ = init_consensus_hashgraph()
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    h.process_decided_rounds()

    block = h.store.get_block(1)
    frame = h.get_frame(block.round_received())

    # the JSON round-trip clears computed per-event metadata (round,
    # lamport, roundReceived), which the reset hashgraph must recompute
    frame2 = Frame.from_json(frame.to_json())
    assert frame2.hash() == frame.hash()

    h2 = Hashgraph(h.participants, InmemStore(h.participants, CACHE_SIZE))
    h2.reset(block, frame2)

    # Known: the reset store reports the frame's per-participant heads
    known = h2.store.known_events()
    expected_known = {}
    for peer in h.participants.to_peer_slice():
        last = -1
        for ev in frame.events:
            if ev.creator() == peer.pub_key_hex:
                last = max(last, ev.index())
        expected_known[peer.id] = last
    assert known == expected_known

    # DivideRounds on the reset graph must reproduce the original's
    # round-1 witnesses and per-event rounds/lamports
    h2.divide_rounds()
    assert sorted(h.store.get_round(1).witnesses()) == sorted(
        h2.store.get_round(1).witnesses()
    )
    for ev in frame.events:
        name = get_name(index, ev.hex())
        assert h2.round(ev.hex()) == h.round(ev.hex()), name
        assert h2.lamport_timestamp(ev.hex()) == h.lamport_timestamp(
            ev.hex()
        ), name

    # consensus state after the reset matches the checkpoint
    h2.decide_fame()
    h2.decide_round_received()
    h2.process_decided_rounds()
    assert h2.store.last_block_index() == block.index()
    assert h2.last_consensus_round == block.round_received()
    assert h2.anchor_block is None

    # continue after reset: insert the original's round 2-4 events and
    # verify the witness sets converge round by round
    for r in range(2, 5):
        events = []
        for eh in h.store.get_round(r).round_events():
            events.append(h.store.get_event(eh))
        events.sort(key=lambda e: e.topological_index)
        for ev in events:
            ev2 = Event.from_json(ev.to_json())
            h2.insert_event(ev2, True)

    h2.divide_rounds()
    h2.decide_fame()
    h2.decide_round_received()
    h2.process_decided_rounds()

    for r in range(1, 5):
        assert sorted(h.store.get_round(r).witnesses()) == sorted(
            h2.store.get_round(r).witnesses()
        ), f"round {r} witnesses diverged after reset"
