"""Unit-level Reset-from-Frame tests: rebuild a hashgraph mid-history from
a (block, frame) checkpoint and verify it reproduces the original's
rounds, witnesses and consensus — then keep going with the remaining
events (reference: src/hashgraph/hashgraph_test.go:1711-1907
TestResetFromFrame, :2344-2530 TestFunkyHashgraphReset, :2656-2816
TestSparseHashgraphReset).

The every-block reset tests add a stronger oracle than the reference's
witness comparison: every block the reset graph re-derives above its
anchor must be BYTE-IDENTICAL to the original's (the re-decide path is
exactly what a fast-sync joiner runs, so a divergence here is the unit
form of the cluster-level block-body divergence)."""

from babble_tpu.hashgraph import Event, Frame, Hashgraph, InmemStore

from dsl import (
    CACHE_SIZE,
    get_name,
    init_consensus_hashgraph,
    init_funky_hashgraph,
    init_sparse_hashgraph,
)


def test_reset_from_frame():
    h, index, _ = init_consensus_hashgraph()
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    h.process_decided_rounds()

    block = h.store.get_block(1)
    frame = h.get_frame(block.round_received())

    # the JSON round-trip clears computed per-event metadata (round,
    # lamport, roundReceived), which the reset hashgraph must recompute
    frame2 = Frame.from_json(frame.to_json())
    assert frame2.hash() == frame.hash()

    h2 = Hashgraph(h.participants, InmemStore(h.participants, CACHE_SIZE))
    h2.reset(block, frame2)

    # Known: the reset store reports the frame's per-participant heads
    known = h2.store.known_events()
    expected_known = {}
    for peer in h.participants.to_peer_slice():
        last = -1
        for ev in frame.events:
            if ev.creator() == peer.pub_key_hex:
                last = max(last, ev.index())
        expected_known[peer.id] = last
    assert known == expected_known

    # DivideRounds on the reset graph must reproduce the original's
    # round-1 witnesses and per-event rounds/lamports
    h2.divide_rounds()
    assert sorted(h.store.get_round(1).witnesses()) == sorted(
        h2.store.get_round(1).witnesses()
    )
    for ev in frame.events:
        name = get_name(index, ev.hex())
        assert h2.round(ev.hex()) == h.round(ev.hex()), name
        assert h2.lamport_timestamp(ev.hex()) == h.lamport_timestamp(
            ev.hex()
        ), name

    # consensus state after the reset matches the checkpoint
    h2.decide_fame()
    h2.decide_round_received()
    h2.process_decided_rounds()
    assert h2.store.last_block_index() == block.index()
    assert h2.last_consensus_round == block.round_received()
    assert h2.anchor_block is None

    # continue after reset: insert the original's round 2-4 events and
    # verify the witness sets converge round by round
    for r in range(2, 5):
        events = []
        for eh in h.store.get_round(r).round_events():
            events.append(h.store.get_event(eh))
        events.sort(key=lambda e: e.topological_index)
        for ev in events:
            ev2 = Event.from_json(ev.to_json())
            h2.insert_event(ev2, True)

    h2.divide_rounds()
    h2.decide_fame()
    h2.decide_round_received()
    h2.process_decided_rounds()

    for r in range(1, 5):
        assert sorted(h.store.get_round(r).witnesses()) == sorted(
            h2.store.get_round(r).witnesses()
        ), f"round {r} witnesses diverged after reset"


def _wire_diff(h, h2):
    """Every event of `h` above `h2`'s per-participant heads, in
    topological order as wire events (the reference's getDiff +
    ToWire loop, hashgraph_test.go:2384-2405)."""
    known = h2.store.known_events()
    diff = []
    for peer in h.participants.to_peer_slice():
        for eh in h.store.participant_events(peer.pub_key_hex, known[peer.id]):
            diff.append(h.store.get_event(eh))
    diff.sort(key=lambda ev: ev.topological_index)
    return [ev.to_wire() for ev in diff]


def _reset_from_every_block(builder, n_blocks):
    """Reset a fresh hashgraph from each of the first `n_blocks` blocks'
    (block, frame) checkpoints, catch it up through the wire-event diff,
    and require (a) witness sets to converge per round and (b) every
    re-derived block above the anchor to be byte-identical."""
    h, index, _ = builder()
    h.divide_rounds()
    h.decide_fame()
    h.decide_round_received()
    h.process_decided_rounds()
    assert h.store.last_block_index() >= n_blocks - 1, (
        "fixture decided fewer blocks than the test resets from"
    )

    for bi in range(n_blocks):
        block = h.store.get_block(bi)
        frame = h.get_frame(block.round_received())
        # the JSON round-trip clears computed per-event metadata, which
        # the reset graph must recompute from the frame roots
        frame2 = Frame.from_json(frame.to_json())
        h2 = Hashgraph(h.participants, InmemStore(h.participants, CACHE_SIZE))
        h2_blocks = []
        h2.commit_callback = h2_blocks.append
        h2.reset(block, frame2)

        for wev in _wire_diff(h, h2):
            ev = h2.read_wire_info(wev)
            h2.insert_event(ev, False)

        h2.divide_rounds()
        h2.decide_fame()
        h2.decide_round_received()
        h2.process_decided_rounds()

        for r in range(block.round_received() + 1, h2.store.last_round() + 1):
            try:
                expected = sorted(h.store.get_round(r).witnesses())
            except Exception:
                continue
            assert expected == sorted(h2.store.get_round(r).witnesses()), (
                f"reset from block {bi}: round {r} witnesses diverged"
            )

        # the re-derived chain above the anchor must be the original's,
        # byte for byte (the fast-sync joiner safety oracle)
        for b2 in h2_blocks:
            orig = h.store.get_block(b2.index())
            assert b2.body.marshal() == orig.body.marshal(), (
                f"reset from block {bi}: block {b2.index()} body diverged"
            )
        assert h2.store.last_block_index() >= h.store.last_block_index(), (
            f"reset from block {bi}: fewer blocks decided than the original"
        )


def test_funky_reset_every_block():
    """reference: hashgraph_test.go:2344-2530 — the adversarial coin-round
    topology, reset from blocks 0, 1 and 2."""
    _reset_from_every_block(lambda: init_funky_hashgraph(full=True), 3)


def test_sparse_reset_every_block():
    """reference: hashgraph_test.go:2656-2816 — sparse witness sets,
    reset from blocks 0, 1 and 2."""
    _reset_from_every_block(init_sparse_hashgraph, 3)
