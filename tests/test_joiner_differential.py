"""Post-fast-sync backend differential: a cpu-backend and a tpu-backend
joiner core fast-forward from IDENTICAL materials and are fed IDENTICAL
post-join syncs — rounds, receptions, and blocks must match at every
step.

This is the regression net for the post-reset device divergence family
found in round 3 (a re-joined tpu-backend node minting one empty block
per sync, thousands ahead of its peers): the live attach staging
unrounded out-of-window events as engine-base-attached, and device
write-backs stamping rounds/receptions the host round function forbids.
The fixes it pins: the attach's zombie-exclusion + round-closure guards
(live.py), validate_round_writeback's never-overwrite/parent-bounds
gates, and admissible_receptions' host-rule mirror (engine.py)."""

import random

import pytest

from babble_tpu.hashgraph import Block, Frame, InmemStore, Section
from babble_tpu.node import Core

from test_core import init_cores, sync_and_run_consensus


def run_joiner_differential(seed, steps, check_bodies=True):
    rng = random.Random(seed)
    cores, _, _ = init_cores(4)

    i = 0
    while cores[0].get_last_block_index() < 3:
        a = rng.randrange(3)
        b = (a + 1 + rng.randrange(2)) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 3000

    blk = cores[0].hg.store.get_block(1)
    for c in cores[:3]:
        blk.set_signature(blk.sign(c.key))
    cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()
    section = cores[0].hg.get_section(frame.round, block.index())

    def make_joiner(backend):
        j = Core(
            3, cores[3].key, cores[0].participants,
            InmemStore(cores[0].participants, 5000), None,
            consensus_backend=backend,
        )
        j.fast_forward(
            cores[0].hex_id(),
            Block.from_json(block.to_json()),
            Frame.from_json(frame.to_json()),
            Section.from_json(section.to_json()),
        )
        return j

    j_cpu = make_joiner("cpu")
    j_tpu = make_joiner("tpu")

    def compare(tag):
        for p in cores[0].participants.to_peer_slice():
            pk = p.pub_key_hex
            try:
                h, is_root = j_cpu.hg.store.last_event_from(pk)
            except Exception:  # noqa: BLE001
                continue
            while h and not is_root:
                try:
                    ec = j_cpu.hg.store.get_event(h)
                    et = j_tpu.hg.store.get_event(h)
                except Exception:  # noqa: BLE001
                    break
                assert ec.round == et.round, (
                    f"{tag}: round diverged on ({pk[:12]}, {ec.index()}): "
                    f"cpu {ec.round} vs tpu {et.round}"
                )
                assert ec.round_received == et.round_received, (
                    f"{tag}: reception diverged on ({pk[:12]}, {ec.index()}):"
                    f" cpu {ec.round_received} vs tpu {et.round_received}"
                )
                h = ec.self_parent()
        assert j_cpu.get_last_block_index() == j_tpu.get_last_block_index(), (
            f"{tag}: blocks diverged cpu={j_cpu.get_last_block_index()} "
            f"tpu={j_tpu.get_last_block_index()}"
        )
        if check_bodies:
            hi = j_cpu.get_last_block_index()
            for bi in range(max(0, hi - 2), hi + 1):
                assert (
                    j_cpu.hg.store.get_block(bi).body.marshal()
                    == j_tpu.hg.store.get_block(bi).body.marshal()
                ), f"{tag}: block {bi} body diverged"

    for step in range(steps):
        a = rng.randrange(3)
        b = (a + 1 + rng.randrange(2)) % 3
        sync_and_run_consensus(cores, a, b, [f"post{step}".encode()])
        if step % 3 == 0:
            src = cores[rng.randrange(3)]
            for j in (j_cpu, j_tpu):
                known = j.known_events()
                diff = src.event_diff(known)
                wire = src.to_wire(diff)
                j.add_transactions([f"jtx{step}".encode()])
                j.sync(wire)
                j.run_consensus()
            known0 = cores[a].known_events()
            jd = j_cpu.event_diff(known0)
            if jd:
                cores[a].sync(j_cpu.to_wire(jd))
                cores[a].run_consensus()
            compare(f"step {step}")

    assert j_tpu.device_consensus_runs > 0, (
        "tpu joiner never ran the device engine — the differential "
        "degenerated into cpu-vs-cpu"
    )


def test_joiner_differential_seed1():
    run_joiner_differential(seed=1, steps=150, check_bodies=True)


def test_joiner_differential_seed3():
    run_joiner_differential(seed=3, steps=150, check_bodies=True)


@pytest.mark.parametrize("seed", [2, 4, 5, 6, 7, 8, 9, 10, 11, 12])
def test_joiner_differential_block_bodies(seed):
    """STRICT per-call block-body equality between a cpu- and a tpu-backend
    joiner (closed round-4; was the round-3 open defect).

    The round-3 divergence was never a backend skew: the two joiner
    INSTANCES share one validator key and each signed its own events with
    randomized ECDSA nonces, so their own-chain events serialized
    differently — different frame bytes, different block bodies — and a
    cpu-vs-cpu joiner pair failed identically (reproduced 12/12 seeds,
    always at the first body compare with joiner events in a block).
    RFC 6979 deterministic signing (crypto/keys.py) makes same-key
    same-body signatures byte-equal, and with the per-call fame/reception
    delegation on post-reset states (engine.py, live.py) the two backends
    now seal byte-identical blocks at every compare point. Failures
    historically surfaced by step 24; 45 steps gives margin while keeping
    ten seeds affordable in the default suite."""
    run_joiner_differential(seed=seed, steps=45, check_bodies=True)
