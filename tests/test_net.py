"""Transport tests (reference: src/net/net_transport_test.go:21,158,
tcp_transport_test.go:10-27, inmem_transport_test.go:7)."""

import threading

import pytest

from babble_tpu.hashgraph.event import WireBody, WireEvent
from babble_tpu.net import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    SyncRequest,
    SyncResponse,
    TCPTransport,
    TransportError,
)


def sample_wire_events():
    return [
        WireEvent(
            body=WireBody(
                transactions=[b"tx1", b"tx2"],
                block_signatures=[],
                self_parent_index=4,
                other_parent_creator_id=2,
                other_parent_index=7,
                creator_id=9,
                index=5,
            ),
            signature="sig",
        )
    ]


def serve_one(transport, make_response, n=1):
    """Consume n RPCs off the transport's queue, responding via make_response."""

    def loop():
        for _ in range(n):
            rpc = transport.consumer().get(timeout=5)
            rpc.respond(make_response(rpc.command))

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def test_tcp_sync_roundtrip():
    server = TCPTransport("127.0.0.1:0")
    client = TCPTransport("127.0.0.1:0")
    try:
        events = sample_wire_events()

        def respond(cmd):
            assert isinstance(cmd, SyncRequest)
            assert cmd.from_id == 0
            assert cmd.known == {0: 1, 1: 2, 2: 3}
            return SyncResponse(from_id=1, events=events, known={0: 5, 1: 5, 2: 6})

        serve_one(server, respond)
        resp = client.sync(
            server.local_addr(), SyncRequest(from_id=0, known={0: 1, 1: 2, 2: 3})
        )
        assert resp.from_id == 1
        assert len(resp.events) == 1
        got = resp.events[0]
        assert got.body.transactions == [b"tx1", b"tx2"]
        assert got.body.creator_id == 9
        assert got.signature == "sig"
        assert resp.known == {0: 5, 1: 5, 2: 6}
    finally:
        client.close()
        server.close()


def test_tcp_eager_sync_and_fast_forward():
    server = TCPTransport("127.0.0.1:0")
    client = TCPTransport("127.0.0.1:0")
    try:
        def respond(cmd):
            if isinstance(cmd, EagerSyncRequest):
                return EagerSyncResponse(from_id=1, success=True)
            assert isinstance(cmd, FastForwardRequest)
            return FastForwardResponse(from_id=1, snapshot=b"snap")

        serve_one(server, respond, n=2)
        r1 = client.eager_sync(
            server.local_addr(),
            EagerSyncRequest(from_id=0, events=sample_wire_events()),
        )
        assert r1.success
        r2 = client.fast_forward(
            server.local_addr(), FastForwardRequest(from_id=0)
        )
        assert r2.snapshot == b"snap"
    finally:
        client.close()
        server.close()


def test_tcp_pooled_connections():
    """Concurrent RPCs from one client reuse/pool conns
    (reference: net_transport_test.go:158 TestNetworkTransport_PooledConn)."""
    server = TCPTransport("127.0.0.1:0", max_pool=3)
    client = TCPTransport("127.0.0.1:0", max_pool=3)
    try:
        n = 20

        def respond(cmd):
            return SyncResponse(from_id=1, known=dict(cmd.known))

        serve_one(server, respond, n=n)
        errs = []

        def worker(i):
            try:
                resp = client.sync(
                    server.local_addr(), SyncRequest(from_id=0, known={0: i})
                )
                assert resp.known == {0: i}
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert not errs
    finally:
        client.close()
        server.close()


def test_tcp_error_response():
    server = TCPTransport("127.0.0.1:0")
    client = TCPTransport("127.0.0.1:0")
    try:
        def loop():
            rpc = server.consumer().get(timeout=5)
            rpc.respond(None, error="boom")

        threading.Thread(target=loop, daemon=True).start()
        with pytest.raises(TransportError, match="boom"):
            client.sync(server.local_addr(), SyncRequest(from_id=0, known={}))
    finally:
        client.close()
        server.close()


def test_tcp_bad_advertise_rejected():
    with pytest.raises(TransportError):
        TCPTransport("127.0.0.1:0", advertise="0.0.0.0:1337")


def test_tcp_dial_refused():
    client = TCPTransport("127.0.0.1:0")
    try:
        with pytest.raises(TransportError):
            client.sync("127.0.0.1:1", SyncRequest(from_id=0, known={}))
    finally:
        client.close()


def test_trace_piggyback_wire_round_trip():
    """The out-of-band Traces field: present when carried, omitted when
    empty (byte-identical wire for trace-free payloads), and ignored by
    from_json when absent (a trace-unaware peer's payload parses)."""
    ctxs = [{"Id": "ab12", "Origin": 2, "Span": "cd34"}]
    for cls, kwargs in (
        (SyncResponse, {"from_id": 1, "known": {0: 5}}),
        (EagerSyncRequest, {"from_id": 1, "events": sample_wire_events()}),
    ):
        carried = cls(traces=ctxs, **kwargs)
        d = carried.to_json()
        assert d["Traces"] == ctxs
        assert cls.from_json(d).traces == ctxs

        bare = cls(**kwargs)
        d_bare = bare.to_json()
        assert "Traces" not in d_bare  # untraced payloads keep the old wire
        # a legacy payload with no Traces key parses to an empty list
        assert cls.from_json(d_bare).traces == []
