"""Black-box flight recorder + SLO engine tests (ISSUE 7:
babble_tpu/obs/flightrec.py, babble_tpu/obs/slo.py, and their wiring
through the node, the watchdog and the simulator).

The unit tests drive a SimClock by hand; the cluster tests run full
4-node simulations on virtual time (well under a second of wall clock
each — no `slow` markers, same rationale as tests/test_sim.py).
"""

import json
import logging

from babble_tpu.obs import FlightRecorder, Observability, SLOEngine
from babble_tpu.obs.flightrec import (
    DEFAULT_DUMP_SUPPRESS_S,
    FLAP_THRESHOLD,
    MAX_DUMP_DOCS,
)
from babble_tpu.sim import FaultPlan, Partition, SimCluster, SimClock

logging.getLogger("babble.sim").setLevel(logging.CRITICAL)
logging.getLogger("babble.flightrec").setLevel(logging.CRITICAL)
logging.getLogger("babble.slo").setLevel(logging.CRITICAL)

# the stall scenario of test_sim.py: a full four-way partition freezes
# round advance on every node while work stays pending
TOTAL_PARTITION = FaultPlan(
    name="total_partition",
    partitions=(
        Partition(start=1.0, end=99.0, groups=((0,), (1,), (2,), (3,))),
    ),
)


# ----------------------------------------------------------------------
# recorder unit tests
# ----------------------------------------------------------------------

def test_ring_bounds_order_and_fingerprint():
    clock = SimClock()
    fr = FlightRecorder(clock=clock, node_id=7, capacity=4)
    for i in range(6):
        clock.advance_to(float(i))
        fr.record("ladder.demote", rung="live", backoff=i)
    assert len(fr) == 4
    assert fr.dropped == 2
    recs = fr.records()
    # oldest-first, the two oldest overwritten
    assert [r.seq for r in recs] == [2, 3, 4, 5]
    assert [r.t for r in recs] == [2.0, 3.0, 4.0, 5.0]
    assert all(r.name == "ladder.demote" for r in recs)

    # byte-identical replay: an identical recorder produces the same
    # stream bytes and fingerprint
    clock2 = SimClock()
    fr2 = FlightRecorder(clock=clock2, node_id=7, capacity=4)
    for i in range(6):
        clock2.advance_to(float(i))
        fr2.record("ladder.demote", rung="live", backoff=i)
    assert fr.stream_bytes() == fr2.stream_bytes()
    assert fr.fingerprint() == fr2.fingerprint()
    # a diverging field diverges the fingerprint
    fr2.record("watchdog.stall", waited=1.0)
    assert fr.fingerprint() != fr2.fingerprint()


def test_dump_document_and_global_suppression():
    clock = SimClock()
    fr = FlightRecorder(clock=clock, node_id=1)
    fr.record("watchdog.stall", waited=2.5, round=3)
    clock.advance_to(5.0)
    assert fr.dump("consensus-stall", waited=2.5) is None  # in-memory
    assert fr.dumps == 1 and len(fr.dump_docs) == 1
    doc = fr.dump_docs[0]
    assert doc["reason"] == "consensus-stall"
    assert doc["node"] == 1
    assert doc["ordinal"] == 1
    assert doc["context"] == {"waited": 2.5}
    assert [r["name"] for r in doc["records"]] == ["watchdog.stall"]

    # suppression is GLOBAL across reasons: the first trigger of an
    # episode owns the ring; the cascade it causes (stall -> SLO breach
    # -> flap) must not dump near-identical copies
    fr.dump("slo-breach", objective="round_advance")
    clock.advance_to(6.0)
    fr.dump("demotion-flap")
    assert fr.dumps == 1
    assert fr.dumps_suppressed == 2
    # ... and expires on the Clock
    clock.advance_to(5.0 + DEFAULT_DUMP_SUPPRESS_S)
    fr.dump("slo-breach", objective="round_advance")
    assert fr.dumps == 2
    assert fr.dump_docs[-1]["reason"] == "slo-breach"

    # the in-memory dump list is bounded
    for i in range(MAX_DUMP_DOCS + 3):
        clock.advance_to(clock.now + DEFAULT_DUMP_SUPPRESS_S)
        fr.dump("crash")
    assert len(fr.dump_docs) == MAX_DUMP_DOCS


def test_dump_writes_deterministic_artifact(tmp_path):
    clock = SimClock()
    fr = FlightRecorder(clock=clock, node_id=3, dump_dir=str(tmp_path))
    fr.record("fork.evidence", creator="abcd", index=2)
    path = fr.dump("fork", creator="abcd")
    assert path is not None
    # deterministic name: node + ordinal + reason, no timestamps
    assert path.endswith("flightrec-node3-01-fork.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "fork"
    assert [r["name"] for r in doc["records"]] == ["fork.evidence"]


def test_flap_detection_dumps_once():
    clock = SimClock()
    fr = FlightRecorder(clock=clock, node_id=0)
    for i in range(FLAP_THRESHOLD - 1):
        clock.advance_to(float(i))
        fr.record("ladder.demote", rung="live")
        assert fr.note_flap("demotion") is None
    assert fr.dumps == 0
    clock.advance_to(float(FLAP_THRESHOLD - 1))
    fr.record("ladder.demote", rung="live")
    fr.note_flap("demotion")
    assert fr.dumps == 1
    assert fr.dump_docs[0]["reason"] == "demotion-flap"
    # spaced-out demotions (outside the window) never count as a flap
    fr2 = FlightRecorder(clock=clock, node_id=0)
    for i in range(FLAP_THRESHOLD * 2):
        clock.advance_to(clock.now + 20.0)
        fr2.note_flap("demotion")
    assert fr2.dumps == 0


# ----------------------------------------------------------------------
# SLO engine unit tests
# ----------------------------------------------------------------------

def test_slo_gauge_breach_fires_gauges_counter_and_dump():
    clock = SimClock()
    obs = Observability(clock=clock)
    depth = obs.gauge("babble_device_queue_depth", "x")
    slo = SLOEngine(obs)
    slo.objective("queue_depth", series="babble_device_queue_depth",
                  kind="below", threshold=4.5)

    depth.set(2.0)
    status = slo.evaluate()
    assert slo.breached() == []
    (obj,) = status["objectives"]
    assert obj["breached"] is False and obj["burn"]["60s"] is not None

    depth.set(40.0)
    clock.advance_to(1.0)
    slo.evaluate()
    # young engine: no sample predates the windows, so evaluation is
    # cumulative — mean(2, 40) over threshold 4.5 burns in every window
    assert slo.breached() == ["queue_depth"]
    snap = obs.registry.snapshot()
    assert snap["babble_slo_breached"]["series"]["queue_depth"] == 1.0
    assert snap["babble_slo_breaches_total"]["series"]["queue_depth"] == 1.0
    # the breach transition recorded itself and dumped the ring
    names = [r.name for r in obs.flightrec.records()]
    assert "slo.breach" in names
    assert obs.flightrec.dump_docs[-1]["reason"] == "slo-breach"
    breaches_before = snap["babble_slo_breaches_total"]["series"]["queue_depth"]

    # still breached next tick: no second transition, no second dump
    clock.advance_to(2.0)
    slo.evaluate()
    snap = obs.registry.snapshot()
    assert (
        snap["babble_slo_breaches_total"]["series"]["queue_depth"]
        == breaches_before
    )
    assert obs.flightrec.dumps == 1


def test_slo_histogram_p_below_breach_and_recovery_shape():
    clock = SimClock()
    obs = Observability(clock=clock)
    lat = obs.histogram("babble_commit_latency_seconds", "x")
    slo = SLOEngine(obs)
    slo.objective("commit_p99", series="babble_commit_latency_seconds",
                  kind="p_below", threshold=0.5, quantile=0.99)

    # all observations comfortably under the threshold: no breach
    for _ in range(10):
        lat.observe(0.01)
    slo.evaluate()
    assert slo.breached() == []

    # every new observation blows the threshold: bad/budget burns hot
    for _ in range(10):
        lat.observe(8.0)
    clock.advance_to(1.0)
    status = slo.evaluate()
    assert slo.breached() == ["commit_p99"]
    (obj,) = status["objectives"]
    assert obj["burn"]["60s"] > 1.0


def test_slo_multi_window_spike_does_not_breach():
    """A brief spike burns the short window but not the long one —
    multi-window burn rate pages nobody. A sustained regression burns
    both and does."""
    clock = SimClock()
    obs = Observability(clock=clock)
    g = obs.gauge("babble_consensus_stalled", "x")
    slo = SLOEngine(obs, windows=(10.0, 60.0))
    slo.objective("round_advance", series="babble_consensus_stalled",
                  kind="below", threshold=0.5)

    # 65s of healthy samples age the engine past its longest window
    for i in range(14):
        clock.advance_to(i * 5.0)
        g.set(0.0)
        slo.evaluate()
    assert slo.breached() == []

    # one 5s spike: the 10s window burns, the 60s window stays cool
    g.set(1.0)
    clock.advance_to(70.0)
    status = slo.evaluate()
    assert slo.breached() == []
    (obj,) = status["objectives"]
    assert obj["burn"]["10s"] >= 1.0
    assert obj["burn"]["60s"] < 1.0

    # sustained: once the long window's mean crosses too, it breaches
    t = 70.0
    while t < 140.0 and not slo.breached():
        t += 5.0
        clock.advance_to(t)
        slo.evaluate()
    assert slo.breached() == ["round_advance"]


def test_bench_slo_gates():
    """bench.py --slo passes at the r05 headline (1.55M events/s) and
    fails a degraded run; bench_dispatch.py --slo mirrors it over the
    blocked-ms ceiling. Gates run against synthetic registries — no
    device pipeline in unit tests."""
    import bench
    import bench_dispatch

    obs = Observability()
    obs.gauge("babble_bench_events_per_second", "x").set(1_550_165.4)
    ok, status = bench.slo_gate(obs, 1_000_000.0)
    assert ok
    (obj,) = status["objectives"]
    assert obj["breached"] is False

    degraded = Observability()
    degraded.gauge("babble_bench_events_per_second", "x").set(400_000.0)
    ok, status = bench.slo_gate(degraded, 1_000_000.0)
    assert not ok

    dobs = Observability()
    hist = dobs.histogram("babble_bench_dispatch_blocked_seconds", "x",
                          labels=("path",))
    hist.labels(path="queued_mesh").observe(0.020)
    ok, _ = bench_dispatch.slo_gate(dobs, 0.150)
    assert ok
    slow = Observability()
    shist = slow.histogram("babble_bench_dispatch_blocked_seconds", "x",
                           labels=("path",))
    shist.labels(path="queued_mesh").observe(0.500)
    ok, _ = bench_dispatch.slo_gate(slow, 0.150)
    assert not ok


# ----------------------------------------------------------------------
# simulator integration (the acceptance scenarios)
# ----------------------------------------------------------------------

def _stall_cluster(seed=3):
    return SimCluster(n=4, seed=seed, plan=TOTAL_PARTITION,
                      stall_deadline=2.0)


def test_stall_run_exactly_one_auto_dump_per_node():
    """A full four-way partition stalls every node: the watchdog's stall
    detection must auto-dump the ring exactly once per node (reason
    consensus-stall, containing the watchdog.stall record), with the SLO
    breach that follows suppressed by the global dump window rather than
    producing a second near-identical dump."""
    cluster = _stall_cluster()
    try:
        cluster.run(until=8.0)
        for sn in cluster.sns:
            fr = sn.node.obs.flightrec
            assert fr.dumps == 1, sn.name
            doc = fr.dump_docs[0]
            assert doc["reason"] == "consensus-stall"
            assert "watchdog.stall" in [r["name"] for r in doc["records"]]
            # the round-advance SLO also breached — recorded in the
            # ring, its dump suppressed by the stall's
            names = [r.name for r in fr.records()]
            assert "slo.breach" in names
            assert fr.dumps_suppressed >= 1
            snap = sn.node.obs.registry.snapshot()
            assert snap["babble_consensus_stalls_total"]["series"][""] == 1.0
            assert (
                snap["babble_slo_breached"]["series"]["round_advance"] == 1.0
            )
    finally:
        cluster.shutdown()


def test_stall_run_streams_and_dumps_byte_identical_across_replays():
    """Same-seed replays must produce byte-identical record streams AND
    byte-identical dump documents on every node — the flight recorder
    joins the sim's determinism fingerprint, so any nondeterministic
    field (wall-clock, thread identity) fails here."""
    def capture():
        cluster = _stall_cluster()
        try:
            res = cluster.run(until=8.0)
            streams = {
                sn.name: sn.node.obs.flightrec.stream_bytes()
                for sn in cluster.sns
            }
            dumps = {
                sn.name: json.dumps(sn.node.obs.flightrec.dump_docs,
                                    sort_keys=True)
                for sn in cluster.sns
            }
            return res, streams, dumps
        finally:
            cluster.shutdown()

    res_a, streams_a, dumps_a = capture()
    res_b, streams_b, dumps_b = capture()
    assert streams_a == streams_b
    assert dumps_a == dumps_b
    assert res_a["flightrec_fingerprint"] == res_b["flightrec_fingerprint"]
    assert res_a["flightrec_records"] == res_b["flightrec_records"]
    # non-empty: the stall actually put records in the rings
    assert all(n > 0 for n in res_a["flightrec_records"].values())


def test_slo_breach_run_auto_dumps_and_replays_identically():
    """A run whose only incident is an SLO breach (commit-latency
    objective tightened to an unmeetable threshold on one node) must
    auto-produce exactly one slo-breach dump on that node, byte-identical
    across same-seed replays."""
    def run_once():
        cluster = SimCluster(n=4, seed=11, plan=FaultPlan(name="clean"))
        try:
            # every commit is now an SLO violation on node0; the other
            # nodes keep the default objective and stay healthy
            obj = cluster.sns[0].node.slo._objectives["submit_commit_p99"]
            obj.threshold = 1e-9
            cluster.run(until=12.0)
            sn0 = cluster.sns[0]
            fr = sn0.node.obs.flightrec
            reasons = [d["reason"] for d in fr.dump_docs]
            healthy = [
                d
                for sn in cluster.sns[1:]
                for d in sn.node.obs.flightrec.dump_docs
            ]
            return (
                reasons,
                json.dumps(fr.dump_docs, sort_keys=True),
                healthy,
                sn0.node.obs.registry.snapshot()["babble_slo_breached"],
            )
        finally:
            cluster.shutdown()

    reasons_a, dumps_a, healthy_a, breached_a = run_once()
    reasons_b, dumps_b, _, _ = run_once()
    assert reasons_a == ["slo-breach"]
    assert reasons_a == reasons_b
    assert dumps_a == dumps_b
    assert healthy_a == []  # untampered nodes breach nothing
    assert breached_a["series"]["submit_commit_p99"] == 1.0


def test_queued_mesh_run_records_dispatch_lifecycle_deterministically():
    """On the queued-mesh backend the recorder captures the dispatch
    lifecycle (enqueue/integrate) — the records the ISSUE wants in the
    ring ahead of a dump — and the stream stays replay-identical, which
    pins that no record leaks wall-clock or thread state from the
    dispatch worker."""
    kwargs = dict(n=4, seed=9, plan=FaultPlan(name="clean"), backend="tpu",
                  mesh_devices=2, dispatch_queue_depth=4,
                  dispatch_batch_deadline=0.2)

    def run_once():
        cluster = SimCluster(**kwargs)
        try:
            res = cluster.run(until=None, target_block=2)
            names = {
                r.name
                for sn in cluster.sns
                for r in sn.node.obs.flightrec.records()
            }
            return res["flightrec_fingerprint"], names
        finally:
            cluster.shutdown()

    fp_a, names_a = run_once()
    fp_b, names_b = run_once()
    assert fp_a == fp_b
    assert names_a == names_b
    assert "dispatch.enqueue" in names_a
    assert "dispatch.integrate" in names_a
