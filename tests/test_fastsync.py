"""Fast-sync / catch-up / bootstrap integration tests
(reference: src/node/node_test.go:455,497,533,583,660)."""

import copy
import pytest
import os
import time

from babble_tpu.crypto import generate_key, pub_key_bytes
from babble_tpu.hashgraph import InmemStore, SQLiteStore
from babble_tpu.net import InmemTransport, SyncRequest
from babble_tpu.node import Config, Node
from babble_tpu.node.state import NodeState
from babble_tpu.peers import Peer, Peers
from babble_tpu.proxy import InmemDummyClient

from test_node import (
    bombard_and_wait,
    check_gossip,
    load_scale,
    run_nodes,
    shutdown_nodes,
)


def make_config(sync_limit=150):
    """sync_limit must be high enough that healthy nodes never spuriously
    flip to CatchingUp (that halts consensus: fewer than a supermajority of
    active event creators remain); only a genuinely-behind joiner should
    exceed it. The reference tests use large limits for the same reason
    (node_test.go:533-541)."""
    return Config(
        heartbeat_timeout=0.005, tcp_timeout=1.0, cache_size=1000,
        sync_limit=sync_limit,
    )


def build_cluster(n, conf, store_factory=None, proxy_factory=None):
    """Like test_node.init_nodes but keeps keys so nodes can be recycled
    (reference: node_test.go:292-388)."""
    keys = [generate_key() for _ in range(n)]
    participants = Peers()
    peer_list = []
    for i, key in enumerate(keys):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr=f"127.0.0.1:{9990 + i}", pub_key_hex=pub_hex)
        participants.add_peer(peer)
        peer_list.append(peer)

    # RPC timeout balances two pressures: fast-forward responses wait on
    # core_lock while the serving node is mid-consensus (needs headroom),
    # while gossip to a dark peer burns a gossip thread for the full
    # timeout (needs a cap)
    transports = [InmemTransport(p.net_addr, timeout=5.0) for p in peer_list]
    for t in transports:
        for u in transports:
            if t is not u:
                t.connect(u.local_addr(), u)

    nodes, proxies = [], []
    for i, key in enumerate(keys):
        store = (
            store_factory(i, participants, conf)
            if store_factory
            else InmemStore(participants, conf.cache_size)
        )
        prox = proxy_factory(i) if proxy_factory else InmemDummyClient()
        node = Node(
            copy.copy(conf), peer_list[i].id, key, participants, store,
            transports[i], prox,
        )
        node.init()
        nodes.append(node)
        proxies.append(prox)
    return nodes, proxies, keys, peer_list, participants, transports


def first_available_block(node, upto):
    """A fast-forwarded node starts mid-history — and a node that
    fast-forwarded more than once can hold disjoint ranges. Return the
    start of the contiguous block range ending at `upto` (the range the
    byte-equality check can walk)."""
    start = None
    for i in range(upto, -1, -1):
        try:
            node.get_block(i)
            start = i
        except Exception:  # noqa: BLE001
            break
    if start is None:
        raise AssertionError(f"node holds no blocks at or below {upto}")
    return start


def connect_transport(transports, new_trans):
    for t in transports:
        t.connect(new_trans.local_addr(), new_trans)
        new_trans.connect(t.local_addr(), t)


def test_sync_limit_response():
    """A peer far behind must get SyncLimit=true instead of a huge diff
    (reference: node_test.go:455-496)."""
    conf = make_config()
    nodes, proxies, *_ = build_cluster(4, conf)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=1)
        # drop the responder's limit only now (a healthy run-time limit
        # this low would halt consensus), then claim to know nothing
        nodes[1].conf.sync_limit = 10
        node0 = nodes[0]
        empty_known = {p_id: -1 for p_id in node0.core.known_events()}
        resp = node0.trans.sync(
            nodes[1].local_addr,
            SyncRequest(from_id=node0.id, known=empty_known),
        )
        assert resp.sync_limit is True
    finally:
        shutdown_nodes(nodes)


def test_catching_up_node_serves_fast_forward():
    """A node in CatchingUp must still answer FastForwardRequest from its
    STORED anchor (deliberate deviation from the reference, which discards
    all RPCs outside Babbling): when several nodes flip to CatchingUp
    together, mutual "not ready" refusals would otherwise livelock the
    cluster — nobody can fast-forward, nobody exits."""
    from babble_tpu.net import FastForwardRequest

    conf = make_config()
    nodes, proxies, *_ = build_cluster(4, conf)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)
        donor = nodes[0]
        # wait for an anchor to accumulate signatures
        deadline = time.monotonic() + 60
        while donor.core.hg.anchor_block is None and time.monotonic() < deadline:
            bombard_and_wait(
                nodes, proxies,
                target_block=donor.core.get_last_block_index() + 1,
                timeout_s=120,
            )
        assert donor.core.hg.anchor_block is not None

        # flip the donor to CatchingUp and request a fast-forward from it.
        # Cut the donor's OUTBOUND links first: its own run loop would
        # otherwise fast-forward against a peer, hit the not-actually-
        # behind guard, and bounce back to Babbling mid-assertion —
        # stranded outbound keeps it deterministically in CatchingUp
        # (inbound delivery rides the requesters' own transports).
        donor.trans.disconnect_all()
        donor.set_state(NodeState.CATCHING_UP)
        resp = nodes[1].trans.fast_forward(
            donor.local_addr, FastForwardRequest(from_id=nodes[1].id)
        )
        assert resp.block is not None and resp.frame is not None
        # ordinary sync requests stay refused outside Babbling
        try:
            nodes[1].trans.sync(
                donor.local_addr,
                SyncRequest(from_id=nodes[1].id,
                            known=nodes[1].core.known_events()),
            )
            raise AssertionError("sync served in CatchingUp")
        except Exception as e:  # noqa: BLE001
            assert "not ready" in str(e)
        donor.set_state(NodeState.BABBLING)
    finally:
        shutdown_nodes(nodes)


def test_spurious_catching_up_bounces_back():
    """A node that flips to CatchingUp while actually current must NOT
    apply a fast-forward: every donor anchor is at or below its own last
    block, and applying would rewind its own chain — its next events
    would re-use indexes peers have already seen, and the whole cluster
    rejects its diffs with invalid-signature/fork errors forever. The
    node must bounce straight back to Babbling with its chain intact."""
    conf = make_config()
    nodes, proxies, *_ = build_cluster(4, conf)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)
        donor = nodes[0]
        deadline = time.monotonic() + 60
        while donor.core.hg.anchor_block is None and time.monotonic() < deadline:
            bombard_and_wait(
                nodes, proxies,
                target_block=donor.core.get_last_block_index() + 1,
                timeout_s=120,
            )
        assert donor.core.hg.anchor_block is not None

        victim = nodes[1]
        blocks_before = victim.core.get_last_block_index()
        head_before = victim.core.head
        seq_before = victim.core.seq
        victim.set_state(NodeState.CATCHING_UP)
        # drive the catch-up attempts directly (the run loop does the
        # same); donors' anchors are all <= the victim's last block, so
        # the guard must resume Babbling without ever resetting
        deadline = time.monotonic() + 60
        while (
            victim.get_state() == NodeState.CATCHING_UP
            and time.monotonic() < deadline
        ):
            victim.fast_forward()
        assert victim.get_state() == NodeState.BABBLING
        assert victim.core.get_last_block_index() >= blocks_before
        # the node may legitimately create NEW events once resumed; what
        # it must never do is rewind: its index counter stays monotone and
        # the event it had at seq_before is still the same one
        assert victim.core.seq >= seq_before, "own chain was rewound"
        ev = victim.core.hg.store.participant_event(
            victim.core.hex_id(), seq_before
        )
        assert ev == head_before, "own chain was forked by the reset"
    finally:
        shutdown_nodes(nodes)


@pytest.mark.slow
def test_catch_up():
    """Start 3 of 4 nodes, run ahead beyond sync-limit, then start the 4th:
    it must flip to CatchingUp, fast-forward from a peer's anchor block and
    rejoin consensus (reference: node_test.go:533-582)."""
    conf = make_config()
    nodes, proxies, *_ = build_cluster(4, conf)
    node4, prox4 = nodes[3], proxies[3]
    nodes3, proxies3 = nodes[:3], proxies[:3]
    try:
        run_nodes(nodes3)
        # run until the joiner would be beyond the sync limit
        target = 3
        while True:
            bombard_and_wait(nodes3, proxies3, target_block=target, timeout_s=180)
            total_events = sum(
                i + 1 for i in nodes3[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            target += 1
        target = min(n.core.get_last_block_index() for n in nodes3)

        node4.run_async(True)
        bombard_and_wait(nodes, proxies, target_block=target + 2, timeout_s=180)
        # node4 joined mid-history: its first block came from a frame,
        # and from there on bodies must be byte-identical (compare over
        # the committed range every node shares — the joiner's anchor may
        # sit above the original target if the others raced ahead)
        upto = min(n.core.get_last_block_index() for n in nodes)
        start = first_available_block(node4, upto)
        check_gossip(nodes, from_block=start, upto=upto)
    finally:
        shutdown_nodes(nodes)


def test_fast_sync_repeated():
    """Kill and restart a node twice; it must catch up each time
    (reference: node_test.go:583-642)."""
    conf = make_config()
    nodes, proxies, keys, peer_list, participants, transports = build_cluster(4, conf)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)

        for _round in range(2):
            victim = nodes[3]
            victim.shutdown()
            transports[3].disconnect_all()
            for t in transports[:3]:
                t.disconnect(transports[3].local_addr())

            # run the survivors far enough ahead that the recycled node's
            # empty store is beyond the sync limit
            base = max(n.core.get_last_block_index() for n in nodes[:3])
            goal_ahead = base + 3
            while True:
                bombard_and_wait(
                    nodes[:3], proxies[:3], target_block=goal_ahead, timeout_s=180
                )
                total_events = sum(
                    i + 1 for i in nodes[0].core.known_events().values()
                )
                if total_events > conf.sync_limit + 50:
                    break
                goal_ahead += 1
            base = goal_ahead

            # recycle: fresh store + transport, same key (node_test.go:357-388)
            trans = InmemTransport(peer_list[3].net_addr, timeout=5.0)
            connect_transport(transports[:3], trans)
            transports[3] = trans
            prox = InmemDummyClient()
            store = InmemStore(participants, conf.cache_size)
            node = Node(
                conf, peer_list[3].id, keys[3], participants, store, trans, prox
            )
            node.init()
            nodes[3] = node
            proxies[3] = prox
            node.run_async(True)

            # generous: under full-suite load the joiner may need several
            # fast-forward attempts while the survivors keep racing ahead
            goal = base + 5
            bombard_and_wait(nodes, proxies, target_block=goal, timeout_s=240)
            upto = min(n.core.get_last_block_index() for n in nodes)
            start = first_available_block(node, upto)
            check_gossip(nodes, from_block=start, upto=upto)
    finally:
        shutdown_nodes(nodes)


def test_bootstrap_all_nodes(tmp_path):
    """Run a sqlite-backed cluster, stop it, then rebuild every node from its
    database replay and keep going (reference: node_test.go:660-729)."""
    conf = make_config()

    def store_factory(i, participants, conf):
        return SQLiteStore.load_or_create(
            participants, conf.cache_size, os.path.join(tmp_path, f"node{i}.db")
        )

    nodes, proxies, keys, peer_list, participants, transports = build_cluster(
        4, conf, store_factory=store_factory
    )
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)
        check_gossip(nodes, upto=2)
        base = min(n.core.get_last_block_index() for n in nodes)
        shutdown_nodes(nodes)
        for s in [n.core.hg.store for n in nodes]:
            s.close()
        time.sleep(0.1)

        # rebuild everything from disk
        transports = [InmemTransport(p.net_addr) for p in peer_list]
        for t in transports:
            for u in transports:
                if t is not u:
                    t.connect(u.local_addr(), u)
        nodes2, proxies2 = [], []
        for i, key in enumerate(keys):
            store = store_factory(i, participants, conf)
            assert store.need_bootstrap(), f"node {i} store should need bootstrap"
            prox = InmemDummyClient()
            node = Node(
                conf, peer_list[i].id, key, participants, store,
                transports[i], prox,
            )
            node.init()
            assert node.core.get_last_block_index() >= 0, (
                "bootstrap lost the committed blocks"
            )
            nodes2.append(node)
            proxies2.append(prox)

        run_nodes(nodes2)
        bombard_and_wait(nodes2, proxies2, target_block=base + 2, timeout_s=180)
        check_gossip(nodes2, upto=base + 2)
        nodes = nodes2  # for the finally clause
    finally:
        shutdown_nodes(nodes)


def test_eviction_livelock_escape():
    """Round-5 regression: a node whose store has evicted event BODIES its
    peers' diffs still reference as parents cannot sync incrementally —
    but its known-events high-water mark still claims those events, so
    peers never resend them and over_sync_limit never trips (observed as
    a survivor wedged for 960s with "EventCache ... Not Found" on the
    same hashes forever). After 3 consecutive missing-parent sync
    failures the node must flip to CatchingUp and rebuild via
    fast-forward instead of livelocking."""
    conf = make_config()
    nodes, proxies, *_ = build_cluster(4, conf)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=3, timeout_s=180)

        victim = nodes[0]
        # surgically induce the livelock: evict a recent event body from
        # the victim's store while keeping its participant index entry
        # (exactly what the LRU does when the undetermined backlog
        # outgrows cache_size). Pick each peer's LAST KNOWN event so every
        # incoming diff's next event references a missing parent.
        with victim.core_lock:
            store = victim.core.hg.store
            for p in victim.core.participants.to_peer_slice():
                h, is_root = store.last_event_from(p.pub_key_hex)
                if not is_root and h in store.event_cache:
                    del store.event_cache._items[h]

        wedge_block = victim.core.get_last_block_index()

        # traffic must flow for diffs to arrive and fail; recovery = the
        # victim is committing again past its wedge point on a store that
        # can serve every chain head (fast_forward reset rebuilt it)
        deadline = time.monotonic() + 120 * load_scale()
        recovered = False
        while time.monotonic() < deadline:
            proxies[1].submit_tx(f"evict-{time.monotonic()}".encode())
            if victim.core.get_last_block_index() >= wedge_block + 2:
                with victim.core_lock:
                    cur = victim.core.hg.store
                    healthy = all(
                        is_root or h in cur.event_cache
                        for h, is_root in (
                            cur.last_event_from(p.pub_key_hex)
                            for p in victim.core.participants.to_peer_slice()
                        )
                    )
                if healthy:
                    recovered = True
                    break
            time.sleep(0.1)
        assert recovered, (
            f"victim never recovered from evicted-parent livelock: "
            f"state={victim.get_state()}, "
            f"block={victim.core.get_last_block_index()} "
            f"(wedged at {wedge_block}), "
            f"missing_parent_syncs={victim._missing_parent_syncs}, "
            f"rewind_ok={victim._rewind_ok}, "
            f"last_exported_seq={victim._last_exported_seq}, "
            f"seq={victim.core.seq}, "
            f"bounces={victim.fast_forward_bounces}"
        )
    finally:
        shutdown_nodes(nodes)
