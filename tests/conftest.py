"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this image's interpreter pre-imports jax from sitecustomize against
the real TPU tunnel, so env vars alone are too late — jax.config.update
before the first backend use is what sticks.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
