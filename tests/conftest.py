"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this image's interpreter pre-imports jax from sitecustomize against
the real TPU tunnel, so env vars alone are too late — jax.config.update
before the first backend use is what sticks.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _race_certify_session():
    """`make race` / BABBLE_RACE_CERTIFY=1: run the entire tier-1 suite
    inside one certify() scope, and fail the session if any race
    candidate or lock-order cycle surfaced (analysis/lockruntime.py).
    Off by default: instrumentation patches live classes, and tests that
    construct seeded defects manage their own nested scopes."""
    if not os.environ.get("BABBLE_RACE_CERTIFY"):
        yield None
        return
    from babble_tpu.analysis.lockruntime import certify, format_finding

    with certify() as cert:
        yield cert
    assert not cert.findings, (
        "race certification failed across the test session: "
        + "; ".join(format_finding(f) for f in cert.findings)
    )
