"""Deterministic-simulator tests (babble_tpu/sim/): seeded determinism,
fault-plan convergence, crash-restart with a persistent store, and the
round-5 divergence shape (late witness during fast-forward under load)
as a regression scenario.

All of these run entire 4-node clusters, but on VIRTUAL time — a run
that simulates ~10 seconds of cluster activity takes well under a
second of wall clock, so none of them need the `slow` marker.
"""

import json
import logging

import pytest

from babble_tpu.sim import (
    CrashSpec,
    DivergenceChecker,
    FaultPlan,
    LatencySpec,
    Partition,
    SimCluster,
    SimClock,
    SimScheduler,
    preset_plan,
    run_one,
)

# node-level logging is meaningless noise across hundreds of simulated
# exchanges; failures surface through assertions and artifacts
logging.getLogger("babble.sim").setLevel(logging.CRITICAL)


# ----------------------------------------------------------------------
# primitives
# ----------------------------------------------------------------------

def test_scheduler_orders_ties_by_insertion():
    sched = SimScheduler()
    seen = []
    sched.at(1.0, lambda: seen.append("a"))
    sched.at(0.5, lambda: seen.append("b"))
    sched.at(1.0, lambda: seen.append("c"))
    sched.run_until(2.0)
    assert seen == ["b", "a", "c"]
    assert sched.clock.now == 2.0


def test_sim_clock_captures_sleep():
    clock = SimClock()
    clock.sleep(0.25)
    clock.sleep(0.5)
    assert clock.monotonic() == 0.0  # sleep never advances virtual time
    assert clock.take_pending_sleep() == 0.75
    assert clock.take_pending_sleep() == 0.0


def test_fault_plan_json_round_trip():
    plan = FaultPlan(
        name="custom",
        latency=LatencySpec(base=0.02, jitter=0.08),
        drop_rate=0.1,
        dup_rate=0.05,
        partitions=[Partition(start=1.0, end=4.0, groups=((0,), (1, 2, 3)))],
        crashes=[CrashSpec(node=3, at=1.5, restart_at=5.0)],
    )
    back = FaultPlan.from_json(plan.to_json())
    assert back.to_dict() == plan.to_dict()
    # partition semantics survive the trip
    assert back.partitioned(0, 2, 2.0)
    assert not back.partitioned(1, 2, 2.0)  # same group
    assert not back.partitioned(0, 2, 5.0)  # healed


def test_preset_plans_exist():
    for name in ("clean", "lossy", "partition_heal", "crash_restart", "chaos"):
        plan = preset_plan(name, 4)
        assert plan.name == name
    with pytest.raises(ValueError):
        preset_plan("nope", 4)


# ----------------------------------------------------------------------
# seeded determinism (ISSUE 1 acceptance: same seed => byte-identical
# committed blocks on every node, twice)
# ----------------------------------------------------------------------

def test_seeded_determinism_same_seed_twice():
    a = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    b = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    assert a["ok"] and b["ok"]
    assert a["reached_target"] and b["reached_target"]
    assert a["digest"] == b["digest"]
    # the whole event sequence replayed, not just the outcome
    assert a["events_run"] == b["events_run"]
    assert a["virtual_time"] == b["virtual_time"]
    assert a["net"] == b["net"]


def test_different_seeds_diverge_in_schedule():
    a = run_one(5, plan="clean", n=4, until=None, target_block=2)
    b = run_one(6, plan="clean", n=4, until=None, target_block=2)
    assert a["ok"] and b["ok"]
    # different seeds drive different workloads/schedules — if these were
    # equal the seed would not actually be feeding the streams
    assert a["digest"] != b["digest"]


# ----------------------------------------------------------------------
# fault convergence
# ----------------------------------------------------------------------

def test_partition_heal_converges():
    res = run_one(3, plan="partition_heal", n=4, until=30.0, target_block=10)
    assert res["ok"], res["error"]
    assert res["reached_target"]
    assert res["net"]["severed"] > 0  # the partition actually bit
    assert res["blocks_checked"] >= 10


def test_crash_restart_sqlite_store(tmp_path):
    """The crashed node's sqlite store survives; on restart it bootstraps
    from disk (replaying its own history through consensus) and rejoins
    the cluster without diverging."""
    res = run_one(
        9,
        plan="crash_restart",
        n=4,
        store="sqlite",
        store_dir=str(tmp_path),
        until=40.0,
        target_block=10,
    )
    assert res["ok"], res["error"]
    assert res["reached_target"]
    assert res["restarts"] == 1
    # all four db files exist — including the crashed node's
    assert len(list(tmp_path.glob("node*.db"))) == 4


def test_crash_restart_inmem_rejoins():
    """An inmem node loses its store in the crash and rejoins as an
    effective fresh joiner — convergence must still hold."""
    res = run_one(9, plan="crash_restart", n=4, until=40.0, target_block=10)
    assert res["ok"], res["error"]
    assert res["reached_target"]
    assert res["restarts"] == 1


# ----------------------------------------------------------------------
# round-5 divergence shape: a node that comes back far behind, under
# sustained load, with a sync limit tight enough to force the
# fast-forward path (late witness arriving during catch-up was the r5
# reception-divergence shape — this pins the scenario as a regression)
# ----------------------------------------------------------------------

def test_r5_shape_fast_forward_under_load():
    plan = FaultPlan(
        name="deep_crash",
        latency=LatencySpec(base=0.01, jitter=0.03),
        crashes=[CrashSpec(node=3, at=1.0, restart_at=8.0)],
    )
    cluster = SimCluster(n=4, seed=11, plan=plan, sync_limit=30)
    try:
        res = cluster.run(until=60.0, target_block=20)
    finally:
        cluster.shutdown()
    assert res["reached_target"], res
    # the restarted node MUST have gone through the catch-up state
    # machine (sync-limit flip + fast-forward), not ordinary sync —
    # otherwise this test is not exercising the r5 shape at all
    assert res["catchup_flips"] >= 1
    assert res["ff_attempts"] >= 1
    flipped = [sn for sn in cluster.sns if sn.catchup_flips]
    assert [sn.index for sn in flipped] == [3]
    # and every settled block byte-matched across nodes during the run
    assert res["blocks_checked"] >= 20


# ----------------------------------------------------------------------
# divergence detection + artifact (inject a fake divergence: the checker
# itself must catch it and dump a replayable artifact)
# ----------------------------------------------------------------------

def test_divergence_dumps_artifact(tmp_path):
    from babble_tpu.sim.checker import DivergenceError

    cluster = SimCluster(
        n=4, seed=2, artifact_dir=str(tmp_path / "artifacts")
    )
    try:
        cluster.run(until=None, target_block=2)
        # corrupt one node's copy of block 1 behind the checker's back
        store = cluster.sns[2].node.core.hg.store
        blk = store.get_block(1)
        blk.body.transactions.append(b"byzantine extra tx")
        store.set_block(blk)
        cluster.checker.checked_upto = -1  # force a full re-check
        with pytest.raises(DivergenceError) as ei:
            cluster.check_divergence()
        # the failure auto-dumped every live node's flight recorder —
        # the triage bundle the sweep exports beside the artifact
        for sn in cluster.sns:
            docs = sn.node.obs.flightrec.dump_docs
            assert docs and docs[-1]["reason"] == "divergence"
        exported = cluster.export_flight_dumps(str(tmp_path / "artifacts"))
        assert len(exported) == 4
        for p in exported:
            with open(p) as f:
                assert json.load(f)["reason"] == "divergence"
    finally:
        cluster.shutdown()
    artifact_path = ei.value.artifact_path
    assert artifact_path is not None
    with open(artifact_path) as f:
        artifact = json.load(f)
    assert artifact["kind"] == "babble-tpu-sim-divergence"
    assert artifact["block_index"] == 1
    assert artifact["seed"] == 2
    # the embedded plan replays: it must round-trip through FaultPlan
    assert FaultPlan.from_dict(artifact["plan"]).name == "clean"
    assert "node2" in artifact["blocks"]


def test_checker_skips_unsettled_blocks():
    """A block missing its state hash on one node is mid-commit, not a
    divergence — the watermark must stop below it."""

    class FakeBlock:
        def __init__(self, index, hashed):
            from babble_tpu.hashgraph import Block

            self._b = Block(index, 1, b"fh", [b"tx"])
            if hashed:
                self._b.body.state_hash = b"H"
            self.body = self._b.body

        def state_hash(self):
            return self._b.body.state_hash

    class FakeStore:
        def __init__(self, blocks):
            self.blocks = blocks

        def last_block_index(self):
            return max(self.blocks)

        def get_block(self, i):
            return self.blocks[i]

    a = FakeStore({0: FakeBlock(0, True), 1: FakeBlock(1, True)})
    b = FakeStore({0: FakeBlock(0, True), 1: FakeBlock(1, False)})
    checker = DivergenceChecker()
    upto = checker.check([("a", a), ("b", b)])
    assert upto == 0  # block 1 not settled on b: not compared yet


# ----------------------------------------------------------------------
# CLI entry point
# ----------------------------------------------------------------------

def test_cli_sim_single_seed(capsys, tmp_path):
    from babble_tpu.cli import main

    rc = main([
        "sim", "--seed", "4", "--plan", "clean",
        "--target-block", "2", "--until", "20",
        "--artifact-dir", str(tmp_path),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert out["seed"] == 4
    assert len(out["digest"]) == 64


def test_cli_sim_plan_file(capsys, tmp_path):
    from babble_tpu.cli import main

    plan_path = tmp_path / "plan.json"
    plan_path.write_text(preset_plan("lossy", 4).to_json())
    rc = main([
        "sim", "--seed", "4", "--plan", str(plan_path),
        "--target-block", "2", "--until", "20",
        "--artifact-dir", str(tmp_path),
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert out["plan"] == "lossy"


# ----------------------------------------------------------------------
# cross-node causal tracing (ISSUE 5): fingerprint determinism, the
# hash-safety differential, fault-plan trace completeness, watchdog
# ----------------------------------------------------------------------

def test_trace_fingerprint_deterministic():
    """Same seed+plan => byte-identical cross-node trace fingerprints and
    stage-latency histogram snapshots: tracing is part of the determinism
    contract, not an exception to it."""
    a = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    b = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    assert a["ok"] and b["ok"]
    assert a["trace_fingerprint"] == b["trace_fingerprint"]
    assert (
        json.dumps(a["stage_latency"], sort_keys=True)
        == json.dumps(b["stage_latency"], sort_keys=True)
    )
    # the fingerprint covers real spans and the stage histograms measured
    # every stage on every node
    counts = [
        snap[name]["series"][""]["count"]
        for snap in a["stage_latency"].values()
        for name in SimCluster.STAGE_HISTOGRAMS
    ]
    assert counts and all(c > 0 for c in counts)


def test_tracing_is_hash_safe_differential():
    """Tracing on vs off must not change what the cluster commits: trace
    context never reaches signed event bytes, so the block digest — the
    replay fingerprint over every committed body — is identical."""
    traced = run_one(7, plan="clean", n=4, until=None, target_block=3)
    untraced = run_one(7, plan="clean", n=4, until=None, target_block=3,
                       tracing=False)
    assert traced["ok"] and untraced["ok"]
    assert traced["digest"] == untraced["digest"]
    assert traced["events_run"] == untraced["events_run"]
    assert traced["virtual_time"] == untraced["virtual_time"]
    # and tracing was actually on in the traced run
    assert traced["trace_fingerprint"] != untraced["trace_fingerprint"]


@pytest.mark.parametrize("preset", ["lossy", "partition_heal", "crash_restart"])
def test_traces_complete_or_cleanly_truncated_under_faults(preset):
    """Under drop/dup/partition/crash faults every assembled cluster
    trace is complete or cleanly truncated: no span references a parent
    span id that is missing from the merged document, and the per-node
    stores stay within their capacity bound."""
    cluster = SimCluster(n=4, seed=7, plan=preset_plan(preset, 4))
    try:
        cluster.run(until=12.0)
        doc = cluster.cluster_trace()
        evs = [e for e in doc["traceEvents"]
               if e.get("args", {}).get("trace")]
        assert evs  # faults thin the traces but cannot erase them all
        span_ids = {e["args"]["span"] for e in evs}
        orphans = [e for e in evs
                   if e["args"].get("parent")
                   and e["args"]["parent"] not in span_ids]
        assert orphans == []
        for sn in cluster.sns:
            assert len(sn.node.obs.traces) <= sn.node.obs.traces.capacity
    finally:
        cluster.shutdown()


def test_watchdog_trips_on_injected_stall():
    """A full four-way partition freezes round advance on every node; the
    watchdog must raise babble_consensus_stalled within one deadline of
    virtual time (stall begins ~t=1, deadline 2s, asserted at t=8)."""
    plan = FaultPlan(
        name="total_partition",
        partitions=(
            Partition(start=1.0, end=99.0,
                      groups=((0,), (1,), (2,), (3,))),
        ),
    )
    cluster = SimCluster(n=4, seed=3, plan=plan, stall_deadline=2.0)
    try:
        cluster.run(until=8.0)
        for sn in cluster.sns:
            snap = sn.node.obs.registry.snapshot()
            assert snap["babble_consensus_stalled"]["series"][""] == 1.0
            # peer gauges were populated from the sync feed, with labels
            health = snap["babble_peer_health"]["series"]
            assert health and all(0.0 <= v <= 1.0 for v in health.values())
    finally:
        cluster.shutdown()


def test_watchdog_quiet_on_healthy_run():
    """Rounds keep advancing on a clean plan — the stall gauge must sit
    at 0 even with a deadline short enough to be trippable."""
    cluster = SimCluster(n=4, seed=5, plan=preset_plan("clean", 4),
                         stall_deadline=2.0)
    try:
        cluster.run(until=12.0)
        for sn in cluster.sns:
            snap = sn.node.obs.registry.snapshot()
            assert snap["babble_consensus_stalled"]["series"][""] == 0.0
    finally:
        cluster.shutdown()


# ----------------------------------------------------------------------
# device-backend differential (ISSUE 6: the queued-mesh dispatch rung
# must commit the same blocks as the CPU engine)
# ----------------------------------------------------------------------

def test_mixed_cpu_and_queued_mesh_cluster_byte_identical():
    """Two CPU nodes and two queued-mesh nodes in ONE cluster. The
    divergence checker byte-compares their settled blocks every 0.5
    virtual seconds, so this is the strictest cross-backend gate the sim
    has: a queued-mesh node whose async dispatch stamped a wrong round,
    or integrated results out of FIFO order, commits different bytes and
    the run raises immediately. Dispatch lag is allowed to shift WHEN a
    mesh node seals (decisions are DAG facts) — the checker compares the
    common settled prefix, so timing skew passes and content skew
    fails."""
    res = run_one(
        7, plan="clean", n=4,
        backend=("cpu", "cpu", "tpu", "tpu"),
        mesh_devices=2,
        dispatch_queue_depth=4,
        dispatch_batch_deadline=0.2,
        until=None, target_block=2,
    )
    assert res["ok"], res["error"]
    assert res["reached_target"]
    assert res["blocks_checked"] >= 2


def test_queued_mesh_run_to_run_deterministic():
    """The queued rung's integration triggers are functions of queue
    occupancy and the call sequence — never of whether a worker thread
    happens to have finished — so two same-seed runs must replay the
    identical schedule: same digest, same causal-trace fingerprint, same
    event count (tpu/dispatch.py's determinism discipline)."""
    kwargs = dict(
        plan="clean", n=4, backend="tpu", mesh_devices=2,
        dispatch_queue_depth=4, dispatch_batch_deadline=0.2,
        until=None, target_block=2,
    )
    a = run_one(9, **kwargs)
    b = run_one(9, **kwargs)
    assert a["ok"] and b["ok"], (a["error"], b["error"])
    assert a["reached_target"] and b["reached_target"]
    assert a["digest"] == b["digest"]
    assert a["trace_fingerprint"] == b["trace_fingerprint"]
    assert a["events_run"] == b["events_run"]
    assert a["virtual_time"] == b["virtual_time"]


def test_sync_mesh_rung_matches_cpu_digest():
    """dispatch_queue_depth=0 disables the queued rung, leaving the sync
    one-shot mesh path — which blocks call-for-call, so decisions land on
    the same serve call as the CPU engine and the two backends produce
    byte-identical committed history for the same seed. (The queued rung
    is excluded from THIS gate on purpose: dispatch lag shifts which
    self-event carries a block signature, signatures are inside event
    hashes, and frame hashes cover event bytes — so cross-RUN digest
    equality only holds for zero-lag rungs; the mixed-cluster test above
    is the queued rung's equality gate.)"""
    cpu = run_one(9, plan="clean", n=4, backend="cpu",
                  until=None, target_block=2)
    mesh = run_one(9, plan="clean", n=4, backend="tpu", mesh_devices=2,
                   dispatch_queue_depth=0,
                   until=None, target_block=2)
    assert cpu["ok"] and mesh["ok"], (cpu["error"], mesh["error"])
    assert cpu["digest"] == mesh["digest"]
    assert cpu["events_run"] == mesh["events_run"]
    assert cpu["virtual_time"] == mesh["virtual_time"]


# ----------------------------------------------------------------------
# round-batched mesh rung (ISSUE 9: one dispatch carries many rounds)
# ----------------------------------------------------------------------

def _rounds_per_dispatch_count(res, node):
    hist = (res["mesh_dispatch"].get(node) or {}).get(
        "babble_mesh_rounds_per_dispatch"
    )
    if not hist:
        return 0
    return sum(s["count"] for s in hist["series"].values())


def test_mixed_cpu_and_round_batched_mesh_cluster_byte_identical():
    """CPU nodes gossiping with ROUND-BATCHED mesh nodes (small
    dispatch_batch_rows so batches actually form and ride the doubling-
    preferred path) under the continuous divergence checker. Batching
    only shifts WHEN a mesh node seals — decisions stay DAG facts — so
    the common settled prefix must stay byte-identical, and the
    rounds-per-dispatch histogram must show the batched rung actually
    integrated dispatches."""
    res = run_one(
        7, plan="clean", n=4,
        backend=("cpu", "cpu", "tpu", "tpu"),
        mesh_devices=2,
        dispatch_queue_depth=4,
        dispatch_batch_deadline=0.2,
        dispatch_batch_rows=8,
        until=None, target_block=2,
    )
    assert res["ok"], res["error"]
    assert res["reached_target"]
    assert res["blocks_checked"] >= 2
    assert (
        _rounds_per_dispatch_count(res, "node2")
        + _rounds_per_dispatch_count(res, "node3")
    ) > 0, "round-batched rung never integrated a dispatch"


def test_round_batched_dispatch_deterministic():
    """Same-seed determinism of the batched rung's NEW observable
    surface: the babble_mesh_rounds_per_dispatch / babble_mesh_batch_rows
    histograms (observed on the serve thread from DAG facts, never from
    worker timing) and the flight-record stream must be byte-identical
    across two runs while batching is active."""
    kwargs = dict(
        plan="clean", n=4, backend="tpu", mesh_devices=2,
        dispatch_queue_depth=4, dispatch_batch_deadline=0.2,
        dispatch_batch_rows=8, until=None, target_block=2,
    )
    a = run_one(11, **kwargs)
    b = run_one(11, **kwargs)
    assert a["ok"] and b["ok"], (a["error"], b["error"])
    assert a["reached_target"] and b["reached_target"]
    assert a["digest"] == b["digest"]
    assert a["mesh_dispatch"] == b["mesh_dispatch"]
    assert a["flightrec_fingerprint"] == b["flightrec_fingerprint"]
    assert sum(
        _rounds_per_dispatch_count(a, f"node{i}") for i in range(4)
    ) > 0, "batching never active — the determinism assertion is vacuous"
