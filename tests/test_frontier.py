"""Differential oracle for the round-frontier DivideRounds: it must match
the level-scan kernel bit-exactly on every DAG — rounds, witness flags,
witness tables, fame and round-received."""

import numpy as np
import pytest

from babble_tpu.tpu import synthetic_grid
from babble_tpu.tpu.engine import run_passes
from babble_tpu.tpu.frontier import (
    build_inv,
    chain_table,
    frontier_pipeline,
    level_lamport,
    sp_index_of,
)


def run_frontier(grid, r_cap):
    ref = run_passes(grid)  # level-scan reference
    rows_by = chain_table(grid)
    inv = build_inv(rows_by, grid.last_ancestors)
    res = frontier_pipeline(
        inv, rows_by, grid.creator, grid.index, sp_index_of(grid),
        grid.last_ancestors, grid.first_descendants,
        level_lamport(grid), grid.coin_bit,
        grid.super_majority, grid.n, r_cap,
    )
    return ref, res


@pytest.mark.parametrize("n,e,seed,zipf,byz", [
    (4, 64, 1, 0.0, 0.0),
    (8, 256, 2, 0.0, 0.0),
    (8, 512, 3, 1.1, 0.0),
    (16, 1024, 4, 1.1, 0.0),
    (8, 300, 7, 2.0, 0.0),  # heavy skew: deep chains, frequent round jumps
    (32, 768, 9, 1.1, 0.0),  # wider validator set (supermajority = 22)
    # adversarial withhold/flush structure (BASELINE config #4's graph
    # shape, bench_scale.py SCALE_CONFIG=4): stale other-parents and
    # bursty chain reveals
    (32, 1024, 11, 1.05, 1.0 / 3.0),
    (64, 2048, 13, 1.05, 1.0 / 3.0),
])
def test_frontier_matches_scan(n, e, seed, zipf, byz):
    grid = synthetic_grid(n, e, seed=seed, zipf_a=zipf, byzantine_frac=byz)
    r_cap = 64
    ref, res = run_frontier(grid, r_cap)

    np.testing.assert_array_equal(np.asarray(res.rounds), ref.rounds)
    np.testing.assert_array_equal(np.asarray(res.witness), ref.witness)
    np.testing.assert_array_equal(np.asarray(res.lamport), ref.lamport)
    assert int(res.last_round) == ref.last_round
    # witness tables agree on every real round
    r = ref.last_round + 1
    np.testing.assert_array_equal(
        np.asarray(res.witness_table)[:r], ref.witness_table[:r]
    )
    # downstream passes agree
    np.testing.assert_array_equal(
        np.asarray(res.fame_decided)[:r], ref.fame_decided[:r]
    )
    np.testing.assert_array_equal(
        np.asarray(res.famous)[:r] & np.asarray(res.fame_decided)[:r],
        ref.famous[:r] & ref.fame_decided[:r],
    )
    np.testing.assert_array_equal(np.asarray(res.received), ref.received)


def test_suffix_min_matches_numpy():
    """suffix_min replaces lax.associative_scan(min, reverse=True), which
    silently corrupts on some platforms at large shapes — pin the exact
    semantics at the shapes the INV build uses."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 3000, size=(4, 5, 2801)).astype(np.int32)
    from babble_tpu.tpu.kernels import suffix_min

    got = np.asarray(suffix_min(x, 3000, axis=2))
    want = np.minimum.accumulate(x[:, :, ::-1], axis=2)[:, :, ::-1]
    np.testing.assert_array_equal(got, want)


def test_m0_binsearch_matches_sort():
    """The two m0 formulations (einsum+sort for small N, binary-search for
    large N — frontier.M0_BINSEARCH_MIN_N) must agree exactly: force the
    binsearch path on small-N grids and differential the walk against the
    sort-based walk. Calls the UNJITTED walk — the jitted pipeline's cache
    does not key on the module flag, so a monkeypatched run through it
    could silently reuse the sort-path executable."""
    from babble_tpu.tpu import frontier

    orig = frontier.M0_BINSEARCH_MIN_N
    try:
        for n, e, seed, zipf in [(8, 256, 2, 0.0), (16, 1024, 4, 1.1),
                                 (8, 300, 7, 2.0)]:
            grid = synthetic_grid(n, e, seed=seed, zipf_a=zipf)
            import jax.numpy as jnp

            rows_by = chain_table(grid)
            inv = build_inv(rows_by, grid.last_ancestors)
            args = (
                inv, jnp.asarray(rows_by), jnp.asarray(grid.creator),
                jnp.asarray(grid.index), jnp.asarray(sp_index_of(grid)),
                jnp.asarray(grid.first_descendants), grid.super_majority, 64,
            )
            la_dev = jnp.asarray(grid.last_ancestors)
            frontier.M0_BINSEARCH_MIN_N = 1 << 30  # force sort
            a = frontier._frontier_rounds(*args, la=la_dev)
            frontier.M0_BINSEARCH_MIN_N = 1  # force binsearch
            b = frontier._frontier_rounds(*args, la=la_dev)
            np.testing.assert_array_equal(np.asarray(a.rounds), np.asarray(b.rounds))
            np.testing.assert_array_equal(np.asarray(a.witness), np.asarray(b.witness))
            np.testing.assert_array_equal(
                np.asarray(a.witness_table), np.asarray(b.witness_table)
            )
            assert int(a.last_round) == int(b.last_round)
    finally:
        frontier.M0_BINSEARCH_MIN_N = orig


def test_level_lamport_matches_reference():
    """The vectorized level-table scatter must equal the per-level loop it
    replaced — including ragged level rows, whose -1 pad slots carry no
    scatter — and agree with the exact kernel's lamports on base grids."""
    from babble_tpu.tpu.grid import synthetic_deep_grid

    grids = [
        synthetic_grid(4, 64, seed=1),
        synthetic_grid(16, 1024, seed=4, zipf_a=1.1),
        synthetic_deep_grid(6, 128, seed=2, zipf_a=1.2),
    ]
    for grid in grids:
        ref = np.zeros(grid.e, dtype=np.int32)
        for lvl in range(grid.num_levels):
            for ev in grid.levels[lvl]:
                if ev >= 0:
                    ref[ev] = lvl
        np.testing.assert_array_equal(level_lamport(grid), ref)
    base = grids[1]
    np.testing.assert_array_equal(
        level_lamport(base), np.asarray(run_passes(base).lamport)
    )
