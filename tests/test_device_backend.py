"""Live-node integration of the device consensus backend.

The strongest oracle is the MIXED cluster: nodes running the CPU engine and
nodes running the device engine participate in the SAME network, so every
block body must come out byte-identical across backends on the very same
DAG (the check_gossip discipline of reference src/node/node_test.go:741-771,
upgraded from cross-node to cross-backend).

Also covers the post-reset path: a device-backend node that joins late
fast-forwards (Reset + section replay) and must keep committing through the
device engine afterwards — the state VERDICT r1 flagged as fatal
(GridUnsupported on any post-reset state).
"""

import copy
import pytest

from babble_tpu.crypto import generate_key, pub_key_bytes
from babble_tpu.hashgraph import InmemStore
from babble_tpu.net import InmemTransport
from babble_tpu.node import Config, Node
from babble_tpu.peers import Peer, Peers
from babble_tpu.proxy import InmemDummyClient

from test_node import (
    bombard_and_wait,
    check_gossip,
    run_nodes,
    shutdown_nodes,
)
from test_fastsync import connect_transport, first_available_block


def make_config(backend="tpu", sync_limit=150):
    return Config(
        heartbeat_timeout=0.005,
        tcp_timeout=1.0,
        cache_size=1000,
        sync_limit=sync_limit,
        consensus_backend=backend,
    )


def build_mixed_cluster(backends, sync_limit=150, mesh_devices=None):
    """One node per entry of `backends` ("cpu" | "tpu"), full-mesh inmem.
    `mesh_devices` optionally maps node index -> chip count for the
    sharded device backend (node.Config.mesh_devices)."""
    n = len(backends)
    keys = [generate_key() for _ in range(n)]
    participants = Peers()
    peer_list = []
    for i, key in enumerate(keys):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr=f"127.0.0.1:{9990 + i}", pub_key_hex=pub_hex)
        participants.add_peer(peer)
        peer_list.append(peer)

    transports = [InmemTransport(p.net_addr, timeout=5.0) for p in peer_list]
    for t in transports:
        for u in transports:
            if t is not u:
                t.connect(u.local_addr(), u)

    nodes, proxies = [], []
    for i, key in enumerate(keys):
        conf = make_config(backend=backends[i], sync_limit=sync_limit)
        if mesh_devices and i in mesh_devices:
            conf.mesh_devices = mesh_devices[i]
        prox = InmemDummyClient()
        node = Node(
            copy.copy(conf), peer_list[i].id, key, participants,
            InmemStore(participants, conf.cache_size), transports[i], prox,
        )
        node.init()
        nodes.append(node)
        proxies.append(prox)
    return nodes, proxies, keys, peer_list, participants, transports


def test_device_backend_cluster():
    """All-device 4-node cluster reaches blocks; no silent CPU fallback."""
    nodes, proxies, *_ = build_mixed_cluster(["tpu"] * 4)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)
        check_gossip(nodes, upto=2)
        for node in nodes:
            assert node.core.device_consensus_runs > 0, (
                f"node {node.id} never ran the device engine"
            )
            assert node.core.device_consensus_fallbacks == 0, (
                f"node {node.id} silently fell back to CPU "
                f"{node.core.device_consensus_fallbacks} times"
            )
    finally:
        shutdown_nodes(nodes)


def test_mixed_backend_cluster_byte_identical():
    """2 CPU + 2 device nodes in one network: every block body byte-equal
    across backends, and the app state hashes agree at every block."""
    nodes, proxies, *_ = build_mixed_cluster(["cpu", "tpu", "cpu", "tpu"])
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=3, timeout_s=180)
        check_gossip(nodes, upto=3)
        for i in range(3 + 1):
            hashes = {n.get_block(i).state_hash() for n in nodes}
            assert len(hashes) == 1, f"state hash diverged at block {i}"
        for node in (nodes[1], nodes[3]):
            assert node.core.device_consensus_runs > 0
            assert node.core.device_consensus_fallbacks == 0
    finally:
        shutdown_nodes(nodes)


def test_pipelined_fetch_cluster_byte_identical(monkeypatch):
    """VERDICT r3 #2: with the device->host result fetch forced OFF the
    consensus critical path (pipelined discipline — decisions integrate
    one sync late), a mixed cpu/tpu cluster must still commit
    byte-identical blocks: reception/fame values are DAG facts, so the
    lag shifts only WHEN a block seals, never what goes into it. Also
    forces rebases (tiny round axis) so the rebase-between-integrations
    ordering is exercised under lag."""
    from babble_tpu.tpu import live as live_mod

    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "async_fetch", True)
    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "r_cap", 16)

    nodes, proxies, *_ = build_mixed_cluster(
        ["cpu", "tpu", "cpu", "tpu"], sync_limit=2000
    )
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=12, timeout_s=300)
        check_gossip(nodes, upto=12)
        pipelined = 0
        for node in (nodes[1], nodes[3]):
            assert node.core.device_consensus_runs > 0
            eng = getattr(node.core.hg, "_live_device_engine", None)
            if eng is not None and eng.async_fetch:
                pipelined += 1
        assert pipelined > 0, "no node ran the pipelined fetch discipline"
    finally:
        shutdown_nodes(nodes)


def test_device_backend_rebases_past_round_capacity(monkeypatch):
    """A live device engine with a tiny round axis must REBASE through it
    (round_base advances, not a CPU fallback) while the mixed cluster's
    blocks stay byte-identical — the streaming/windowing axis of
    SURVEY §5 and BASELINE config #5 at live-node scale."""
    from babble_tpu.tpu import live as live_mod

    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "r_cap", 16)
    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "e_cap", 4096)
    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "e_win", 4096)

    # sync_limit large enough that ordinary virtual-device dispatch lag
    # doesn't flip nodes into CatchingUp, but finite so a genuinely
    # stuck node can still escape via fast-sync instead of deadlocking
    # against the others' rolled windows
    nodes, proxies, *_ = build_mixed_cluster(
        ["cpu", "tpu", "tpu", "tpu"], sync_limit=2000
    )
    try:
        run_nodes(nodes)
        # past the 16-round device axis: forces rebases (the trigger
        # fires at shifted round r_cap - 8 = 8). Kept modest: on the
        # virtual CPU device every sync pays a real dispatch, and too
        # ambitious a target can starve the slowest node of gossip.
        bombard_and_wait(nodes, proxies, target_block=15, timeout_s=300)
        # byte-equality across backends is unconditional
        check_gossip(nodes, upto=15)
        # under adversarial timing an engine may legitimately retire
        # through its safety valves (fast-sync reset, late-witness latch,
        # host-frozen round) — but the round-axis WINDOWING must have
        # carried at least one node past the tiny r_cap: either an
        # in-place rebase or a drop-and-re-attach (the healing path),
        # both of which advance round_base past the initial window
        windowed = [
            eng for node in nodes[1:]
            if (eng := getattr(node.core.hg, "_live_device_engine", None))
            is not None and eng.round_base > 0
        ]
        assert windowed, "no device node survived past r_cap via windowing"
    finally:
        shutdown_nodes(nodes)


def test_device_backend_survives_fast_sync():
    """A device-backend node killed and recycled must fast-forward (Reset +
    section replay) and KEEP running the device engine on the post-reset
    hashgraph — byte-identical to the rest of the cluster."""
    nodes, proxies, keys, peer_list, participants, transports = (
        build_mixed_cluster(["tpu"] * 4)
    )
    conf = make_config()
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)

        victim = nodes[3]
        victim.shutdown()
        transports[3].disconnect_all()
        for t in transports[:3]:
            t.disconnect(transports[3].local_addr())

        # run the survivors beyond the joiner's sync limit
        goal_ahead = max(n.core.get_last_block_index() for n in nodes[:3]) + 3
        while True:
            bombard_and_wait(
                nodes[:3], proxies[:3], target_block=goal_ahead, timeout_s=180
            )
            total_events = sum(
                i + 1 for i in nodes[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            goal_ahead += 1

        trans = InmemTransport(peer_list[3].net_addr, timeout=5.0)
        connect_transport(transports[:3], trans)
        transports[3] = trans
        prox = InmemDummyClient()
        node = Node(
            conf, peer_list[3].id, keys[3], participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes[3] = node
        proxies[3] = prox
        node.run_async(True)

        # generous: under full-suite load the joiner may need several
        # fast-forward attempts while the survivors keep racing ahead
        goal = goal_ahead + 5
        bombard_and_wait(nodes, proxies, target_block=goal, timeout_s=240)
        # compare over the committed range every node shares: the joiner's
        # anchor may sit above `goal` if the survivors raced ahead
        upto = min(n.core.get_last_block_index() for n in nodes)
        start = first_available_block(node, upto)
        check_gossip(nodes, from_block=start, upto=upto)

        # the recycled node must have committed through the device engine
        # on its post-reset hashgraph, with no CPU fallback
        assert node.core.device_consensus_runs > 0
        assert node.core.device_consensus_fallbacks == 0
    finally:
        shutdown_nodes(nodes)


def test_mixed_backend_fast_sync_byte_identical():
    """VERDICT r3 #1 closure: a MIXED cluster (cpu and tpu backends in the
    same network) where a tpu node is killed, left behind past the sync
    limit, and rejoins by fast-sync UNDER LIVE TRAFFIC — every block body
    in the shared committed range must be byte-equal across all four
    nodes (the check_gossip oracle of reference
    src/node/node_test.go:741-772, crossed with both backend and
    post-reset state)."""
    nodes, proxies, keys, peer_list, participants, transports = (
        build_mixed_cluster(["cpu", "tpu", "cpu", "tpu"])
    )
    conf = make_config()
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)

        victim = nodes[3]
        victim.shutdown()
        transports[3].disconnect_all()
        for t in transports[:3]:
            t.disconnect(transports[3].local_addr())

        # run the survivors beyond the joiner's sync limit
        goal_ahead = max(n.core.get_last_block_index() for n in nodes[:3]) + 3
        while True:
            bombard_and_wait(
                nodes[:3], proxies[:3], target_block=goal_ahead, timeout_s=180
            )
            total_events = sum(
                i + 1 for i in nodes[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            goal_ahead += 1

        trans = InmemTransport(peer_list[3].net_addr, timeout=5.0)
        connect_transport(transports[:3], trans)
        transports[3] = trans
        prox = InmemDummyClient()
        node = Node(
            conf, peer_list[3].id, keys[3], participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes[3] = node
        proxies[3] = prox
        node.run_async(True)

        # live traffic while the joiner catches up: trickle submissions
        # (full bombardment saturates the survivors' core locks and
        # starves the joiner's FastForwardRequests — see the reattach
        # test below); consensus needs SOME traffic to integrate it
        import random as _random
        import time as _time

        from test_node import load_scale

        deadline = _time.monotonic() + 240 * load_scale()
        goal = goal_ahead + 5
        while _time.monotonic() < deadline:
            if min(n.core.get_last_block_index() for n in nodes) >= goal:
                break
            k = _random.randrange(3)
            proxies[k].submit_tx(f"mixed-join-{_time.monotonic()}".encode())
            _time.sleep(0.1)
        assert min(n.core.get_last_block_index() for n in nodes) >= goal, (
            f"joiner failed to catch up: indices="
            f"{[n.core.get_last_block_index() for n in nodes]}"
        )
        upto = min(n.core.get_last_block_index() for n in nodes)
        start = first_available_block(node, upto)
        check_gossip(nodes, from_block=start, upto=upto)
        assert node.core.device_consensus_runs > 0
    finally:
        shutdown_nodes(nodes)


@pytest.mark.slow
def test_live_engine_reattaches_after_fast_sync():
    """VERDICT r2 #4: demotions must heal. A device-backend node that
    fast-syncs must RETURN to the incremental live engine afterwards (via
    the frontier attach on its post-reset state), with the demotion and
    re-attach visible in the core counters."""
    nodes, proxies, keys, peer_list, participants, transports = (
        build_mixed_cluster(["tpu"] * 4)
    )
    conf = make_config()
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=2, timeout_s=180)

        victim = nodes[3]
        victim.shutdown()
        transports[3].disconnect_all()
        for t in transports[:3]:
            t.disconnect(transports[3].local_addr())

        goal_ahead = max(n.core.get_last_block_index() for n in nodes[:3]) + 3
        while True:
            bombard_and_wait(
                nodes[:3], proxies[:3], target_block=goal_ahead, timeout_s=180
            )
            total_events = sum(
                i + 1 for i in nodes[0].core.known_events().values()
            )
            if total_events > conf.sync_limit + 50:
                break
            goal_ahead += 1

        trans = InmemTransport(peer_list[3].net_addr, timeout=5.0)
        connect_transport(transports[:3], trans)
        transports[3] = trans
        prox = InmemDummyClient()
        node = Node(
            conf, peer_list[3].id, keys[3], participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes[3] = node
        proxies[3] = prox
        node.run_async(True)

        # Rejoin under TRICKLE traffic, not full bombardment: the
        # survivors run at a 5ms heartbeat and saturate their core locks
        # when blasted with transactions, so the joiner's
        # FastForwardRequests queue behind gossip and time out while the
        # survivors' height compounds away from it (observed: survivors
        # at block 2481, joiner pinned at 11 for 9 minutes). A join under
        # saturation is a known limitation of the 5s-timeout in-memory
        # transport, not the property under test; consensus still needs
        # SOME traffic for the joiner to integrate.
        import random as _random
        import time as _time

        from test_node import load_scale

        deadline = _time.monotonic() + 240 * load_scale()
        goal = goal_ahead + 5
        while _time.monotonic() < deadline:
            if min(n.core.get_last_block_index() for n in nodes) >= goal:
                break
            k = _random.randrange(3)
            proxies[k].submit_tx(f"join-tx-{_time.monotonic()}".encode())
            _time.sleep(0.1)
        assert min(n.core.get_last_block_index() for n in nodes) >= goal, (
            f"joiner failed to catch up: indices="
            f"{[n.core.get_last_block_index() for n in nodes]}"
        )
        upto = min(n.core.get_last_block_index() for n in nodes)
        start = first_available_block(node, upto)
        check_gossip(nodes, from_block=start, upto=upto)

        # the joiner fast-forwarded (possibly repeatedly while the
        # survivors raced ahead); once it settles into Babbling, the live
        # engine must attach on its post-reset hashgraph — poll with
        # traffic flowing, the attach needs consensus calls to happen

        deadline = _time.monotonic() + 240 * load_scale()
        target = upto + 2
        while _time.monotonic() < deadline:
            if getattr(node.core.hg, "_live_device_engine", None) is not None:
                break
            bombard_and_wait(nodes, proxies, target_block=target, timeout_s=240)
            target += 1
        eng = getattr(node.core.hg, "_live_device_engine", None)
        assert eng is not None, (
            "live engine did not re-attach after fast-sync "
            f"(demotions={node.core.live_demotions}, "
            f"calls={node.core._consensus_calls}, "
            f"state={node.get_state()})"
        )
        # ... and KEEPS serving (the r05 joiner-liveness gap): runs must
        # grow on the SAME attached engine with no fresh demotion —
        # device_consensus_runs alone would also count one-shot ladder
        # runs after a silent drop, which is exactly the gap
        runs_before = node.core.device_consensus_runs
        demotions_at_attach = node.core.live_demotions
        deadline = _time.monotonic() + 120 * load_scale()
        while (
            node.core.device_consensus_runs <= runs_before
            and _time.monotonic() < deadline
        ):
            target += 1
            bombard_and_wait(nodes, proxies, target_block=target, timeout_s=240)
        assert node.core.device_consensus_runs > runs_before
        assert getattr(node.core.hg, "_live_device_engine", None) is eng, (
            "live engine dropped again after re-attach "
            f"(demotions={node.core.live_demotions})"
        )
        assert node.core.live_demotions == demotions_at_attach, (
            "fresh demotion after re-attach: the engine is flapping, "
            "not serving"
        )
    finally:
        shutdown_nodes(nodes)


def test_live_engine_attaches_large_history(monkeypatch):
    """VERDICT r2 #4: a node whose DAG exceeds the write-back window must
    attach via the frontier assembly (kept rows = undecided frontier), not
    refuse. Round-2 behavior was GridUnsupported('DAG exceeds the
    write-back window')."""
    from babble_tpu.tpu import live as live_mod

    nodes, proxies, *_ = build_mixed_cluster(["cpu"] * 4)
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=28, timeout_s=300)
    finally:
        shutdown_nodes(nodes)

    hg = nodes[0].core.hg
    total = sum(i + 1 for i in hg.store.known_events().values())
    # shrink the window BELOW the DAG size: the old bootstrap would refuse
    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "e_win", 256)
    monkeypatch.setitem(live_mod.ENGINE_DEFAULTS, "batch_cap", 16)
    assert total > 256, f"test DAG too small ({total} events)"

    eng = live_mod.LiveDeviceEngine(hg)
    try:
        assert len(eng.hashes) < total, "frontier attach kept the full DAG"
        assert len(eng.hashes) <= 256
        # kept rows' device rounds must mirror the store (base-relative)
        import numpy as np

        rounds = np.asarray(eng.state.rounds)
        for h, row in list(eng.row_of.items())[:50]:
            ev = hg.store.get_event(h)
            if ev.round is not None:
                assert rounds[row] == ev.round - eng.round_base
    finally:
        eng.detach()
