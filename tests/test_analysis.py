"""Static-analysis framework tests (babble_tpu/analysis/, docs/analysis.md).

Each checker family is exercised against seeded fixture modules laid out
under a temp root mimicking the package structure (scope classification
keys off the repo-relative path), asserting exact rule/file/line, waiver
suppression, and the baseline machinery. The last tests run the real
gate against the real repo: it must be green with an EMPTY baseline.
"""

from __future__ import annotations

import os
import textwrap
from pathlib import Path

import pytest

from babble_tpu.analysis import runner
from babble_tpu.analysis.core import SourceFile, split_baselined
from babble_tpu.analysis.runner import main as lint_main, run_lint

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def _write(root: Path, relpath: str, source: str) -> Path:
    p = root / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return p


def _lint(root: Path, **kw):
    kw.setdefault("baseline_path", None)
    return run_lint(str(root), **kw)


def _findings(root: Path, relpath: str, source: str):
    _write(root, relpath, source)
    return _lint(root).new


# ---------------------------------------------------------------------------
# determinism lint
# ---------------------------------------------------------------------------


def test_det_wallclock_exact_location(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def deadline(seconds):
            return time.monotonic() + seconds
        """,
    )
    assert [(f.rule, f.path, f.line) for f in found] == [
        ("det-wallclock", "babble_tpu/node/fixture.py", 4)
    ]
    assert "Clock seam" in found[0].message


def test_det_wallclock_applies_package_wide_but_perf_counter_exempt(tmp_path):
    # utils/ is not consensus-critical, yet wallclock is still flagged;
    # perf_counter (duration-only) never is
    found = _findings(
        tmp_path, "babble_tpu/utils/fixture.py", """\
        import time

        def f():
            t0 = time.perf_counter()
            time.sleep(0.1)
            return time.perf_counter() - t0
        """,
    )
    assert [(f.rule, f.line) for f in found] == [("det-wallclock", 5)]


def test_det_wallclock_sees_through_import_alias(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        import time as _t
        from time import monotonic as now

        def f():
            return _t.time() + now()
        """,
    )
    assert [(f.rule, f.line) for f in found] == [
        ("det-wallclock", 5), ("det-wallclock", 5),
    ]


def test_det_rules_scoped_to_consensus_critical(tmp_path):
    source = """\
    import random

    def pick(xs):
        random.shuffle(xs)
        h = hash(tuple(xs))
        for x in {1, 2, 3}:
            h += x
        return h
    """
    # in hashgraph/: random + builtin-hash + set-order all fire
    crit = _findings(tmp_path, "babble_tpu/hashgraph/fixture.py", source)
    assert sorted((f.rule, f.line) for f in crit) == [
        ("det-builtin-hash", 5),
        ("det-random", 4),
        ("det-set-order", 6),
    ]
    # the same code outside the consensus-critical scope: silent
    (tmp_path / "babble_tpu/hashgraph/fixture.py").unlink()
    relaxed = _findings(tmp_path, "babble_tpu/utils/fixture.py", source)
    assert relaxed == []


def test_det_set_order_tracks_assigned_names_and_sorted_is_clean(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        def order(events):
            pending = set(events)
            for e in sorted(pending):
                yield e
            for e in pending:
                yield e
        """,
    )
    assert [(f.rule, f.line) for f in found] == [("det-set-order", 5)]


def test_det_waiver_requires_reason(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def f():
            a = time.monotonic()  # det-ok: duration fixture, cannot schedule
            b = time.monotonic()  # det-ok:
            return a + b
        """,
    )
    # the bare tag (no reason after the colon) does NOT suppress
    assert [(f.rule, f.line) for f in found] == [("det-wallclock", 5)]


def test_generic_lint_ok_waiver_and_comment_above(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def f():
            # lint-ok: fixture exercising the comment-above waiver form
            a = time.monotonic()
            return a
        """,
    )
    assert found == []


# ---------------------------------------------------------------------------
# lock-discipline checker
# ---------------------------------------------------------------------------

LOCK_FIXTURE = """\
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._count += 1

    def peek(self):
        return self._count

    def _bump_locked(self):  # requires-lock: _lock
        self._count += 1

    def waived(self):
        return self._count  # unguarded-ok: stale reads acceptable in stats

    def deferred(self):
        with self._lock:
            def later():
                return self._count
            return later
"""


def test_lock_guarded_by_seeded_violation(tmp_path):
    found = _findings(tmp_path, "babble_tpu/net/fixture.py", LOCK_FIXTURE)
    # peek() reads outside the lock (line 14); later() runs after the
    # with-block exits, so the definition-site lock does not count (25).
    # bump (locked), __init__ (exempt), _bump_locked (requires-lock) and
    # waived (reasoned waiver) are all clean.
    assert [(f.rule, f.line, f.symbol) for f in found] == [
        ("lock-guarded-by", 14, "Box.peek"),
        ("lock-guarded-by", 25, "Box.deferred"),
    ]
    assert "guarded-by _lock" in found[0].message


def test_lock_scope_does_not_cover_uncontended_modules(tmp_path):
    # same fixture under tpu/ (outside LOCK_SCOPE_PREFIXES): no findings
    found = _findings(tmp_path, "babble_tpu/tpu/fixture.py", LOCK_FIXTURE)
    assert [f for f in found if f.rule == "lock-guarded-by"] == []


def test_lock_condition_objects_work_as_locks(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        import threading


        class Tracker:
            def __init__(self):
                self._n = 0  # guarded-by: _cv
                self._cv = threading.Condition()

            def inc(self):
                with self._cv:
                    self._n += 1
                    self._cv.notify_all()

            def racy(self):
                return self._n
        """,
    )
    assert [(f.rule, f.line) for f in found] == [("lock-guarded-by", 15)]


# ---------------------------------------------------------------------------
# JAX staging audit
# ---------------------------------------------------------------------------


def test_jax_tracer_branch_seeded_violation(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        import functools
        import jax
        import jax.numpy as jnp


        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x


        @functools.partial(jax.jit, static_argnames=("flip",))
        def ok_static(x, flip):
            if flip:
                return -x
            return x


        @jax.jit
        def ok_probe(x, aux=None):
            if aux is None:
                return x
            return x + aux
        """,
    )
    assert [(f.rule, f.line, f.symbol) for f in found] == [
        ("jax-tracer-branch", 8, "bad")
    ]


def test_jax_wrapped_form_and_host_sync(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        import jax
        import jax.numpy as jnp
        import numpy as np


        def kernel(x):
            y = jnp.cumsum(x)
            n = y[-1].item()
            host = np.asarray(y)
            return host[:1], n


        kernel_jit = jax.jit(kernel)
        """,
    )
    assert sorted((f.rule, f.line) for f in found) == [
        ("jax-host-sync", 8),
        ("jax-host-sync", 9),
    ]


def test_jax_float_order_and_waiver(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def bad(r):
            return r.astype(jnp.float32) < 2.0


        @jax.jit
        def waived(r):
            return r.astype(jnp.float32) < 2.0  # jax-ok: fixture, bounded < 2^24


        @jax.jit
        def matmul_cast_is_fine(a, b):
            return jnp.einsum("ij,jk->ik", a.astype(jnp.float32), b.astype(jnp.float32))
        """,
    )
    assert [(f.rule, f.line) for f in found] == [("jax-float-order", 7)]


def test_jax_shard_mapped_function_host_sync(tmp_path):
    # shard_map discovery: the sharded backend builds its per-shard
    # device functions inside cached factories (tpu/sharded.py idiom),
    # so discovery must catch `shard_map(f, ...)` anywhere in the module
    # — including nested defs and the aliased/wrapped spellings — and
    # audit every parameter as a tracer (no static_argnames channel).
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        import numpy as np
        from jax.experimental.shard_map import shard_map as _exp_shard_map


        def _fame_factory(mesh, specs):
            def local_fame(votes, decided):
                n = int(votes[0, 0])
                if decided:
                    return votes
                return np.asarray(votes)

            return _exp_shard_map(
                local_fame, mesh=mesh, in_specs=specs, out_specs=specs
            )


        def unmapped_helper(votes):
            return np.asarray(votes)
        """,
    )
    assert sorted((f.rule, f.line) for f in found) == [
        ("jax-host-sync", 7),       # int() on a shard_map tracer
        ("jax-host-sync", 10),      # np.asarray mid-kernel
        ("jax-tracer-branch", 8),   # `if decided:` on a tracer
    ]
    assert all(f.symbol == "local_fame" for f in found)


def test_jax_rules_only_inside_staged_functions(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/tpu/fixture.py", """\
        import numpy as np


        def plain_host_helper(x):
            if x > 0:
                return np.asarray(x).item()
            return 0
        """,
    )
    assert [f for f in found if f.rule.startswith("jax-")] == []


# ---------------------------------------------------------------------------
# observability lint
# ---------------------------------------------------------------------------


def test_obs_dynamic_name_and_label_decl(tmp_path):
    found = _findings(
        tmp_path, "babble_tpu/utils/fixture.py", """\
        def instrument(obs, kind, names):
            obs.counter(f"babble_{kind}_total", "computed name")
            obs.histogram("babble_ok_seconds", "y", labels=names)
            good = obs.gauge("babble_fine", "z", labels=("state",))
            return good
        """,
    )
    assert sorted((f.rule, f.line) for f in found) == [
        ("obs-dynamic-name", 2),
        ("obs-label-decl", 3),
    ]
    assert "static string literals" in found[0].message


def test_obs_rules_apply_package_wide_with_waiver(tmp_path):
    # node/ and net/ are in scope too (the rules run wherever the
    # determinism lint runs), and a reasoned obs-ok waiver suppresses
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        def decl(registry, suffix):
            registry.counter("x_" + suffix, "a")  # obs-ok: fixture, bounded by caller enum
            registry.counter("y_" + suffix, "b")
        """,
    )
    assert [(f.rule, f.line) for f in found] == [("obs-dynamic-name", 3)]


def test_obs_ignores_foreign_receivers(tmp_path):
    # .histogram() on a non-obs receiver (e.g. a dataframe) is not ours
    found = _findings(
        tmp_path, "babble_tpu/utils/fixture.py", """\
        def plot(df, col):
            return df.histogram(col, bins=10)
        """,
    )
    assert [f for f in found if f.rule.startswith("obs-")] == []


def test_obs_trace_static_name_rule(tmp_path):
    # span emissions on obs/tracer receivers need literal names; a
    # reasoned waiver suppresses, foreign receivers are not ours
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        def emit(obs, tracer, phase, writer):
            obs.tracer.record("consensus." + phase, 0.0, 1.0)
            tracer.record("gossip", 0.0, 1.0)
            with obs.span(f"dyn.{phase}"):
                pass
            tracer.record("x." + phase, 0.0, 1.0)  # obs-ok: phases are a literal enum
            writer.record(phase, 0.0, 1.0)
        """,
    )
    assert sorted((f.rule, f.line) for f in found) == [
        ("obs-trace-static-name", 2),
        ("obs-trace-static-name", 4),
    ]
    assert "static string literals" in found[0].message


def test_obs_ctx_in_event_rule(tmp_path):
    # trace vocabulary in hashgraph/event.py is a finding (identifiers,
    # parameters, key-like strings); prose docstrings stay free to
    # mention tracing, and the same code elsewhere is not flagged
    src = """\
        '''Signed bodies never carry causal traces - prose is fine.'''
        def marshal(self, trace_id):
            body = {"Traces": trace_id}
            return body
        """
    found = _findings(tmp_path, "babble_tpu/hashgraph/event.py", src)
    ctx = [f for f in found if f.rule == "obs-ctx-in-event"]
    assert {f.line for f in ctx} == {2, 3}
    assert any("trace_id" in f.message for f in ctx)
    assert any("Traces" in f.message for f in ctx)

    other = tmp_path / "elsewhere"
    found2 = _findings(other, "babble_tpu/node/fixture.py", src)
    assert [f for f in found2 if f.rule == "obs-ctx-in-event"] == []


def test_obs_flightrec_static_name_rule(tmp_path):
    # flight-recorder emissions need literal record names (they feed the
    # record catalog and the sim's flightrec fingerprint); a reasoned
    # waiver suppresses, foreign .record receivers are not ours
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        def emit(obs, flightrec, kind, db):
            obs.flightrec.record("ladder." + kind, rung="live")
            flightrec.record("watchdog.stall", waited=1.0)
            flightrec.record(f"dyn.{kind}")  # obs-ok: kinds are a literal enum
            recorder.record(kind)
            db.record(kind)
        """,
    )
    flight = [f for f in found if f.rule == "obs-flightrec-static-name"]
    assert [(f.rule, f.line) for f in flight] == [
        ("obs-flightrec-static-name", 2),
        ("obs-flightrec-static-name", 5),
    ]
    assert "static string literals" in flight[0].message


def test_obs_slo_decl_rule(tmp_path):
    # SLO declarations need literal objective names AND literal series;
    # foreign .objective receivers are not ours
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        def declare(slo, name, series, planner):
            slo.objective(name, series="babble_x_seconds",
                          kind="p_below", threshold=1.0)
            slo.objective("commit_p99", series=series,
                          kind="p_below", threshold=1.0)
            slo.objective("good", series="babble_y_seconds",
                          kind="below", threshold=2.0)
            planner.objective(name)
        """,
    )
    decls = [f for f in found if f.rule == "obs-slo-decl"]
    assert [(f.rule, f.line) for f in decls] == [
        ("obs-slo-decl", 2),
        ("obs-slo-decl", 4),
    ]
    assert any("series=" in f.message for f in decls)


def test_obs_prov_static_name_rule(tmp_path):
    # provenance marks need literal names (they feed the mark catalog
    # and the provenance stream fingerprint); a reasoned waiver
    # suppresses, foreign .mark receivers are not ours
    found = _findings(
        tmp_path, "babble_tpu/node/fixture.py", """\
        def emit(obs, prov, kind, parser):
            obs.provenance.mark("prov." + kind, cells=1)
            prov.mark("prov.capture", engine="live")
            prov.mark(f"dyn.{kind}")  # obs-ok: kinds are a literal enum
            parser.mark(kind)
        """,
    )
    marks = [f for f in found if f.rule == "obs-prov-static-name"]
    assert [(f.rule, f.line) for f in marks] == [
        ("obs-prov-static-name", 2),
    ]
    assert "static string literals" in marks[0].message


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def test_baseline_suppresses_then_duplicate_fails(tmp_path):
    rel = "babble_tpu/node/fixture.py"
    _write(tmp_path, rel, """\
        import time

        def f():
            return time.monotonic()
        """)
    baseline = tmp_path / "baseline.json"

    first = run_lint(str(tmp_path), baseline_path=str(baseline),
                     update_baseline=True)
    assert len(first.baselined) == 1 and baseline.exists()

    gated = run_lint(str(tmp_path), baseline_path=str(baseline))
    assert gated.ok and len(gated.baselined) == 1

    # the fingerprint is line-number independent: shifting the finding
    # down keeps it suppressed...
    _write(tmp_path, rel, """\
        import time


        def f():
            return time.monotonic()
        """)
    assert run_lint(str(tmp_path), baseline_path=str(baseline)).ok

    # ...but each entry pays for at most ONE finding: duplicating the
    # baselined pattern fails the gate
    _write(tmp_path, rel, """\
        import time

        def f():
            return time.monotonic()

        def g():
            return time.monotonic()
        """)
    dup = run_lint(str(tmp_path), baseline_path=str(baseline))
    assert not dup.ok and len(dup.new) == 1 and len(dup.baselined) == 1


def test_split_baselined_matches_on_symbol_and_text(tmp_path):
    _write(tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def f():
            return time.monotonic()
        """)
    sf = SourceFile.parse(
        str(tmp_path / "babble_tpu/node/fixture.py"),
        "babble_tpu/node/fixture.py",
    )
    [finding] = runner.lint_file(sf)
    pair = [(finding, sf.line_text(finding.line))]
    fp = finding.fingerprint(sf.line_text(finding.line))
    assert fp["symbol"] == "f" and fp["text"] == "return time.monotonic()"
    new, old = split_baselined(pair, [fp])
    assert (new, [f.rule for f in old]) == ([], ["det-wallclock"])
    # a different symbol does not match
    new, old = split_baselined(pair, [dict(fp, symbol="g")])
    assert [f.rule for f in new] == ["det-wallclock"] and old == []


def test_syntax_error_is_reported_not_fatal(tmp_path):
    _write(tmp_path, "babble_tpu/node/fixture.py", "def broken(:\n")
    result = _lint(tmp_path)
    assert not result.ok and result.errors and result.new == []


# ---------------------------------------------------------------------------
# the real repo gate + CLI
# ---------------------------------------------------------------------------


def test_repo_is_lint_clean_with_empty_baseline():
    # the shipped baseline must stay empty: every real finding is fixed
    # or carries a reasoned waiver at the site
    assert runner.load_baseline is not None
    from babble_tpu.analysis.core import load_baseline

    assert load_baseline(runner.DEFAULT_BASELINE) == []
    result = run_lint(REPO_ROOT, baseline_path=None)
    assert result.errors == []
    assert [f.location() for f in result.new] == []
    assert result.files_checked > 50


def test_cli_exit_codes(tmp_path, capsys, monkeypatch):
    assert lint_main(["--no-baseline"], root=REPO_ROOT) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out

    _write(tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def f():
            return time.monotonic()
        """)
    assert lint_main(["--no-baseline"], root=str(tmp_path)) == 1
    out = capsys.readouterr().out
    assert "babble_tpu/node/fixture.py:4: [det-wallclock]" in out

    # the `babble-tpu lint` dispatch path (cli.main intercepts the
    # subcommand and forwards the remaining argv untouched)
    from babble_tpu.cli import main as cli_main

    monkeypatch.chdir(tmp_path)
    assert cli_main(["lint", "--no-baseline"]) == 1
    monkeypatch.chdir(REPO_ROOT)
    assert cli_main(["lint"]) == 0
    capsys.readouterr()


def test_cli_narrows_to_paths(tmp_path, capsys):
    _write(tmp_path, "babble_tpu/node/bad.py", """\
        import time

        def f():
            return time.monotonic()
        """)
    _write(tmp_path, "babble_tpu/node/good.py", "x = 1\n")
    assert lint_main(
        ["--no-baseline", "babble_tpu/node/good.py"], root=str(tmp_path)
    ) == 0
    assert lint_main(
        ["--no-baseline", "babble_tpu/node/bad.py"], root=str(tmp_path)
    ) == 1
    capsys.readouterr()


def test_write_baseline_flag_round_trip(tmp_path, capsys):
    _write(tmp_path, "babble_tpu/node/fixture.py", """\
        import time

        def f():
            return time.monotonic()
        """)
    baseline = str(tmp_path / "b.json")
    assert lint_main(
        ["--baseline", baseline, "--write-baseline"], root=str(tmp_path)
    ) == 0
    assert lint_main(["--baseline", baseline], root=str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "1 baselined" in out


def test_staging_audit_covers_doubling_cold_path(tmp_path):
    """The log-diameter cold path (tpu/doubling.py) sits squarely inside
    the staging-audit + determinism scope: a violation seeded into a
    scratch copy of the REAL module must fire, and the checked-in module
    itself must stay clean with the (empty) shipped baseline."""
    real = Path(REPO_ROOT) / "babble_tpu" / "tpu" / "doubling.py"
    src = real.read_text()
    seeded = src + (
        "\n\n@jax.jit\n"
        "def _seeded_probe(x):\n"
        "    if x.sum() > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    p = tmp_path / "babble_tpu" / "tpu" / "doubling.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(seeded)
    found = _lint(tmp_path).new
    assert [(f.rule, f.symbol) for f in found] == [
        ("jax-tracer-branch", "_seeded_probe")
    ]
    assert found[0].line > len(src.splitlines())

    clean = run_lint(
        REPO_ROOT, paths=["babble_tpu/tpu/doubling.py"], baseline_path=None
    )
    assert clean.errors == []
    assert [f.location() for f in clean.new] == []
    assert clean.files_checked == 1


def test_staging_audit_covers_packed_kernels(tmp_path):
    """ISSUE 17: the bit-packed voting module (tpu/packed.py) sits inside
    the staging-audit + determinism scope like every other kernel module:
    a tracer-branch violation seeded into a scratch copy of the REAL
    module must fire, and the checked-in module itself must stay clean
    with the (empty) shipped baseline."""
    real = Path(REPO_ROOT) / "babble_tpu" / "tpu" / "packed.py"
    src = real.read_text()
    seeded = src + (
        "\n\n@jax.jit\n"
        "def _seeded_probe(x):\n"
        "    if x.sum() > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    p = tmp_path / "babble_tpu" / "tpu" / "packed.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(seeded)
    found = _lint(tmp_path).new
    assert [(f.rule, f.symbol) for f in found] == [
        ("jax-tracer-branch", "_seeded_probe")
    ]
    assert found[0].line > len(src.splitlines())

    clean = run_lint(
        REPO_ROOT, paths=["babble_tpu/tpu/packed.py"], baseline_path=None
    )
    assert clean.errors == []
    assert [f.location() for f in clean.new] == []
    assert clean.files_checked == 1


def test_staging_audit_covers_batched_dispatch_path(tmp_path):
    """ISSUE 9: the round-batched dispatch path (tpu/dispatch.py staging
    through GridStager, tpu/sharded.py 2-D fame loop) must stay inside
    the jax-host-sync audit scope. A host-sync violation seeded into a
    scratch copy of the REAL sharded module's shard_map factory must
    fire, and the checked-in dispatch + sharded modules themselves must
    stay clean with the (empty) shipped baseline — i.e. the batched path
    added no new host syncs."""
    real = Path(REPO_ROOT) / "babble_tpu" / "tpu" / "sharded.py"
    src = real.read_text()
    seeded = src + (
        "\n\ndef _seeded_factory(mesh):\n"
        "    def _seeded_local(votes):\n"
        "        return int(votes[0, 0])\n"
        "    return _shard_map(\n"
        "        _seeded_local, mesh=mesh, in_specs=P(), out_specs=P()\n"
        "    )\n"
    )
    p = tmp_path / "babble_tpu" / "tpu" / "sharded.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(seeded)
    found = _lint(tmp_path).new
    assert [(f.rule, f.symbol) for f in found] == [
        ("jax-host-sync", "_seeded_local")
    ]
    assert found[0].line > len(src.splitlines())

    for mod in ("babble_tpu/tpu/sharded.py", "babble_tpu/tpu/dispatch.py"):
        clean = run_lint(REPO_ROOT, paths=[mod], baseline_path=None)
        assert clean.errors == []
        assert [f.location() for f in clean.new] == [], mod
        assert clean.files_checked == 1
