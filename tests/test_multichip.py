"""Multi-device CPU differential tests: the sharded SPMD pipeline
(babble_tpu/tpu/sharded.py) must produce exactly the single-device
pipeline's outputs on every topology (conftest pins JAX to a virtual
8-device CPU platform)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from babble_tpu.tpu import grid_from_hashgraph, run_passes, synthetic_grid
from babble_tpu.tpu.sharded import sharded_run_passes

from dsl import init_consensus_hashgraph, init_simple_hashgraph


def make_mesh(n_devices):
    devices = jax.devices("cpu")
    if len(devices) < n_devices:
        pytest.skip(f"need {n_devices} CPU devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_devices]), ("rounds",))


def assert_sharded_matches(grid, n_devices):
    mesh = make_mesh(n_devices)
    sharded = sharded_run_passes(mesh, grid)
    single = run_passes(grid)

    np.testing.assert_array_equal(sharded.rounds, single.rounds)
    np.testing.assert_array_equal(sharded.witness, single.witness)
    np.testing.assert_array_equal(sharded.lamport, single.lamport)
    np.testing.assert_array_equal(sharded.fame_decided, single.fame_decided)
    np.testing.assert_array_equal(
        sharded.famous & sharded.fame_decided,
        single.famous & single.fame_decided,
    )
    np.testing.assert_array_equal(sharded.rounds_decided, single.rounds_decided)
    np.testing.assert_array_equal(sharded.received, single.received)
    assert sharded.last_round == single.last_round


@pytest.mark.parametrize("n_devices", [2, 8])
def test_synthetic_sharded_differential(n_devices):
    grid = synthetic_grid(8, 192, seed=11)
    assert_sharded_matches(grid, n_devices)


def test_zipf_sharded_differential():
    grid = synthetic_grid(16, 384, seed=23, zipf_a=1.1)
    assert_sharded_matches(grid, 8)


def test_fixture_sharded_differential():
    """Named consensus fixture through the sharded pipeline."""
    hg, _, _ = init_consensus_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_sharded_matches(grid, 4)


def test_simple_fixture_sharded_differential():
    hg, _, _ = init_simple_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_sharded_matches(grid, 2)


# -- chains-sharded frontier pipeline (the flagship kernel) ------------------


def assert_frontier_sharded_matches(grid, n_devices, r_cap=None):
    from babble_tpu.tpu.engine import run_frontier_passes
    from babble_tpu.tpu.sharded import sharded_frontier_passes

    mesh = make_mesh(n_devices)
    sharded = sharded_frontier_passes(mesh, grid, r_cap=r_cap)
    single = run_frontier_passes(grid)

    np.testing.assert_array_equal(sharded.rounds, single.rounds)
    np.testing.assert_array_equal(sharded.witness, single.witness)
    np.testing.assert_array_equal(sharded.lamport, single.lamport)
    np.testing.assert_array_equal(sharded.received, single.received)
    assert sharded.last_round == single.last_round
    # fame tables may differ in round-axis length (adaptive single-device
    # bucketing); their real content must agree on the overlap
    r = min(sharded.fame_decided.shape[0], single.fame_decided.shape[0])
    np.testing.assert_array_equal(sharded.fame_decided[:r], single.fame_decided[:r])
    np.testing.assert_array_equal(
        (sharded.famous & sharded.fame_decided)[:r],
        (single.famous & single.fame_decided)[:r],
    )


@pytest.mark.parametrize("n_devices", [2, 8])
def test_frontier_sharded_differential(n_devices):
    grid = synthetic_grid(8, 192, seed=11)
    assert_frontier_sharded_matches(grid, n_devices)


def test_frontier_sharded_zipf():
    grid = synthetic_grid(16, 384, seed=23, zipf_a=1.1)
    assert_frontier_sharded_matches(grid, 8)


def test_frontier_sharded_chain_padding():
    """Validator count not divisible by the mesh: chain axis padded."""
    grid = synthetic_grid(12, 300, seed=7)
    assert_frontier_sharded_matches(grid, 8)


def test_frontier_sharded_fixture():
    hg, _, _ = init_consensus_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_frontier_sharded_matches(grid, 4)


def test_frontier_sharded_n256():
    """BASELINE config #4 scale on the CPU mesh: 256 validators, Zipf
    fan-out, chains-sharded INV (32 chains per device)."""
    grid = synthetic_grid(256, 1024, seed=41, zipf_a=1.05)
    assert_frontier_sharded_matches(grid, 8, r_cap=16)


def test_dryrun_multichip_entrypoint():
    """The driver's dryrun must pass end-to-end on the CPU mesh."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_mesh_backend_node_in_cluster_byte_identical():
    """VERDICT r3 #3: the sharded pipeline as a PRODUCT capability — a
    full Node configured with consensus_backend=tpu + mesh_devices=8
    participates in a live cluster over the in-memory transport and
    commits byte-identical blocks (check_gossip), with every consensus
    call routed through the mesh (no silent CPU fallback)."""
    from test_device_backend import build_mixed_cluster
    from test_node import (
        bombard_and_wait, check_gossip, run_nodes, shutdown_nodes,
    )

    nodes, proxies, *_ = build_mixed_cluster(
        ["cpu", "cpu", "cpu", "tpu"], sync_limit=2000, mesh_devices={3: 8},
    )
    try:
        run_nodes(nodes)
        bombard_and_wait(nodes, proxies, target_block=3, timeout_s=300)
        check_gossip(nodes, upto=3)
        assert nodes[3].core.device_consensus_runs > 0, (
            "mesh node never ran the sharded backend"
        )
        assert nodes[3].core.device_consensus_fallbacks == 0, (
            "mesh node silently fell back to the CPU engine"
        )
        assert nodes[3].core._mesh is not None
    finally:
        shutdown_nodes(nodes)


# -- driver-environment simulation (subprocess; conftest pins must NOT leak) --

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_driver_like_subprocess(code, extra_env=None):
    """Run `code` in a subprocess whose environment mimics the driver:
    jax importable, JAX_PLATFORMS and XLA_FLAGS UNSET (conftest's pins
    scrubbed), jax pre-imported before __graft_entry__ — the exact setup
    under which MULTICHIP_r02 failed (module-level default-backend touch +
    env-var-only pin arriving too late)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_PLATFORM_NAME")
    }
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO,
        timeout=600,
    )


def test_tpu_import_initializes_no_backend():
    """Importing the kernel/engine modules must not create any JAX array —
    a module-level array constant initializes the process's DEFAULT backend
    at import time (the round-2 multichip killer: a dead `NEG` constant in
    kernels.py landed on the real TPU and died on a libtpu mismatch in the
    driver env). Regression-pinned by asserting the backend registry stays
    empty across import."""
    proc = run_driver_like_subprocess(
        """
        import jax  # simulate sitecustomize pre-import
        from jax._src import xla_bridge
        assert not xla_bridge.backends_are_initialized(), "pre-import dirty"
        import babble_tpu.tpu  # pulls grid, engine, kernels
        import babble_tpu.tpu.sharded
        import babble_tpu.tpu.frontier
        import babble_tpu.tpu.incremental
        import babble_tpu.tpu.live
        import babble_tpu.tpu.dispatch
        assert not xla_bridge.backends_are_initialized(), (
            "importing babble_tpu.tpu initialized a JAX backend"
        )
        print("IMPORT_PURE")
        """
    )
    assert proc.returncode == 0, proc.stderr
    assert "IMPORT_PURE" in proc.stdout


def test_dryrun_multichip_driver_env():
    """dryrun_multichip(8) must succeed when jax is pre-imported and
    JAX_PLATFORMS is unset — the entry point's own jax.config.update pin
    must do the work (env vars alone are too late once jax is imported,
    per conftest.py's note)."""
    proc = run_driver_like_subprocess(
        """
        import jax  # pre-import BEFORE __graft_entry__, like the driver
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)
        """
    )
    assert proc.returncode == 0, proc.stderr
    assert "dryrun_multichip OK" in proc.stdout


# -- 2-D (validators, rounds) mesh (ISSUE 9) ---------------------------------


def make_mesh2(dv, dr):
    devices = jax.devices("cpu")
    if len(devices) < dv * dr:
        pytest.skip(f"need {dv * dr} CPU devices, have {len(devices)}")
    return Mesh(
        np.array(devices[: dv * dr]).reshape(dv, dr), ("validators", "rounds")
    )


def assert_2d_matches(grid, dv=2, dr=2):
    """Every sharded pipeline on the 2-D mesh must be byte-equal to the
    single-device oracle — the validator-axis partition of the voting
    state (per-shard local tallies + one psum per fame step) is an
    implementation layout, never an observable."""
    from babble_tpu.tpu.engine import run_frontier_passes
    from babble_tpu.tpu.sharded import (
        mesh_validator_shards, sharded_frontier_passes, sharded_run_passes,
    )

    mesh = make_mesh2(dv, dr)
    assert mesh_validator_shards(mesh) == dv

    single = run_passes(grid)
    sharded = sharded_run_passes(mesh, grid)
    np.testing.assert_array_equal(sharded.rounds, single.rounds)
    np.testing.assert_array_equal(sharded.witness, single.witness)
    np.testing.assert_array_equal(sharded.lamport, single.lamport)
    np.testing.assert_array_equal(sharded.fame_decided, single.fame_decided)
    np.testing.assert_array_equal(
        sharded.famous & sharded.fame_decided,
        single.famous & single.fame_decided,
    )
    np.testing.assert_array_equal(sharded.rounds_decided, single.rounds_decided)
    np.testing.assert_array_equal(sharded.received, single.received)
    assert sharded.last_round == single.last_round

    single_f = run_frontier_passes(grid)
    sf = sharded_frontier_passes(mesh, grid)
    np.testing.assert_array_equal(sf.rounds, single_f.rounds)
    np.testing.assert_array_equal(sf.received, single_f.received)
    assert sf.last_round == single_f.last_round
    r = min(sf.fame_decided.shape[0], single_f.fame_decided.shape[0])
    np.testing.assert_array_equal(sf.fame_decided[:r], single_f.fame_decided[:r])
    np.testing.assert_array_equal(
        (sf.famous & sf.fame_decided)[:r],
        (single_f.famous & single_f.fame_decided)[:r],
    )


def test_2d_mesh_synthetic_differential():
    assert_2d_matches(synthetic_grid(8, 192, seed=11))


def test_2d_mesh_witness_padding():
    """Validator count not divisible by the validator shards: the
    witness axes pad to a multiple of dv (padded strongly-seen columns
    are False so padded vote rows tally zero)."""
    assert_2d_matches(synthetic_grid(7, 128, seed=9))


def test_2d_mesh_fixture_differential():
    hg, _, _ = init_consensus_hashgraph()
    assert_2d_matches(grid_from_hashgraph(hg))


def test_2d_mesh_post_reset_section():
    """Acceptance: 2-D outputs byte-equal on post-reset sections too."""
    from babble_tpu.tpu.grid import section_grid

    grid = synthetic_grid(8, 192, seed=11)
    res = run_passes(grid)
    sec = section_grid(grid, res, cut=4)
    assert_2d_matches(sec)


def test_2d_mesh_doubling_cold_path():
    """The sharded pointer-doubling pipeline (the round-batched rung's
    cold path) on the 2-D mesh, vs the frontier oracle."""
    from babble_tpu.tpu.engine import run_frontier_passes
    from babble_tpu.tpu.sharded import sharded_doubling_passes

    grid = synthetic_grid(8, 192, seed=11)
    mesh = make_mesh2(2, 2)
    sd = sharded_doubling_passes(mesh, grid)
    single = run_frontier_passes(grid)
    np.testing.assert_array_equal(sd.rounds, single.rounds)
    np.testing.assert_array_equal(sd.received, single.received)
    assert sd.last_round == single.last_round


# -- delta staging (GridStager, ISSUE 9) -------------------------------------


def test_grid_stager_incremental_matches_full_restage():
    """Replay the consensus fixture's event stream into a fresh
    hashgraph a few events at a time; after every chunk the persistent
    stager's grid must be byte-equal to a from-scratch
    grid_from_hashgraph on every column — delta staging is a pure
    restage eliminator, never an observable."""
    from babble_tpu.hashgraph import Hashgraph, InmemStore
    from babble_tpu.tpu.grid import GridStager

    from dsl import CACHE_SIZE

    src, _, ordered = init_consensus_hashgraph()
    hg = Hashgraph(
        src.participants, InmemStore(src.participants, CACHE_SIZE)
    )
    stager = GridStager(hg)
    CHUNK = 3
    for lo in range(0, len(ordered), CHUNK):
        for ev in ordered[lo : lo + CHUNK]:
            hg.insert_event(ev, True)
        got = stager.stage()
        want = grid_from_hashgraph(hg)
        assert got.e == want.e
        assert got.num_levels == want.num_levels
        for col in (
            "creator", "index", "self_parent", "other_parent",
            "last_ancestors", "first_descendants",
            "ext_sp_round", "ext_op_round", "fixed_round",
            "ext_sp_lamport", "ext_op_lamport", "fixed_lamport",
            "coin_bit",
        ):
            np.testing.assert_array_equal(
                getattr(got, col)[: got.e], getattr(want, col)[: want.e],
                err_msg=f"stager column {col} diverged at e={got.e}",
            )
        for lv in range(want.num_levels):
            np.testing.assert_array_equal(
                np.sort(got.levels[lv][got.levels[lv] >= 0]),
                np.sort(want.levels[lv][want.levels[lv] >= 0]),
                err_msg=f"stager level {lv} diverged at e={got.e}",
            )
        assert list(got.hashes) == list(want.hashes)
    assert stager.full_restages == 1, "delta path never took over"
    assert stager.delta_stages > 0
    last_chunk = len(ordered) - ((len(ordered) - 1) // CHUNK) * CHUNK
    assert stager.last_delta_rows == last_chunk


def test_grid_stager_snapshots_are_immutable():
    """A staged snapshot handed to an in-flight dispatch must not change
    under later inserts (first_descendants and levels mutate in the
    stager's resident buffers — snapshots copy them)."""
    from babble_tpu.hashgraph import Hashgraph, InmemStore
    from babble_tpu.tpu.grid import GridStager

    from dsl import CACHE_SIZE

    src, _, ordered = init_consensus_hashgraph()
    hg = Hashgraph(
        src.participants, InmemStore(src.participants, CACHE_SIZE)
    )
    stager = GridStager(hg)
    half = len(ordered) // 2
    for ev in ordered[:half]:
        hg.insert_event(ev, True)
    snap = stager.stage()
    fd_before = snap.first_descendants.copy()
    levels_before = snap.levels.copy()
    for ev in ordered[half:]:
        hg.insert_event(ev, True)
    stager.stage()
    np.testing.assert_array_equal(snap.first_descendants, fd_before)
    np.testing.assert_array_equal(snap.levels, levels_before)


def test_use_doubling_prefer_lowers_crossover():
    """The round-batched rung prefers the doubling cold path well below
    the per-sync crossover: one dispatch per batch amortizes the train."""
    from babble_tpu.tpu.doubling import use_doubling

    grid = synthetic_grid(8, 512, seed=3)
    assert grid.num_levels >= 64, "fixture too shallow for the assertion"
    assert not use_doubling(grid)
    assert use_doubling(grid, prefer=True)
