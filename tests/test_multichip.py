"""Multi-device CPU differential tests: the sharded SPMD pipeline
(babble_tpu/tpu/sharded.py) must produce exactly the single-device
pipeline's outputs on every topology (conftest pins JAX to a virtual
8-device CPU platform)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from babble_tpu.tpu import grid_from_hashgraph, run_passes, synthetic_grid
from babble_tpu.tpu.sharded import sharded_run_passes

from dsl import init_consensus_hashgraph, init_simple_hashgraph


def make_mesh(n_devices):
    devices = jax.devices("cpu")
    if len(devices) < n_devices:
        pytest.skip(f"need {n_devices} CPU devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_devices]), ("rounds",))


def assert_sharded_matches(grid, n_devices):
    mesh = make_mesh(n_devices)
    sharded = sharded_run_passes(mesh, grid)
    single = run_passes(grid)

    np.testing.assert_array_equal(sharded.rounds, single.rounds)
    np.testing.assert_array_equal(sharded.witness, single.witness)
    np.testing.assert_array_equal(sharded.lamport, single.lamport)
    np.testing.assert_array_equal(sharded.fame_decided, single.fame_decided)
    np.testing.assert_array_equal(
        sharded.famous & sharded.fame_decided,
        single.famous & single.fame_decided,
    )
    np.testing.assert_array_equal(sharded.rounds_decided, single.rounds_decided)
    np.testing.assert_array_equal(sharded.received, single.received)
    assert sharded.last_round == single.last_round


@pytest.mark.parametrize("n_devices", [2, 8])
def test_synthetic_sharded_differential(n_devices):
    grid = synthetic_grid(8, 192, seed=11)
    assert_sharded_matches(grid, n_devices)


def test_zipf_sharded_differential():
    grid = synthetic_grid(16, 384, seed=23, zipf_a=1.1)
    assert_sharded_matches(grid, 8)


def test_fixture_sharded_differential():
    """Named consensus fixture through the sharded pipeline."""
    hg, _, _ = init_consensus_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_sharded_matches(grid, 4)


def test_simple_fixture_sharded_differential():
    hg, _, _ = init_simple_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_sharded_matches(grid, 2)


def test_dryrun_multichip_entrypoint():
    """The driver's dryrun must pass end-to-end on the CPU mesh."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
