"""Ingress pipeline tests (ISSUE 16): batching boundaries on the
injected Clock, DRR fairness under an aggressive client, trace_id dedup
idempotency (including across the LRU horizon), explicit shed verdicts,
the typed SubmitRejected contract over real TCP, deterministic verdict
accounting under sim, and batched-vs-single-tx digest equality on a
mixed CPU+mesh cluster."""

import json

import pytest

from babble_tpu.cli import _merge_config_file, build_parser, run_command
from babble_tpu.ingress import (
    IngressPipeline,
    IngressVerdict,
    OpenLoopLoadGen,
    SubmitRejected,
    verdict_from_wire,
)
from babble_tpu.obs.tracectx import trace_id_for
from babble_tpu.sim import SimClock, SimCluster

from test_socket_proxy import make_pair


def make_pipeline(clock=None, **kw):
    """Pipeline wired to a list-of-batches collector on a SimClock."""
    clock = clock or SimClock()
    batches = []
    pipe = IngressPipeline(downstream=batches.append, clock=clock, **kw)
    return pipe, batches, clock


# ----------------------------------------------------------------------
# batching boundaries
# ----------------------------------------------------------------------

def test_size_threshold_flushes_batch():
    """Crossing batch_bytes closes the batch mid-pump; with deadline 0
    the remainder ships in the same pump as its own batch."""
    pipe, batches, _ = make_pipeline(batch_bytes=64, batch_deadline=0.0)
    txs = [bytes([65 + i]) * 24 for i in range(3)]  # 3 x 24B vs 64B cap
    verdicts = pipe.submit_batch(txs, client_id="a")
    assert [v.verdict for v in verdicts] == ["accepted"] * 3
    assert batches == [[txs[0], txs[1], txs[2]]] or len(batches) == 2
    # the size rule: no released batch except the last exceeds... the
    # first closed batch is the one that crossed 64 bytes
    assert sum(len(t) for t in batches[0]) >= 64 or len(batches) == 1
    assert [t for b in batches for t in b] == txs  # order preserved
    assert pipe.pending() == 0


def test_deadline_holds_partial_batch_until_clock_elapses():
    """deadline > 0: a partial batch is HELD; tick() releases it only
    once the injected Clock passes the deadline — no wallclock."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=1 << 20, batch_deadline=0.5,
    )
    v = pipe.submit(b"early bird", client_id="a")
    assert v.verdict == "accepted"
    assert batches == []  # held: under size, deadline not reached
    assert pipe.pending() == 1
    clock.advance_to(0.4)
    pipe.tick()
    assert batches == []  # still inside the deadline window
    clock.advance_to(0.6)
    pipe.tick()
    assert batches == [[b"early bird"]]
    assert pipe.pending() == 0


def test_oversize_tx_bypasses_coalescing():
    """A tx >= batch_bytes ships alone, after the open batch flushes —
    it never waits on a deadline and never pads a shared batch."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=64, batch_deadline=10.0,
    )
    pipe.submit(b"small", client_id="a")
    assert batches == []  # held on the deadline
    pipe.submit(b"X" * 200, client_id="a")
    # open partial batch flushed first, then the oversize tx alone
    assert batches == [[b"small"], [b"X" * 200]]


def test_flush_ships_partial_batch():
    pipe, batches, _ = make_pipeline(batch_bytes=1 << 20, batch_deadline=9.0)
    pipe.submit(b"tail", client_id="a")
    assert batches == []
    pipe.flush()
    assert batches == [[b"tail"]]


# ----------------------------------------------------------------------
# dedup idempotency
# ----------------------------------------------------------------------

def test_retry_is_idempotent_and_answered_accepted():
    """A client retry gets a SUCCESS verdict (deduped flag set), and the
    tx enters the pool exactly once."""
    pipe, batches, _ = make_pipeline(batch_bytes=16, batch_deadline=0.0)
    first = pipe.submit(b"pay alice 5", client_id="a")
    retry = pipe.submit(b"pay alice 5", client_id="a")
    assert first.verdict == "accepted" and not first.deduped
    assert retry.verdict == "accepted" and retry.deduped
    assert retry.reason == "duplicate"
    assert retry.trace_id == trace_id_for(b"pay alice 5")
    assert [t for b in batches for t in b] == [b"pay alice 5"]
    snap = pipe.obs.registry.snapshot()
    assert snap["babble_ingress_dedup_hits_total"]["series"][""] == 1


def test_dedup_forgets_past_the_lru_horizon():
    """The window is an LRU: once enough fresh trace_ids evict an old
    one, re-offering it is a fresh submission again (the idempotency
    contract is bounded, by design)."""
    pipe, batches, _ = make_pipeline(
        batch_bytes=16, batch_deadline=0.0, dedup_window=2,
    )
    pipe.submit(b"tx-A", client_id="a")
    pipe.submit(b"tx-B", client_id="a")
    pipe.submit(b"tx-C", client_id="a")  # evicts tx-A
    again = pipe.submit(b"tx-A", client_id="a")
    assert again.verdict == "accepted" and not again.deduped
    flat = [t for b in batches for t in b]
    assert flat == [b"tx-A", b"tx-B", b"tx-C", b"tx-A"]


def test_shed_tx_not_poisoned_by_dedup():
    """A SHED tx must not enter the dedup window: the client's retry
    after backoff has to be admissible, not absorbed as a 'duplicate'
    of a submission that never entered the pool."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=1 << 20, batch_deadline=5.0, queue_cap=1,
    )
    assert pipe.submit(b"fills the queue", client_id="a").verdict == "accepted"
    shed = pipe.submit(b"unlucky", client_id="b")
    assert shed.verdict == "shed" and shed.reason == "queue_full"
    pipe.flush()  # capacity frees up
    retry = pipe.submit(b"unlucky", client_id="b")
    assert retry.verdict == "accepted" and not retry.deduped
    pipe.flush()
    assert [t for b in batches for t in b] == [b"fills the queue", b"unlucky"]


# ----------------------------------------------------------------------
# admission control: explicit verdicts, never silent drops
# ----------------------------------------------------------------------

def test_queue_full_sheds_with_reason_and_counters():
    pipe, _, _ = make_pipeline(
        batch_bytes=1 << 20, batch_deadline=5.0, queue_cap=2,
    )
    verdicts = pipe.submit_batch(
        [b"one", b"two", b"three", b"four"], client_id="a",
    )
    assert [v.verdict for v in verdicts] == [
        "accepted", "accepted", "shed", "shed",
    ]
    assert all(v.reason == "queue_full" for v in verdicts[2:])
    assert all(v.trace_id for v in verdicts)  # shed answers carry the id too
    snap = pipe.obs.registry.snapshot()
    assert snap["babble_ingress_shed_total"]["series"]["queue_full"] == 2
    assert snap["babble_ingress_verdicts_total"]["series"]["shed"] == 2


def test_overrate_client_queued_then_released_on_refill():
    """Past its token budget a client's txs are QUEUED (admitted, held),
    and a Clock advance refills the bucket so tick() releases them."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=16, batch_deadline=0.0,
        client_rate=1.0, client_burst=1.0,
    )
    v1 = pipe.submit(b"paid by the burst token", client_id="c")
    v2 = pipe.submit(b"over the rate", client_id="c")
    assert v1.verdict == "accepted"
    assert v2.verdict == "queued" and v2.reason == "rate_limited"
    assert [t for b in batches for t in b] == [b"paid by the burst token"]
    assert pipe.pending() == 1
    clock.advance_to(1.5)  # 1 token/s refill
    pipe.tick()
    assert [t for b in batches for t in b][-1] == b"over the rate"
    assert pipe.pending() == 0


def test_sustained_overrate_sheds_bounded_backlog():
    """An aggressive client may park only a bounded backlog behind its
    empty bucket — past queue_cap//4 it is shed as rate_limited, so one
    flooder cannot fill the shared admission queue."""
    pipe, _, _ = make_pipeline(
        batch_bytes=1 << 20, batch_deadline=5.0,
        queue_cap=8, client_rate=1.0, client_burst=1.0,
    )
    verdicts = [
        pipe.submit(b"flood %d" % i, client_id="f") for i in range(6)
    ]
    kinds = [v.verdict for v in verdicts]
    # 1 paid (burst), queue_cap//4 == 2 queued, the rest shed
    assert kinds == ["accepted", "queued", "queued", "shed", "shed", "shed"]
    assert all(v.reason == "rate_limited" for v in verdicts[3:])


def test_drr_meek_client_releases_ahead_of_flooder_backlog():
    """Fairness: a flooder's rate-deferred backlog does not head-of-line
    block a meek client — the meek tx releases immediately while the
    flooder's txs stay parked on its empty bucket."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=16, batch_deadline=0.0,
        queue_cap=64, client_rate=1.0, client_burst=1.0,
    )
    flood = pipe.submit_batch(
        [b"flood-%d" % i for i in range(5)], client_id="flooder",
    )
    assert [v.verdict for v in flood][:1] == ["accepted"]
    assert {v.verdict for v in flood[1:]} <= {"queued", "shed"}
    backlog_before = pipe.pending()
    assert backlog_before > 0
    meek = pipe.submit(b"meek but timely", client_id="meek")
    assert meek.verdict == "accepted"
    released = [t for b in batches for t in b]
    assert b"meek but timely" in released  # ahead of the parked backlog
    assert pipe.pending() == backlog_before  # flooder still parked


def test_drr_interleaves_clients_within_a_pump():
    """With both clients' backlogs parked before one pump, release order
    alternates by quantum (32B here, one tx per round per client)
    instead of draining one client before touching the other."""
    clock = SimClock()
    pipe, batches, _ = make_pipeline(
        clock=clock, batch_bytes=128, batch_deadline=0.0,
        client_rate=1.0, client_burst=3.0,
    )
    # park both clients' txs behind empty buckets (burst spent), then
    # refill enough for everything and pump once
    for i in range(6):
        pipe.submit(b"A%d" % i + b"." * 30, client_id="a")
    for i in range(6):
        pipe.submit(b"B%d" % i + b"." * 30, client_id="b")
    held = pipe.pending()
    assert held == 6  # 3 paid per client released, 3 parked each
    clock.advance_to(10.0)
    pipe.tick()
    pipe.flush()
    order = [bytes(t[:1]) for b in batches for t in b]
    # the post-refill tail interleaves a/b, a quantum per client per round
    assert order[-6:] == [b"A", b"B", b"A", b"B", b"A", b"B"]


# ----------------------------------------------------------------------
# wire encoding + typed rejection over real TCP
# ----------------------------------------------------------------------

def test_verdict_wire_roundtrip_and_legacy_mapping():
    v = IngressVerdict("queued", reason="rate_limited", trace_id="abc123")
    assert verdict_from_wire(v.to_wire()) == v
    legacy_ok = verdict_from_wire(True)
    assert legacy_ok.verdict == "accepted" and legacy_ok.reason == "legacy"
    legacy_no = verdict_from_wire(False)
    assert legacy_no.verdict == "shed" and legacy_no.reason == "rejected"


def test_socket_batch_submit_and_shed_rejection():
    """The TCP contract end to end: SubmitTxBatch returns per-tx
    verdicts; a shed single-tx submit raises SubmitRejected with
    verdict='shed' and the server's verdict attached; batch sheds are
    RETURNED, not raised."""
    node, app, _ = make_pair()
    batches = []
    pipe = IngressPipeline(
        downstream=batches.append, batch_bytes=1 << 20,
        batch_deadline=30.0, queue_cap=2,
    )
    node.bind_ingress(pipe)
    try:
        verdicts = app.submit_tx_batch([b"t1", b"t2"], client_id="app-7")
        assert [v.verdict for v in verdicts] == ["accepted", "accepted"]
        assert verdicts[0].trace_id == trace_id_for(b"t1")
        # queue now full (deadline holds the batch): single tx -> typed
        # rejection the caller can branch on
        with pytest.raises(SubmitRejected) as ei:
            app.submit_tx(b"t3", client_id="app-7")
        assert ei.value.verdict == "shed"
        assert ei.value.server_verdict.reason == "queue_full"
        # batch path: per-tx shed verdicts come back as data
        batch_verdicts = app.submit_tx_batch([b"t4"], client_id="app-7")
        assert batch_verdicts[0].verdict == "shed"
        # a duplicate rides the dedup window even while the queue is full
        dup = app.submit_tx(b"t1", client_id="app-7")
        assert dup.verdict == "accepted" and dup.deduped
    finally:
        node.close()
        app.close()


def test_socket_server_error_maps_to_submit_rejected_error():
    """A server-side failure (not backpressure) surfaces as
    SubmitRejected(verdict='error'): the submission may never have been
    seen, which is a different client contract than 'shed'."""
    def exploding(batch):
        raise RuntimeError("downstream unavailable")

    node, app, _ = make_pair()
    node.bind_ingress(IngressPipeline(
        downstream=exploding, batch_bytes=16, batch_deadline=0.0,
    ))
    try:
        with pytest.raises(SubmitRejected) as ei:
            app.submit_tx(b"doomed")
        assert ei.value.verdict == "error"
    finally:
        node.close()
        app.close()


def test_socket_legacy_server_without_pipeline():
    """An unbound server answers plain True; the app-side proxy maps it
    to an accepted/legacy verdict instead of raising."""
    node, app, _ = make_pair()
    try:
        v = app.submit_tx(b"old school")
        assert v.verdict == "accepted" and v.reason == "legacy"
        assert node.submit_ch().get(timeout=3) == b"old school"
    finally:
        node.close()
        app.close()


# ----------------------------------------------------------------------
# loadgen + sim determinism
# ----------------------------------------------------------------------

def test_loadgen_schedule_deterministic_per_seed():
    def sample(seed):
        g = OpenLoopLoadGen(rate=50.0, clients=1000, burst=3, seed=seed)
        return [
            (round(g.next_gap(), 12),
             tuple((e["tx"], e["client_id"]) for e in g.next_burst()))
            for _ in range(20)
        ]

    assert sample(4) == sample(4)
    assert sample(4) != sample(5)


def test_sim_ingress_verdict_accounting_deterministic():
    """Two same-seed cluster runs under offered load replay identical
    digests AND identical ingress counters — shed/dedup decisions are
    part of the determinism fingerprint, not best-effort."""
    def run(seed):
        cluster = SimCluster(
            n=4, seed=seed, heartbeat=0.05,
            ingress_batch_deadline=0.0,
            # tight cap so the run actually sheds: the determinism claim
            # must cover the shed path, not only the happy path
            ingress_queue_cap=4,
        )
        gen = OpenLoopLoadGen(
            rate=200.0, clients=500, burst=4, retry_every=8, seed=seed,
        )
        gen.drive_sim(cluster, until=2.0, via="ingress")
        res = cluster.run(until=2.0, inject=False)
        return res, gen

    res_a, gen_a = run(3)
    res_b, gen_b = run(3)
    assert res_a["digest"] == res_b["digest"]
    assert res_a["ingress"] == res_b["ingress"]
    assert gen_a.stats() == gen_b.stats()
    # the offered load was heavy enough to exercise every verdict
    assert gen_a.verdicts["accepted"] > 0


def test_mixed_backend_digest_identical_batched_vs_single_tx():
    """The acceptance gate on a mixed CPU+mesh cluster: the SAME seeded
    workload submitted through the batching pipeline and submitted
    single-tx (no pipeline) commits byte-identical blocks — batching,
    dedup and fairness reshape HOW txs enter, never WHAT is committed.
    Mesh nodes ride the queued dispatch rung, so this also pins the
    ingress batch boundary against the device batch boundary."""
    def run(via):
        cluster = SimCluster(
            n=4, seed=11, heartbeat=0.05,
            backend=("cpu", "cpu", "tpu", "tpu"),
            mesh_devices=2, dispatch_queue_depth=4,
            dispatch_batch_deadline=0.2,
            ingress_batch_deadline=0.0, ingress_queue_cap=8192,
        )
        gen = OpenLoopLoadGen(
            rate=80.0, clients=2000, burst=3, retry_every=6, seed=11,
        )
        gen.drive_sim(cluster, until=2.5, via=via)
        res = cluster.run(until=2.5, inject=False)
        return res, gen

    res_ingress, gen_ingress = run("ingress")
    res_direct, _ = run("direct")
    assert res_ingress["digest"] == res_direct["digest"]
    assert gen_ingress.retries > 0
    dedup_hits = sum(
        (snaps.get("babble_ingress_dedup_hits_total") or {})
        .get("series", {}).get("", 0)
        for snaps in res_ingress["ingress"].values()
    )
    assert dedup_hits == gen_ingress.retries  # every retry absorbed


# ----------------------------------------------------------------------
# config plumbing
# ----------------------------------------------------------------------

def test_cli_rejects_invalid_ingress_knobs():
    parser = build_parser()
    bad = [
        ["run", "--ingress-batch-bytes", "0"],
        ["run", "--ingress-batch-deadline", "-0.1"],
        ["run", "--ingress-queue-cap", "-1"],
        ["run", "--ingress-client-rate", "-2"],
        # contradiction: rate limiting with nothing to shed into
        ["run", "--ingress-client-rate", "5", "--ingress-queue-cap", "0"],
    ]
    for argv in bad:
        assert run_command(parser.parse_args(argv)) == 1, argv


def test_ingress_knobs_merge_from_config_file(tmp_path):
    (tmp_path / "babble.json").write_text(json.dumps({
        "ingress-batch-bytes": 1024,
        "ingress-batch-deadline": 0.25,
        "ingress-queue-cap": 99,
        "ingress-client-rate": 7.5,
    }))
    argv = ["run", "--datadir", str(tmp_path)]
    args = build_parser().parse_args(argv)
    _merge_config_file(args, argv)
    assert args.ingress_batch_bytes == 1024
    assert args.ingress_batch_deadline == 0.25
    assert args.ingress_queue_cap == 99
    assert args.ingress_client_rate == 7.5
    # explicit flag still wins over the file
    argv = ["run", "--datadir", str(tmp_path), "--ingress-queue-cap", "5"]
    args = build_parser().parse_args(argv)
    _merge_config_file(args, argv)
    assert args.ingress_queue_cap == 5
    assert args.ingress_batch_bytes == 1024
