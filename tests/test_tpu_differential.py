"""Differential oracle: the device consensus kernels must produce exactly
the host engine's rounds / witness flags / lamport timestamps / fame /
round-received — and byte-identical blocks — on every fixture.

This is the fourth load-bearing test idea on top of the reference's three
(play DSL, named topologies, block byte-equality; reference:
src/hashgraph/hashgraph_test.go): CPU pass vs TPU pass on the same DAG.
"""

import copy

import numpy as np
import pytest

from babble_tpu.hashgraph import Event, Hashgraph, InmemStore
from babble_tpu.tpu import grid_from_hashgraph, run_passes, run_consensus_device, synthetic_grid
from babble_tpu.tpu.grid import MAX_INT32

from dsl import (
    init_consensus_hashgraph,
    init_funky_hashgraph,
    init_round_hashgraph,
    init_simple_hashgraph,
    init_sparse_hashgraph,
)


def clone_hashgraph(hg):
    """Fresh hashgraph with the same events re-inserted (events deep-copied
    via JSON round-trip — insert mutates coordinate metadata in place)."""
    events = []
    for p in hg.participants.to_peer_slice():
        for h in hg.store.participant_events(p.pub_key_hex, -1):
            events.append(hg.store.get_event(h))
    events.sort(key=lambda ev: ev.topological_index)
    fresh = Hashgraph(
        hg.participants, InmemStore(hg.participants, hg.store.cache_size())
    )
    for ev in events:
        fresh.insert_event(Event.from_json(ev.to_json()), True)
    return fresh


def run_both(hg):
    """CPU pipeline on one copy, device pipeline on another; returns both."""
    cpu = clone_hashgraph(hg)
    dev = clone_hashgraph(hg)
    cpu_blocks, dev_blocks = [], []
    cpu.commit_callback = cpu_blocks.append
    dev.commit_callback = dev_blocks.append
    cpu.run_consensus()
    run_consensus_device(dev)
    return cpu, dev, cpu_blocks, dev_blocks


def assert_equivalent(hg):
    cpu, dev, cpu_blocks, dev_blocks = run_both(hg)

    # per-event analysis results
    for p in cpu.participants.to_peer_slice():
        for h in cpu.store.participant_events(p.pub_key_hex, -1):
            ec = cpu.store.get_event(h)
            ed = dev.store.get_event(h)
            assert ec.round == ed.round, f"round mismatch for {h[:16]}"
            assert ec.lamport_timestamp == ed.lamport_timestamp, (
                f"lamport mismatch for {h[:16]}"
            )
            assert ec.round_received == ed.round_received, (
                f"round_received mismatch for {h[:16]}: "
                f"{ec.round_received} vs {ed.round_received}"
            )

    # round infos: witnesses + fame
    assert cpu.store.last_round() == dev.store.last_round()
    for r in range(cpu.store.last_round() + 1):
        rc = cpu.store.get_round(r)
        rd = dev.store.get_round(r)
        assert sorted(rc.witnesses()) == sorted(rd.witnesses()), f"round {r}"
        for w in rc.witnesses():
            assert rc.events[w].famous == rd.events[w].famous, (
                f"fame mismatch round {r} witness {w[:16]}"
            )

    # consensus order + blocks, byte for byte
    assert cpu.store.consensus_events() == dev.store.consensus_events()
    assert len(cpu_blocks) == len(dev_blocks)
    for bc, bd in zip(cpu_blocks, dev_blocks):
        assert bc.body.marshal() == bd.body.marshal()
    assert cpu.undetermined_events == dev.undetermined_events
    return cpu


def test_simple_hashgraph_differential():
    hg, _, _ = init_simple_hashgraph()
    assert_equivalent(hg)


def test_round_hashgraph_differential():
    hg, _, _ = init_round_hashgraph()
    assert_equivalent(hg)


def test_consensus_hashgraph_differential():
    hg, _, _ = init_consensus_hashgraph()
    assert_equivalent(hg)


def test_funky_hashgraph_differential():
    """The adversarial coin-round topology: the CPU engine demonstrably
    takes the coin branch, and the device engine must agree bit-exactly on
    every fame verdict anyway (the kernel's coin path uses the same
    precomputed event-hash middle bits)."""
    hg, _, _ = init_funky_hashgraph(full=True)
    cpu = assert_equivalent(hg)
    assert cpu.coin_rounds > 0, "fixture no longer exercises the coin branch"


def test_sparse_hashgraph_differential():
    """Rounds with sparse witness sets (participants skipping rounds)."""
    hg, _, _ = init_sparse_hashgraph()
    assert_equivalent(hg)


def build_hashgraph_from_grid(grid):
    """Materialize a synthetic DagGrid as real signed events in a fresh
    Hashgraph; returns (hashgraph, events-by-row)."""
    from babble_tpu.crypto import generate_key, pub_key_bytes
    from babble_tpu.hashgraph import root_self_parent
    from babble_tpu.peers import Peer, Peers

    keys = [generate_key() for _ in range(grid.n)]
    participants = Peers()
    for k in keys:
        participants.add_peer(
            Peer(net_addr="", pub_key_hex="0x" + pub_key_bytes(k).hex().upper())
        )
    plist = participants.to_peer_slice()
    # synthetic creator positions index the sorted peer slice
    sorted_keys = [
        k
        for p in plist
        for k in keys
        if "0x" + pub_key_bytes(k).hex().upper() == p.pub_key_hex
    ]

    hg = Hashgraph(participants, InmemStore(participants, 1000))
    rows = []
    for i in range(grid.e):
        c = int(grid.creator[i])
        sp_row = int(grid.self_parent[i])
        op_row = int(grid.other_parent[i])
        sp = rows[sp_row].hex() if sp_row >= 0 else root_self_parent(plist[c].id)
        op = rows[op_row].hex() if op_row >= 0 else ""
        ev = Event(
            transactions=[f"tx{i}".encode()],
            parents=[sp, op],
            creator=pub_key_bytes(sorted_keys[c]),
            index=int(grid.index[i]),
        )
        ev.sign(sorted_keys[c])
        hg.insert_event(ev, True)
        rows.append(ev)
    return hg, rows


def test_synthetic_grid_matches_host_coordinates():
    """The synthetic generator's coordinate matrices must match what the
    host insert path computes for the same DAG."""
    grid = synthetic_grid(4, 60, seed=7)
    hg, rows = build_hashgraph_from_grid(grid)

    for i, ev in enumerate(rows):
        la_host = np.array([x[0] for x in ev.last_ancestors], dtype=np.int64)
        fd_host = np.array([x[0] for x in ev.first_descendants], dtype=np.int64)
        assert np.array_equal(la_host, grid.last_ancestors[i]), f"LA row {i}"
        assert np.array_equal(fd_host, grid.first_descendants[i]), f"FD row {i}"


def test_synthetic_dag_differential():
    """Random gossip DAG: host engine vs device kernels on the same events
    (coin bits taken from the real event hashes on both sides)."""
    grid = synthetic_grid(5, 120, seed=13)
    hg, _ = build_hashgraph_from_grid(grid)
    assert_equivalent(hg)


# seeds × sizes chosen so each validator count shares one padded device
# shape (e <= 256 pads to one bucket): 3 compiles serve all 10 cases
FUZZ_CASES = [
    (4, 150, 101), (4, 200, 102), (4, 250, 103), (4, 180, 104),
    (5, 150, 201), (5, 220, 202), (5, 250, 203), (5, 170, 204),
    (6, 200, 301), (6, 240, 302),
]


@pytest.mark.parametrize("n,e,seed", FUZZ_CASES)
def test_fuzz_dag_differential(n, e, seed):
    """VERDICT r4 #5: seeded random-DAG fuzz differential in the default
    suite — host engine vs device kernels must agree on rounds, fame,
    round-received, consensus order and block BYTES for every seed. Any
    blind spot shared by a fixture and both engines is exactly what random
    topologies flush out."""
    grid = synthetic_grid(n, e, seed=seed)
    hg, _ = build_hashgraph_from_grid(grid)
    assert_equivalent(hg)


def test_partial_participation_differential():
    """A dark validator leaves padding lanes in level 0 of the device grid
    (regression: duplicate-index scatter must not corrupt row 0)."""
    from dsl import Play, init_hashgraph_nodes, play_events, create_hashgraph
    from babble_tpu.hashgraph import root_self_parent

    # 4 participants, only 3 ever create events
    nodes, index, ordered, participants = init_hashgraph_nodes(4)
    plist = participants.to_peer_slice()
    for i in range(3):
        ev = Event(
            parents=[root_self_parent(plist[i].id), ""],
            creator=nodes[i].pub,
            index=0,
        )
        nodes[i].sign_and_add_event(ev, f"e{i}", index, ordered)
    plays = [
        Play(0, 1, "e0", "e1", "a0", [b"a0"]),
        Play(1, 1, "e1", "a0", "a1", [b"a1"]),
        Play(2, 1, "e2", "a1", "a2", [b"a2"]),
        Play(0, 2, "a0", "a2", "b0", [b"b0"]),
        Play(1, 2, "a1", "b0", "b1", [b"b1"]),
        Play(2, 2, "a2", "b1", "b2", [b"b2"]),
        Play(0, 3, "b0", "b2", "c0", [b"c0"]),
        Play(1, 3, "b1", "c0", "c1", [b"c1"]),
        Play(2, 3, "b2", "c1", "c2", [b"c2"]),
    ]
    play_events(plays, nodes, index, ordered)
    hg = create_hashgraph(ordered, participants)
    assert_equivalent(hg)
