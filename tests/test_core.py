"""Core-pair tests: Cores synchronized manually, no transport
(reference: src/node/core_test.go)."""

import pytest

from babble_tpu.common import hash32
from babble_tpu.crypto import generate_key, pub_key_bytes
from babble_tpu.hashgraph import Event, InmemStore, root_self_parent
from babble_tpu.node import Core
from babble_tpu.peers import Peer, Peers


def init_cores(n):
    cache_size = 1000
    participants = Peers()
    keys_by_id = {}
    for _ in range(n):
        key = generate_key()
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr="", pub_key_hex=pub_hex)
        participants.add_peer(peer)
        keys_by_id[peer.id] = key

    cores = []
    index = {}
    for i, peer in enumerate(participants.to_peer_slice()):
        core = Core(
            i,
            keys_by_id[peer.id],
            participants,
            InmemStore(participants, cache_size),
            None,
        )
        initial = Event(
            transactions=None,
            block_signatures=None,
            parents=[root_self_parent(peer.id), ""],
            creator=core.pub_key(),
            index=0,
        )
        core.sign_and_insert_self_event(initial)
        cores.append(core)
        index[f"e{i}"] = core.head
    return cores, keys_by_id, index


def synchronize_cores(cores, from_, to, payload):
    known_by_to = cores[to].known_events()
    unknown_by_to = cores[from_].event_diff(known_by_to)
    unknown_wire = cores[from_].to_wire(unknown_by_to)
    cores[to].add_transactions(payload)
    cores[to].sync(unknown_wire)


def sync_and_run_consensus(cores, from_, to, payload):
    synchronize_cores(cores, from_, to, payload)
    cores[to].run_consensus()


def init_consensus_hashgraph():
    """The 3-core, 4-super-round playbook driving events to consensus
    (reference: src/node/core_test.go:313-359)."""
    cores, _, _ = init_cores(3)
    playbook = [
        (0, 1, [b"e10"]),
        (1, 2, [b"e21"]),
        (2, 0, [b"e02"]),
        (0, 1, [b"f1"]),
        (1, 0, [b"f0"]),
        (1, 2, [b"f2"]),
        (0, 1, [b"f10"]),
        (1, 2, [b"f21"]),
        (2, 0, [b"f02"]),
        (0, 1, [b"g1"]),
        (1, 0, [b"g0"]),
        (1, 2, [b"g2"]),
        (0, 1, [b"g10"]),
        (1, 2, [b"g21"]),
        (2, 0, [b"g02"]),
        (0, 1, [b"h1"]),
        (1, 0, [b"h0"]),
        (1, 2, [b"h2"]),
    ]
    for from_, to, payload in playbook:
        sync_and_run_consensus(cores, from_, to, payload)
    return cores


def test_event_diff_and_sync():
    cores, _, index = init_cores(3)

    def peer_id(i):
        return hash32(cores[i].pub_key())

    # core 1 tells core 0 everything it knows
    synchronize_cores(cores, 1, 0, [])
    known_by_0 = cores[0].known_events()
    assert known_by_0[peer_id(0)] == 1
    assert known_by_0[peer_id(1)] == 0
    assert known_by_0[peer_id(2)] == -1
    head0 = cores[0].get_head()
    assert head0.self_parent() == index["e0"]
    assert head0.other_parent() == index["e1"]
    index["e01"] = head0.hex()

    # core 0 tells core 2 everything it knows
    synchronize_cores(cores, 0, 2, [])
    known_by_2 = cores[2].known_events()
    assert known_by_2[peer_id(0)] == 1
    assert known_by_2[peer_id(1)] == 0
    assert known_by_2[peer_id(2)] == 1
    head2 = cores[2].get_head()
    assert head2.self_parent() == index["e2"]
    assert head2.other_parent() == index["e01"]
    index["e20"] = head2.hex()

    # core 2 tells core 1 everything it knows
    synchronize_cores(cores, 2, 1, [])
    known_by_1 = cores[1].known_events()
    assert known_by_1[peer_id(0)] == 1
    assert known_by_1[peer_id(1)] == 1
    assert known_by_1[peer_id(2)] == 1
    head1 = cores[1].get_head()
    assert head1.self_parent() == index["e1"]
    assert head1.other_parent() == index["e20"]

    # diff from core 0's perspective of what core 1 is missing
    known_by_1 = cores[1].known_events()
    unknown_by_1 = cores[0].event_diff(known_by_1)
    assert unknown_by_1 == []


def test_consensus():
    cores = init_consensus_hashgraph()
    assert len(cores[0].get_consensus_events()) == 6
    c0 = cores[0].get_consensus_events()
    c1 = cores[1].get_consensus_events()
    c2 = cores[2].get_consensus_events()
    assert c0 == c1 == c2


def test_consensus_transactions_flow():
    cores = init_consensus_hashgraph()
    # every core agrees on the consensus transactions prefix
    txs0 = cores[0].get_consensus_transactions()
    txs1 = cores[1].get_consensus_transactions()
    txs2 = cores[2].get_consensus_transactions()
    assert txs0 == txs1 == txs2


def test_over_sync_limit():
    cores = init_consensus_hashgraph()

    def peer_id(i):
        return hash32(cores[i].pub_key())

    sync_limit = 10
    known = {peer_id(0): 1, peer_id(1): 1, peer_id(2): 1}
    assert cores[0].over_sync_limit(known, sync_limit)

    known = {peer_id(0): 6, peer_id(1): 6, peer_id(2): 6}
    assert not cores[0].over_sync_limit(known, sync_limit)

    known = {peer_id(0): 2, peer_id(1): 3, peer_id(2): 3}
    assert not cores[0].over_sync_limit(known, sync_limit)


def test_core_fast_forward():
    """A lagging core catches up from a peer's anchor block + frame
    (reference: src/node/core_test.go:516-...)."""
    cores = init_consensus_hashgraph()

    # sign enough blocks on core 0's copy that an anchor block appears
    block0 = cores[0].hg.store.get_block(0)
    sig1 = block0.sign(cores[1].key)
    sig2 = block0.sign(cores[2].key)
    block0.set_signature(sig1)
    block0.set_signature(sig2)
    cores[0].hg.store.set_block(block0)
    cores[0].hg.anchor_block = 0

    block, frame = cores[0].get_anchor_block_with_frame()
    assert block.index() == 0
    assert len(frame.events) > 0

    # a brand-new core fast-forwards onto it
    fresh_cores, _, _ = init_cores(3)
    # replace participant set mismatch: reuse core set from same run is
    # required, so fast-forward within the same participant universe
    lagging = Core(
        3,
        cores[1].key,
        cores[1].participants,
        InmemStore(cores[1].participants, 1000),
        None,
    )
    lagging.fast_forward(cores[0].hex_id(), block, frame)
    assert lagging.get_last_block_index() == 0
    assert lagging.hg.last_consensus_round == block.round_received()


def test_core_fast_forward_then_keep_syncing():
    """Regression: consensus must keep advancing on a core that joined
    mid-history via fast-forward. Over the in-process transport, frame
    events arrive as live objects whose cached round/coordinate metadata
    (and shared mutable state) must be stripped at the fast-forward
    boundary, or DivideRounds skips witness registration and the joiner
    stalls forever (reference gets this from Go value+codec semantics)."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 2:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 600, "3-core playbook failed to make blocks"

    blk = cores[0].hg.store.get_block(1)
    for c in cores[:3]:
        blk.set_signature(blk.sign(c.key))
    cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()

    section = cores[0].hg.get_section(frame.round)

    lagging = Core(
        3, cores[3].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    lagging.fast_forward(cores[0].hex_id(), block, frame, section)
    # the live section replays the donor's blocks above the anchor
    joined_at = lagging.get_last_block_index()
    assert joined_at == cores[0].get_last_block_index()
    for bi in range(block.index() + 1, joined_at + 1):
        assert (
            cores[0].hg.store.get_block(bi).body.marshal()
            == lagging.hg.store.get_block(bi).body.marshal()
        ), f"replayed block {bi} differs from donor"

    cores[3] = lagging
    for j in range(120):
        a, b = j % 4, (j + 1) % 4
        sync_and_run_consensus(cores, a, b, [f"post{j}".encode()])

    assert lagging.get_last_block_index() > joined_at + 5, (
        "joiner stalled after fast-forward"
    )
    # every block the joiner produced must be byte-identical to core0's
    hi = min(cores[0].get_last_block_index(), lagging.get_last_block_index())
    for bi in range(joined_at + 1, hi + 1):
        assert (
            cores[0].hg.store.get_block(bi).body.marshal()
            == lagging.hg.store.get_block(bi).body.marshal()
        )


def test_fast_synced_core_serves_its_own_anchor():
    """Regression: a core that joined via fast-forward must be able to
    SERVE the anchor it now holds. The received frame's round predates the
    reset, so the joiner cannot rebuild it from round bookkeeping — reset
    must keep the validated frame itself in the frame cache, or every
    FastForwardRequest the joiner serves dies with a missing-round error
    (observed livelocking a cluster whose only Babbling node was a fresh
    joiner: the CatchingUp peers refuse each other, the joiner errors)."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 2:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 600

    blk = cores[0].hg.store.get_block(1)
    for c in cores[:3]:
        blk.set_signature(blk.sign(c.key))
    cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()
    section = cores[0].hg.get_section(frame.round)

    joiner = Core(
        3, cores[3].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    joiner.fast_forward(cores[0].hex_id(), block, frame, section)

    # the joiner holds the signed anchor block; it must serve it with the
    # exact frame it validated (chained fast-sync donor capability)
    joiner.hg.anchor_block = block.index()
    served_block, served_frame = joiner.get_anchor_block_with_frame()
    assert served_block.index() == block.index()
    assert served_frame.hash() == frame.hash()

    # ... and a second-generation joiner fast-forwards off it
    joiner2 = Core(
        2, cores[2].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    section2 = joiner.hg.get_section(served_frame.round)
    joiner2.fast_forward(joiner.hex_id(), served_block, served_frame, section2)
    assert joiner2.get_last_block_index() >= block.index()


def test_section_truncates_at_unprovable_block():
    """A donor whose stored chain contains a block that can no longer
    gather >1/3 signatures (its signers died right after commit) must
    TRUNCATE its section at that block instead of shipping frames the
    joiner is bound to reject — otherwise every fast-forward from this
    donor fails forever and a die-off survivor can never serve a joiner.
    The joiner syncs the provable prefix and recomputes the rest from
    the shipped events."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 5:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 1500, "3-core playbook failed to make blocks"

    for bi in range(1, cores[0].get_last_block_index() + 1):
        blk = cores[0].hg.store.get_block(bi)
        for c in cores[:3]:
            blk.set_signature(blk.sign(c.key))
        cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()

    # block 3 permanently under-signed: keep only the donor's own signature
    b3 = cores[0].hg.store.get_block(3)
    b3.signatures = {k: v for k, v in list(b3.signatures.items())[:1]}
    cores[0].hg.store.set_block(b3)

    section = cores[0].hg.get_section(frame.round, block.index())
    # the donor must not ship provable-prefix-violating frames: the frame
    # producing block 3 sits deeper than the joiner's 2-round trust window
    # in the untruncated section, so the section must stop early
    b3_round = cores[0].hg.store.get_block(3).round_received()
    assert max(f.round for f in section.frames) <= b3_round + 1

    joiner = Core(
        3, cores[3].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    joiner.fast_forward(cores[0].hex_id(), block, frame, section)
    assert joiner.get_last_block_index() >= block.index()
    # the provable prefix replayed byte-identically
    for bi in range(block.index() + 1, min(3, joiner.get_last_block_index() + 1)):
        assert (
            cores[0].hg.store.get_block(bi).body.marshal()
            == joiner.hg.store.get_block(bi).body.marshal()
        )


def test_verify_section_rejects_forged_continuation():
    """A single malicious donor must not be able to feed a joiner a
    fabricated consensus continuation: every replayed block outside the
    signature-propagation lag window needs >1/3 valid validator signatures
    (Hashgraph.verify_section)."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 5:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 1500, "3-core playbook failed to make blocks"

    # accumulate validator signatures on the donor's stored blocks — in a
    # live node process_sig_pool does this from gossiped signatures
    for bi in range(1, cores[0].get_last_block_index() + 1):
        blk = cores[0].hg.store.get_block(bi)
        for c in cores[:3]:
            blk.set_signature(blk.sign(c.key))
        cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()
    section = cores[0].hg.get_section(frame.round, block.index())

    def fresh_joiner():
        return Core(
            3, cores[3].key, cores[0].participants,
            InmemStore(cores[0].participants, 1000), None,
        )

    # the honest section passes
    fresh_joiner().fast_forward(cores[0].hex_id(), block, frame, section)

    # tampered continuation: forge a transaction inside the earliest
    # replayed frame — the donor's accumulated signatures no longer match
    # the rebuilt block body
    from babble_tpu.hashgraph import Section

    forged = Section.from_json(section.to_json())
    target = forged.frames[0]
    assert target.events, "first replayed frame unexpectedly empty"
    target.events[0].body.transactions = [b"forged tx"]
    with pytest.raises(ValueError):
        fresh_joiner().fast_forward(cores[0].hex_id(), block, frame, forged)

    # a continuation with its signature proof stripped must also fail for
    # frames old enough that signatures must have propagated
    stripped = Section.from_json(section.to_json())
    stripped.proof_blocks = {}
    with pytest.raises(ValueError):
        fresh_joiner().fast_forward(cores[0].hex_id(), block, frame, stripped)


def test_verify_section_rejects_non_validator_signatures():
    """Signatures from keys outside the validator set prove nothing: a
    donor forging frames + proof blocks signed by throwaway keys must be
    rejected (both by verify_section and check_block)."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 5:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 1500, "3-core playbook failed to make blocks"

    for bi in range(1, cores[0].get_last_block_index() + 1):
        blk = cores[0].hg.store.get_block(bi)
        for c in cores[:3]:
            blk.set_signature(blk.sign(c.key))
        cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()
    section = cores[0].hg.get_section(frame.round, block.index())

    # replace every proof block's signatures with ones from throwaway keys
    from babble_tpu.hashgraph import Section

    forged = Section.from_json(section.to_json())
    attackers = [generate_key() for _ in range(3)]
    for pb in forged.proof_blocks.values():
        pb.signatures.clear()
        for k in attackers:
            pb.set_signature(pb.sign(k))

    joiner = Core(
        3, cores[3].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    with pytest.raises(ValueError):
        joiner.fast_forward(cores[0].hex_id(), block, frame, forged)

    # check_block: an anchor signed only by outsiders must fail too
    from babble_tpu.hashgraph import Block

    fake_anchor = Block.from_json(block.to_json())
    fake_anchor.signatures.clear()
    for k in attackers:
        fake_anchor.set_signature(fake_anchor.sign(k))
    with pytest.raises(ValueError):
        joiner.hg.check_block(fake_anchor)


def test_section_scrub_drops_unproven_decided_metadata():
    """ADVICE r3 (medium): donor-stamped DECIDED state above the
    proof-checked frame prefix (+ the two-round sig-lag window) must not
    seed the joiner's block composition. The attacker pads the section
    with fabricated contiguous EMPTY frames (exempt from per-block proof
    pairing — they mint no block) to lift the shipped-frame ceiling,
    plants a fully-'decided' RoundInfo above the proven prefix, and
    stamps a shipped event as received there. The joiner must scrub all
    of it (Hashgraph.apply_section) and RE-DECIDE through its own
    consensus passes: replayed blocks byte-match the donor's real chain
    and the forged reception never lands."""
    cores, keys, _ = init_cores(4)
    i = 0
    while cores[0].get_last_block_index() < 5:
        a, b = i % 3, (i + 1) % 3
        sync_and_run_consensus(cores, a, b, [f"tx{i}".encode()])
        i += 1
        assert i < 1500, "3-core playbook failed to make blocks"

    for bi in range(1, cores[0].get_last_block_index() + 1):
        blk = cores[0].hg.store.get_block(bi)
        for c in cores[:3]:
            blk.set_signature(blk.sign(c.key))
        cores[0].hg.store.set_block(blk)
    cores[0].hg.anchor_block = 1
    block, frame = cores[0].get_anchor_block_with_frame()
    section = cores[0].hg.get_section(frame.round, block.index())

    from babble_tpu.hashgraph import Frame, RoundInfo, Section

    forged = Section.from_json(section.to_json())
    top = max(f.round for f in forged.frames)
    roots = forged.frames[-1].roots
    for r in range(top + 1, top + 5):
        forged.frames.append(Frame(round=r, roots=roots, events=[]))
    target_round = top + 4
    victim = next(ev for ev in forged.events if ev.round_received is None)
    victim.set_round(target_round)
    victim.set_round_received(target_round)
    ri = RoundInfo()
    ri.add_event(victim.hex(), witness=True)
    ri.set_fame(victim.hex(), True)
    ri.set_consensus_event(victim.hex())
    forged.rounds[target_round] = ri

    joiner = Core(
        3, cores[3].key, cores[0].participants,
        InmemStore(cores[0].participants, 1000), None,
    )
    joiner.fast_forward(cores[0].hex_id(), block, frame, forged)

    # no fabricated block: everything committed matches the donor's chain
    for bi in range(block.index() + 1, joiner.get_last_block_index() + 1):
        assert (
            joiner.hg.store.get_block(bi).body.marshal()
            == cores[0].hg.store.get_block(bi).body.marshal()
        ), f"block {bi} diverged from the donor's real chain"
    # the forged reception did not survive the scrub
    jev = joiner.hg.store.get_event(victim.hex())
    assert jev.round_received != target_round

    # a section with a round GAP in its frames must be rejected outright
    # (gaps desynchronize the frame->block proof index chain)
    gapped = Section.from_json(section.to_json())
    assert len(gapped.frames) > 1, "fixture must ship a multi-frame section"
    del gapped.frames[0]
    with pytest.raises(ValueError):
        Core(
            3, cores[3].key, cores[0].participants,
            InmemStore(cores[0].participants, 1000), None,
        ).fast_forward(cores[0].hex_id(), block, frame, gapped)
