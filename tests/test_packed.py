"""Bit-packed voting kernels (ISSUE 17, babble_tpu/tpu/packed.py).

The packed layout packs the voted-witness axis of the strongly-seen and
vote tables into uint32 lanes and re-derives every super-majority tally
as a popcount reduction. It is a LAYOUT, never an observable: every test
here is a byte-equality gate of packed against wide on a fixture rung —
one-shot, post-reset/amnesiac sections, the real consensus fixture, the
doubling cold path, the 2-D sharded mesh with non-lane-aligned validator
shards, and the incremental step/train paths — plus the seeded
single-bit-flip arm the PR 11 bisector must localize to its exact
(pass, table, round, witness) cell.
"""

import os
import random
from dataclasses import replace

import jax
import numpy as np
import pytest

from babble_tpu.obs import Observability, bisect_pass_results
from babble_tpu.tpu import synthetic_grid
from babble_tpu.tpu.engine import run_frontier_passes, run_passes
from babble_tpu.tpu.grid import section_grid, synthetic_deep_grid
from babble_tpu.tpu.packed import (
    LANE,
    PACKED_AUTO_MIN_N,
    observe_table_bytes,
    pack_bits,
    pack_votes_t,
    packed_count,
    packed_enabled,
    packed_mode,
    packed_tally,
    packed_words,
    popcount_sum,
    resolve_packed,
    set_packed_mode,
    unpack_bits,
    voting_table_bytes,
)

PASS_FIELDS = (
    "rounds", "witness", "lamport", "fame_decided", "rounds_decided",
    "received",
)


def assert_results_equal(a, b, fields=PASS_FIELDS):
    for f in fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), f
        )
    # famous is only defined where fame is decided
    np.testing.assert_array_equal(
        np.asarray(a.famous) & np.asarray(a.fame_decided),
        np.asarray(b.famous) & np.asarray(b.fame_decided),
    )
    assert int(a.last_round) == int(b.last_round)


# ---------------------------------------------------------------------------
# lane packing primitives
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 7, 31, 32, 33, 64, 100])
def test_pack_unpack_round_trip(n):
    rng = np.random.default_rng(n)
    x = rng.integers(0, 2, size=(3, 5, n)).astype(bool)
    xp = np.asarray(pack_bits(x))
    assert xp.shape == (3, 5, packed_words(n))
    assert xp.dtype == np.uint32
    np.testing.assert_array_equal(np.asarray(unpack_bits(xp, n)), x)
    # popcount over words == the wide sum over lanes
    np.testing.assert_array_equal(
        np.asarray(popcount_sum(xp)), x.sum(axis=-1).astype(np.int32)
    )
    np.testing.assert_array_equal(
        np.asarray(packed_count(x)), x.sum(axis=-1).astype(np.int32)
    )


def test_padding_lanes_are_vote_neutral():
    """pack_bits zero-fills the trailing partial word, so padding lanes
    contribute nothing to any popcount tally — the all-ones row of a
    non-lane-aligned width must count exactly its width."""
    n = 7
    ones = np.ones((4, n), dtype=bool)
    xp = np.asarray(pack_bits(ones))
    assert xp.shape == (4, 1)
    assert (xp == (1 << n) - 1).all()  # top LANE-7 bits stay zero
    np.testing.assert_array_equal(
        np.asarray(popcount_sum(xp)), np.full(4, n, dtype=np.int32)
    )


def test_packed_tally_equals_wide_einsum():
    rng = np.random.default_rng(17)
    r_, ny, nx, w = 3, 9, 9, 70
    ss = rng.integers(0, 2, size=(r_, ny, w)).astype(bool)
    votes = rng.integers(0, 2, size=(r_, w, nx)).astype(bool)
    wide = np.einsum(
        "ryw,rwx->ryx", ss.astype(np.float32), votes.astype(np.float32)
    ).astype(np.int32)
    got = np.asarray(packed_tally(pack_bits(ss), pack_votes_t(votes)))
    np.testing.assert_array_equal(got, wide)


def test_pack_votes_t_packs_the_voter_axis():
    rng = np.random.default_rng(5)
    votes = rng.integers(0, 2, size=(2, 33, 6)).astype(bool)  # (R, W, X)
    vp = np.asarray(pack_votes_t(votes))
    assert vp.shape == (2, 6, packed_words(33))
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(vp, 33)), np.swapaxes(votes, 1, 2)
    )


# ---------------------------------------------------------------------------
# mode knob
# ---------------------------------------------------------------------------


def test_mode_knob_env_and_resolution(monkeypatch):
    monkeypatch.delenv("BABBLE_PACKED_VOTING", raising=False)
    try:
        set_packed_mode("auto")
        assert packed_mode() == "auto"
        assert not packed_enabled(PACKED_AUTO_MIN_N - 1)
        assert packed_enabled(PACKED_AUTO_MIN_N)
        set_packed_mode("1")
        assert packed_enabled(4)
        set_packed_mode("0")
        assert not packed_enabled(4096)
        # the env var wins over the process-global mode at call time
        monkeypatch.setenv("BABBLE_PACKED_VOTING", "1")
        assert packed_mode() == "1" and packed_enabled(4)
        monkeypatch.setenv("BABBLE_PACKED_VOTING", "0")
        assert not packed_enabled(4096)
        # per-call override beats both
        assert resolve_packed(True, 4) is True
        assert resolve_packed(False, 4096) is False
        monkeypatch.delenv("BABBLE_PACKED_VOTING")
        with pytest.raises(ValueError):
            set_packed_mode("banana")
    finally:
        set_packed_mode("auto")


def test_engine_honors_env_knob(monkeypatch):
    """run_passes with packed=None resolves the layout from the env knob;
    both settings must agree byte-for-byte."""
    grid = synthetic_grid(7, 160, seed=9)
    monkeypatch.setenv("BABBLE_PACKED_VOTING", "0")
    wide = run_passes(grid)
    monkeypatch.setenv("BABBLE_PACKED_VOTING", "1")
    packed = run_passes(grid)
    assert_results_equal(wide, packed)


# ---------------------------------------------------------------------------
# differential matrix: packed must be byte-equal to wide on every rung
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n,e,seed",
    [
        (7, 160, 9),    # non-lane-aligned: 25 padding lanes in play
        (33, 400, 4),   # crosses a word boundary (2 words, 31 pad lanes)
        (64, 512, 1),   # lane-aligned
    ],
)
def test_one_shot_packed_matches_wide(n, e, seed):
    grid = synthetic_grid(n, e, seed=seed)
    assert_results_equal(
        run_passes(grid, packed=False), run_passes(grid, packed=True)
    )
    assert_results_equal(
        run_frontier_passes(grid, packed=False),
        run_frontier_passes(grid, packed=True),
    )


def test_consensus_fixture_packed_matches_wide():
    """The real reference fixture (signed events through the host store),
    including the coin-branch topology the wide fame loop exercises."""
    from dsl import init_consensus_hashgraph
    from babble_tpu.tpu.grid import grid_from_hashgraph

    hg, _, _ = init_consensus_hashgraph()
    grid = grid_from_hashgraph(hg)
    assert_results_equal(
        run_passes(grid, packed=False), run_passes(grid, packed=True)
    )


@pytest.mark.parametrize("pin_cut", [True, False])
def test_section_grids_packed_matches_wide(pin_cut):
    """Post-reset (pin_cut=True) and amnesiac (pin_cut=False) sections:
    external parent metadata and pinned cut rounds must not disturb the
    packed tallies."""
    grid = synthetic_grid(7, 320, seed=6)
    full = run_passes(grid)
    sec = section_grid(grid, full, grid.num_levels // 2, pin_cut=pin_cut)
    assert_results_equal(
        run_passes(sec, packed=False), run_passes(sec, packed=True)
    )


def test_doubling_cold_path_packed_matches_wide():
    from babble_tpu.tpu.doubling import run_doubling_passes

    deep = synthetic_deep_grid(7, 2000, seed=11)
    assert_results_equal(
        run_doubling_passes(deep, packed=False),
        run_doubling_passes(deep, packed=True),
    )


@pytest.mark.parametrize("dv,dr", [(2, 2), (4, 2)])
def test_sharded_2d_mesh_packed_matches_wide(dv, dr):
    """2-D (validators, rounds) mesh with validator counts that do NOT
    divide into whole lanes per shard: the witness axis is padded to a
    multiple of LANE * dv so every shard owns whole words, and the psum
    of per-shard popcount tallies must equal the wide psum bit-exactly."""
    from jax.sharding import Mesh
    from babble_tpu.tpu.sharded import (
        sharded_frontier_passes, sharded_run_passes,
    )

    devices = jax.devices("cpu")
    if len(devices) < dv * dr:
        pytest.skip(f"need {dv * dr} CPU devices, have {len(devices)}")
    mesh = Mesh(
        np.array(devices[: dv * dr]).reshape(dv, dr),
        ("validators", "rounds"),
    )
    for n, e, seed in ((7, 160, 9), (33, 320, 4)):
        grid = synthetic_grid(n, e, seed=seed)
        assert_results_equal(
            sharded_run_passes(mesh, grid, packed=False),
            sharded_run_passes(mesh, grid, packed=True),
        )
        assert_results_equal(
            sharded_frontier_passes(mesh, grid, packed=False),
            sharded_frontier_passes(mesh, grid, packed=True),
        )


def test_incremental_step_and_train_packed_match_wide():
    from babble_tpu.tpu.incremental import (
        batches_from_grid, init_state, step, train_step, trains_from_grid,
    )

    n, e = 7, 512
    grid = synthetic_grid(n, e, seed=3, zipf_a=1.1, record_fd_updates=True)
    sm = grid.super_majority

    arms = {}
    for packed in (False, True):
        st = init_state(n, e, 64)
        for b in batches_from_grid(grid, 32, 8192, e):
            st = step(st, b, sm, n, e_win=512, packed=packed)
        arms[packed] = st
    for f in ("rounds", "lamport", "witness", "received", "wtable",
              "fame_decided", "famous", "rounds_decided"):
        np.testing.assert_array_equal(
            np.asarray(getattr(arms[False], f)),
            np.asarray(getattr(arms[True], f)), f,
        )
    assert int(arms[True].last_round) == int(arms[False].last_round)

    tr_arms = {}
    for packed in (False, True):
        st = init_state(n, e, 64)
        for t in trains_from_grid(grid, 128, 8192, e, w_cap=16, t_cap=64):
            st = train_step(st, t, sm, n, e_win=512, packed=packed)
        tr_arms[packed] = st
    for f in ("rounds", "lamport", "witness", "received", "wtable",
              "fame_decided", "famous", "rounds_decided"):
        np.testing.assert_array_equal(
            np.asarray(getattr(tr_arms[False], f)),
            np.asarray(getattr(tr_arms[True], f)), f,
        )


# ---------------------------------------------------------------------------
# table-bytes accounting
# ---------------------------------------------------------------------------


def test_table_bytes_reduction_and_gauge():
    # lane-aligned N: exactly 8x (uint32 words of 32 lanes vs 32 bools);
    # the ISSUE 17 acceptance floor is 4x at N >= 128
    for n in (128, 1024):
        wide = voting_table_bytes(n, 16, False)
        packed = voting_table_bytes(n, 16, True)
        assert set(wide) == {"strongly_seen", "votes"}
        for t in wide:
            assert wide[t] == 16 * n * n
            assert packed[t] == 16 * n * 4 * packed_words(n)
            assert wide[t] / packed[t] >= 4.0
    obs = Observability()
    sizes = observe_table_bytes(obs, 128, 16, True)
    g = obs.registry.get("babble_device_table_bytes")
    assert g is not None
    for t, nbytes in sizes.items():
        assert g.value(table=t, layout="packed") == nbytes
    observe_table_bytes(obs, 128, 16, False)
    assert (
        g.value(table="votes", layout="wide")
        == 8.0 * g.value(table="votes", layout="packed")
    )


# ---------------------------------------------------------------------------
# seeded single-bit flip: the PR 11 bisector owns packed-vs-wide divergence
# ---------------------------------------------------------------------------


def test_seeded_bit_flip_localizes_to_exact_cell(tmp_path):
    """Flip exactly one decided famous bit in the PACKED arm: the
    divergence bisector must localize packed-vs-wide to that exact
    (pass, table, round, witness) cell — the triage path a real packed
    tally defect would take."""
    from babble_tpu.obs.provenance import grid_cell_keys

    grid = synthetic_grid(7, 160, seed=9)
    wide = run_passes(grid, packed=False)
    packed = run_passes(grid, packed=True)

    # clean arm: byte-equal, nothing to localize, no artifact
    loc, path = bisect_pass_results(
        grid, "wide", wide, "packed", packed,
        artifact_dir=str(tmp_path), label="packed-clean",
    )
    assert loc is None and path is None and not os.listdir(tmp_path)

    candidates = [
        (ti, c, int(packed.witness_table[ti, c]))
        for ti in range(packed.witness_table.shape[0])
        for c in range(packed.witness_table.shape[1])
        if int(packed.witness_table[ti, c]) >= 0
        and bool(packed.fame_decided[ti, c])
    ]
    assert candidates, "fixture decided no fame at all"
    ti, c, wrow = candidates[random.Random(17).randrange(len(candidates))]
    famous = np.array(packed.famous, copy=True)
    famous[ti, c] = not bool(famous[ti, c])
    broken = replace(packed, famous=famous)
    inj_round = ti + int(getattr(packed, "round_offset", 0))
    inj_hash = grid_cell_keys(grid)[wrow]

    loc, path = bisect_pass_results(
        grid, "wide", wide, "packed", broken,
        artifact_dir=str(tmp_path), label="packed-flip",
    )
    assert (loc["round"], loc["pass"], loc["table"], loc["cell"]) == (
        inj_round, "fame", "fame", inj_hash,
    )
    assert os.path.basename(path) == "bisect-packed-flip-wide-vs-packed.json"
