"""Consensus-core tests on hand-built DAGs
(reference: src/hashgraph/hashgraph_test.go).

Scenario tables (ancestry, rounds, timestamps, fame, consensus order) are
transcribed from the reference so the rebuilt engine is checked against the
same expectations.
"""

import pytest

from babble_tpu.common import StoreErr
from babble_tpu.hashgraph import (
    Hashgraph,
    InmemStore,
    RoundEvent,
    RoundInfo,
    SQLiteStore,
    Trilean,
)
from dsl import (
    CACHE_SIZE,
    get_name,
    init_consensus_hashgraph,
    init_round_hashgraph,
    init_simple_hashgraph,
)

MAX_INT32 = 2**31 - 1


def sqlite_factory(tmp_path):
    def factory(participants):
        return SQLiteStore(participants, CACHE_SIZE, str(tmp_path / "store.db"))

    return factory


# ---------------------------------------------------------------------------
# ancestry predicates (reference: TestAncestor :204, TestSelfAncestor :251,
# TestSee :283, TestLamportTimestamp :308)
# ---------------------------------------------------------------------------


class TestSimpleDag:
    @pytest.fixture(autouse=True)
    def setup(self):
        self.h, self.index, _ = init_simple_hashgraph()

    def check(self, fn, table):
        for descendant, ancestor, val in table:
            assert fn(self.index[descendant], self.index[ancestor]) == val, (
                f"{fn.__name__}({descendant}, {ancestor}) should be {val}"
            )

    def test_ancestor(self):
        self.check(
            self.h.ancestor,
            [
                # first generation
                ("e01", "e0", True),
                ("e01", "e1", True),
                ("s00", "e01", True),
                ("s20", "e2", True),
                ("e20", "s00", True),
                ("e20", "s20", True),
                ("e12", "e20", True),
                ("e12", "s10", True),
                # second generation
                ("s00", "e0", True),
                ("s00", "e1", True),
                ("e20", "e01", True),
                ("e20", "e2", True),
                ("e12", "e1", True),
                ("e12", "s20", True),
                # third generation
                ("e20", "e0", True),
                ("e20", "e1", True),
                ("e20", "e2", True),
                ("e12", "e01", True),
                ("e12", "e0", True),
                ("e12", "e1", True),
                ("e12", "e2", True),
                # false positives
                ("e01", "e2", False),
                ("s00", "e2", False),
            ],
        )

    def test_ancestor_unknown_raises(self):
        with pytest.raises((StoreErr, KeyError)):
            self.h.ancestor(self.index["e0"], "")

    def test_self_ancestor(self):
        self.check(
            self.h.self_ancestor,
            [
                ("e01", "e0", True),
                ("s00", "e01", True),
                ("e01", "e1", False),
                ("e12", "e20", False),
                ("s20", "e1", False),
                ("e20", "e2", True),
                ("e12", "e1", True),
                ("e20", "e0", False),
                ("e12", "e2", False),
                ("e20", "e01", False),
            ],
        )

    def test_see(self):
        self.check(
            self.h.see,
            [
                ("e01", "e0", True),
                ("e01", "e1", True),
                ("e20", "e0", True),
                ("e20", "e01", True),
                ("e12", "e01", True),
                ("e12", "e0", True),
                ("e12", "e1", True),
                ("e12", "s20", True),
            ],
        )

    def test_lamport_timestamp(self):
        expected = {
            "e0": 0,
            "e1": 0,
            "e2": 0,
            "e01": 1,
            "s10": 1,
            "s20": 1,
            "s00": 2,
            "e20": 3,
            "e12": 4,
        }
        for name, ts in expected.items():
            assert self.h.lamport_timestamp(self.index[name]) == ts, name


# ---------------------------------------------------------------------------
# round hashgraph (reference: TestInsertEvent :436, TestStronglySee :611,
# TestWitness :645, TestRound :679, TestDivideRounds :743)
# ---------------------------------------------------------------------------


class TestRoundDag:
    @pytest.fixture(autouse=True)
    def setup(self):
        self.h, self.index, _ = init_round_hashgraph()

    def _set_round0_witnesses(self):
        ri = RoundInfo()
        for name in ("e0", "e1", "e2"):
            ri.events[self.index[name]] = RoundEvent(witness=True)
        self.h.store.set_round(0, ri)

    def test_insert_event_coordinates(self):
        h, index = self.h, self.index
        e0 = h.store.get_event(index["e0"])
        assert e0.body.self_parent_index == -1
        assert e0.body.other_parent_creator_id == -1
        assert e0.body.other_parent_index == -1
        assert e0.body.creator_id == h.participants.by_pub_key[e0.creator()].id

        assert e0.first_descendants == [
            (0, index["e0"]),
            (1, index["e10"]),
            (2, index["e21"]),
        ]
        assert e0.last_ancestors == [(0, index["e0"]), (-1, ""), (-1, "")]

        e21 = h.store.get_event(index["e21"])
        e10 = h.store.get_event(index["e10"])
        assert e21.body.self_parent_index == 1
        assert e21.body.other_parent_creator_id == h.participants.by_pub_key[e10.creator()].id
        assert e21.body.other_parent_index == 1

        assert e21.first_descendants == [
            (2, index["e02"]),
            (3, index["f1"]),
            (2, index["e21"]),
        ]
        assert e21.last_ancestors == [
            (0, index["e0"]),
            (1, index["e10"]),
            (2, index["e21"]),
        ]

        f1 = h.store.get_event(index["f1"])
        assert f1.body.self_parent_index == 2
        assert f1.body.other_parent_index == 2
        assert f1.first_descendants == [
            (MAX_INT32, ""),
            (3, index["f1"]),
            (MAX_INT32, ""),
        ]
        assert f1.last_ancestors == [
            (2, index["e02"]),
            (3, index["f1"]),
            (2, index["e21"]),
        ]

    def test_undetermined_events_and_pending_loaded(self):
        h, index = self.h, self.index
        expected = [
            index[n]
            for n in ["e0", "e1", "e2", "e10", "s20", "s00", "e21", "e02", "s10", "f1", "s11"]
        ]
        assert h.undetermined_events == expected
        # 3 events with index 0 + 1 event with transactions
        assert h.pending_loaded_events == 4

    def test_read_wire_info_roundtrip(self):
        h, index = self.h, self.index
        for name, evh in self.index.items():
            ev = h.store.get_event(evh)
            ev_from_wire = h.read_wire_info(ev.to_wire())
            assert ev.body.to_canonical() == ev_from_wire.body.to_canonical(), name
            assert ev.signature == ev_from_wire.signature, name
            assert ev_from_wire.verify(), name
            assert ev_from_wire.hex() == ev.hex(), name

    def test_strongly_see(self):
        table = [
            ("e21", "e0", True),
            ("e02", "e10", True),
            ("e02", "e0", True),
            ("e02", "e1", True),
            ("f1", "e21", True),
            ("f1", "e10", True),
            ("f1", "e0", True),
            ("f1", "e1", True),
            ("f1", "e2", True),
            ("s11", "e2", True),
            # false negatives
            ("e10", "e0", False),
            ("e21", "e1", False),
            ("e21", "e2", False),
            ("e02", "e2", False),
            ("s11", "e02", False),
        ]
        for x, y, val in table:
            assert self.h.strongly_see(self.index[x], self.index[y]) == val, (x, y)

    def test_witness(self):
        self._set_round0_witnesses()
        ri = RoundInfo()
        ri.events[self.index["f1"]] = RoundEvent(witness=True)
        self.h.store.set_round(1, ri)

        for name, val in [
            ("e0", True),
            ("e1", True),
            ("e2", True),
            ("f1", True),
            ("e10", False),
            ("e21", False),
            ("e02", False),
        ]:
            assert self.h.witness(self.index[name]) == val, name

    def test_round(self):
        self._set_round0_witnesses()
        for name, r in [
            ("e0", 0),
            ("e1", 0),
            ("e2", 0),
            ("s00", 0),
            ("e10", 0),
            ("s20", 0),
            ("e21", 0),
            ("e02", 0),
            ("s10", 0),
            ("f1", 1),
            ("s11", 1),
        ]:
            assert self.h.round(self.index[name]) == r, name

    def test_round_diff(self):
        self._set_round0_witnesses()
        assert self.h.round_diff(self.index["f1"], self.index["e02"]) == 1
        assert self.h.round_diff(self.index["e02"], self.index["f1"]) == -1
        assert self.h.round_diff(self.index["e02"], self.index["e21"]) == 0

    def test_divide_rounds(self):
        h, index = self.h, self.index
        h.divide_rounds()

        assert h.store.last_round() == 1
        round0 = h.store.get_round(0)
        assert sorted(round0.witnesses()) == sorted(
            [index["e0"], index["e1"], index["e2"]]
        )
        round1 = h.store.get_round(1)
        assert round1.witnesses() == [index["f1"]]

        assert [(pr.index, pr.decided) for pr in h.pending_rounds] == [
            (0, False),
            (1, False),
        ]

        expected = {
            "e0": (0, 0),
            "e1": (0, 0),
            "e2": (0, 0),
            "s00": (1, 0),
            "e10": (1, 0),
            "s20": (1, 0),
            "e21": (2, 0),
            "e02": (3, 0),
            "s10": (2, 0),
            "f1": (4, 1),
            "s11": (5, 1),
        }
        for name, (ts, r) in expected.items():
            ev = h.store.get_event(index[name])
            assert ev.round == r, name
            assert ev.lamport_timestamp == ts, name

    def test_create_root(self):
        h, index = self.h, self.index
        h.divide_rounds()
        participants = h.participants.to_peer_slice()

        from babble_tpu.hashgraph import Root, RootEvent, new_base_root

        expected = {
            "e0": new_base_root(participants[0].id),
            "e02": Root(
                next_round=0,
                self_parent=RootEvent(index["s00"], participants[0].id, 1, 1, 0),
                others={index["e02"]: RootEvent(index["e21"], participants[2].id, 2, 2, 0)},
            ),
            "s10": Root(
                next_round=0,
                self_parent=RootEvent(index["e10"], participants[1].id, 1, 1, 0),
                others={},
            ),
            "f1": Root(
                next_round=1,
                self_parent=RootEvent(index["s10"], participants[1].id, 2, 2, 0),
                others={index["f1"]: RootEvent(index["e02"], participants[0].id, 2, 3, 0)},
            ),
        }
        for name, exp in expected.items():
            ev = h.store.get_event(index[name])
            root = h._create_root(ev)
            assert root == exp, name


# ---------------------------------------------------------------------------
# consensus pipeline (reference: TestDivideRoundsBis :1208, TestDecideFame
# :1267, TestDecideRoundReceived :1346, TestProcessDecidedRounds :1419)
# ---------------------------------------------------------------------------


class TestConsensusPipeline:
    @pytest.fixture(autouse=True)
    def setup(self):
        self.h, self.index, _ = init_consensus_hashgraph()

    def test_divide_rounds_bis(self):
        h, index = self.h, self.index
        h.divide_rounds()
        expected = {
            "e0": (0, 0), "e1": (0, 0), "e2": (0, 0),
            "e10": (1, 0), "e21": (2, 0), "e21b": (3, 0), "e02": (4, 0),
            "f1": (5, 1), "f1b": (6, 1), "f0": (7, 1), "f2": (7, 1),
            "f10": (8, 1), "f0x": (8, 1), "f21": (9, 1), "f02": (10, 1),
            "f02b": (11, 1),
            "g1": (12, 2), "g0": (13, 2), "g2": (13, 2), "g10": (14, 2),
            "g21": (15, 2), "g02": (16, 2),
            "h1": (17, 3), "h0": (18, 3), "h2": (18, 3), "h10": (19, 3),
            "h21": (20, 3), "h02": (21, 3),
            "i1": (22, 4), "i0": (23, 4), "i2": (23, 4),
        }
        for name, (ts, r) in expected.items():
            ev = h.store.get_event(index[name])
            assert ev.round == r, f"{name} round"
            assert ev.lamport_timestamp == ts, f"{name} ts"

    def test_decide_fame(self):
        h, index = self.h, self.index
        h.divide_rounds()
        h.decide_fame()

        round0 = h.store.get_round(0)
        for name in ("e0", "e1", "e2"):
            assert round0.events[index[name]].famous == Trilean.TRUE, name
        round1 = h.store.get_round(1)
        for name in ("f0", "f1", "f2"):
            assert round1.events[index[name]].famous == Trilean.TRUE, name
        round2 = h.store.get_round(2)
        for name in ("g0", "g1", "g2"):
            assert round2.events[index[name]].famous == Trilean.TRUE, name

        assert [(pr.index, pr.decided) for pr in h.pending_rounds[:3]] == [
            (0, True),
            (1, True),
            (2, True),
        ]

    def test_decide_round_received(self):
        h, index = self.h, self.index
        h.divide_rounds()
        h.decide_fame()
        h.decide_round_received()

        for name, hash_ in index.items():
            e = h.store.get_event(hash_)
            if name.startswith("e"):
                assert e.round_received == 1, name
            elif name.startswith("f"):
                assert e.round_received == 2, name
            else:
                assert e.round_received is None, name

        assert len(h.store.get_round(0).consensus_events()) == 0
        assert len(h.store.get_round(1).consensus_events()) == 7
        assert len(h.store.get_round(2).consensus_events()) == 9

        expected_undetermined = [
            index[n]
            for n in [
                "g1", "g0", "g2", "g10", "g21", "g02",
                "h1", "h0", "h2", "h10", "h21", "h02",
                "i1", "i0", "i2",
            ]
        ]
        assert h.undetermined_events == expected_undetermined

    def test_process_decided_rounds(self):
        h, index = self.h, self.index
        committed = []
        h.commit_callback = committed.append
        h.divide_rounds()
        h.decide_fame()
        h.decide_round_received()
        h.process_decided_rounds()

        consensus_events = h.store.consensus_events()
        assert len(consensus_events) == 16
        assert h.pending_loaded_events == 2

        block0 = h.store.get_block(0)
        assert block0.index() == 0
        assert block0.round_received() == 1
        assert block0.transactions() == [b"e21"]
        frame1 = h.get_frame(block0.round_received())
        assert block0.frame_hash() == frame1.hash()

        block1 = h.store.get_block(1)
        assert block1.index() == 1
        assert block1.round_received() == 2
        assert len(block1.transactions()) == 2
        assert block1.transactions()[1] == b"f02b"
        frame2 = h.get_frame(block1.round_received())
        assert block1.frame_hash() == frame2.hash()

        assert [(pr.index, pr.decided) for pr in h.pending_rounds] == [
            (3, False),
            (4, False),
        ]
        assert h.anchor_block is None
        assert [b.index() for b in committed] == [0, 1]

    def test_settled_rounds_never_reminted(self):
        """Round-5 safety regression: a PendingRound at or below
        last_consensus_round must be dropped, never re-processed — even
        when the queue is out of round order. The live failure mode: a
        fast-synced joiner's section replay re-queues scrubbed rounds in
        section TOPOLOGICAL order; processing round N+1 first advances
        last_consensus_round past the settled anchor round N, after which
        the reference-shaped equality skip (`index == last_consensus_round`)
        no longer recognizes it and round N's frame is re-minted as a
        duplicate block at the next free index — shifting the joiner's
        whole chain one block against the cluster (observed in-suite:
        byte-divergent block 13, RR 12 duplicating block 11)."""
        from babble_tpu.hashgraph import PendingRound

        h = self.h
        committed = []
        h.commit_callback = committed.append
        h.run_consensus()
        assert [b.index() for b in committed] == [0, 1]
        last_block = h.store.last_block_index()
        lcr = h.last_consensus_round
        assert lcr == 2

        # stale re-queues of settled rounds, deliberately out of order
        # (the later round first, as section topological order produces)
        h.pending_rounds = [PendingRound(lcr, True), PendingRound(lcr - 1, True)]
        h.process_decided_rounds()

        assert h.store.last_block_index() == last_block, (
            "settled round was re-minted as a duplicate block"
        )
        assert [b.index() for b in committed] == [0, 1]
        assert h.pending_rounds == []
        assert h.last_consensus_round == lcr

    def test_known(self):
        h = self.h
        participants = h.participants.to_peer_slice()
        expected = {
            participants[0].id: 10,
            participants[1].id: 9,
            participants[2].id: 9,
        }
        assert h.store.known_events() == expected

    def test_full_pipeline_deterministic_order(self):
        """Two runs over the same DAG produce identical block bodies."""
        h1, index1, ordered = init_consensus_hashgraph()
        blocks1, blocks2 = [], []
        h1.commit_callback = blocks1.append
        h1.run_consensus()

        # replay the same signed events into a fresh hashgraph
        from dsl import create_hashgraph

        h2 = Hashgraph(h1.participants, InmemStore(h1.participants, CACHE_SIZE))
        h2.commit_callback = blocks2.append
        import json

        for ev in ordered:
            from babble_tpu.hashgraph import Event

            h2.insert_event(Event.from_json(json.loads(json.dumps(ev.to_json()))), True)
        h2.run_consensus()

        assert len(blocks1) == len(blocks2) > 0
        for b1, b2 in zip(blocks1, blocks2):
            assert b1.body.marshal() == b2.body.marshal()


# ---------------------------------------------------------------------------
# persistence: same pipeline on the SQLite store
# ---------------------------------------------------------------------------


class TestSQLiteStorePipeline:
    def test_consensus_on_sqlite(self, tmp_path):
        h, index, _ = init_consensus_hashgraph(sqlite_factory(tmp_path))
        h.run_consensus()
        assert h.store.get_block(0).transactions() == [b"e21"]
        assert len(h.store.consensus_events()) == 16

    def test_bootstrap_replays_to_same_state(self, tmp_path):
        h, index, _ = init_consensus_hashgraph(sqlite_factory(tmp_path))
        h.run_consensus()
        block0 = h.store.get_block(0)
        block1 = h.store.get_block(1)
        participants = h.participants
        h.store.close()

        store2 = SQLiteStore(
            participants, CACHE_SIZE, str(tmp_path / "store.db"), existing_db=True
        )
        h2 = Hashgraph(participants, store2)
        assert store2.need_bootstrap()
        h2.bootstrap()
        assert h2.store.get_block(0).body.marshal() == block0.body.marshal()
        assert h2.store.get_block(1).body.marshal() == block1.body.marshal()
        assert h2.store.last_block_index() == h.store.last_block_index()
