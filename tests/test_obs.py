"""Observability-layer tests (babble_tpu/obs/, docs/observability.md):
bucket math, Prometheus exposition format, bounded label cardinality,
registry get-or-create semantics, span-ring truncation, Chrome trace
export shape, and the headline determinism property — two same-seed
simulator runs produce byte-identical commit-latency histograms.
"""

import json

import pytest

from babble_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS,
    MAX_LABEL_SETS,
    Observability,
    SpanTracer,
    log_buckets,
)
from babble_tpu.obs.metrics import MetricsRegistry
from babble_tpu.sim import SimClock, run_one


# ----------------------------------------------------------------------
# bucket math
# ----------------------------------------------------------------------

def test_log_buckets_geometric():
    assert log_buckets(1, 2.0, 4) == (1.0, 2.0, 4.0, 8.0)
    bs = log_buckets(0.001, 2.0, 17)
    assert bs == DEFAULT_LATENCY_BUCKETS
    assert bs[0] == 0.001 and bs[-1] == pytest.approx(65.536)
    with pytest.raises(ValueError):
        log_buckets(0, 2.0, 4)
    with pytest.raises(ValueError):
        log_buckets(1, 1.0, 4)


def test_histogram_bucket_placement_and_render():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "x", buckets=(0.1, 1.0, 10.0))
    # boundary values land in the bucket whose bound they equal (le is
    # inclusive, as in Prometheus)
    for v in (0.05, 0.1, 0.5, 1.0, 10.0, 99.0):
        h.observe(v)
    assert h.stats() == (6, pytest.approx(110.65))
    text = reg.expose()
    assert '# TYPE h_seconds histogram' in text
    assert 'h_seconds_bucket{le="0.1"} 2' in text  # cumulative
    assert 'h_seconds_bucket{le="1"} 4' in text
    assert 'h_seconds_bucket{le="10"} 5' in text
    assert 'h_seconds_bucket{le="+Inf"} 6' in text
    assert 'h_seconds_count 6' in text
    assert text.endswith("\n")


def test_histogram_rejects_unsorted_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad_h", "x", buckets=(1.0, 0.5))


# ----------------------------------------------------------------------
# exposition format + labels
# ----------------------------------------------------------------------

def test_counter_gauge_exposition():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "counted things", labels=("result",))
    c.labels(result="ok").inc()
    c.labels(result="ok").inc(2)
    c.labels(result="error").inc()
    g = reg.gauge("g_now", "a level")
    g.set(2.5)
    text = reg.expose()
    assert "# HELP c_total counted things" in text
    assert "# TYPE c_total counter" in text
    assert 'c_total{result="error"} 1' in text
    assert 'c_total{result="ok"} 3' in text
    assert "# TYPE g_now gauge" in text
    assert "g_now 2.5" in text
    # integral floats render without the dot
    g.set(4.0)
    assert "g_now 4\n" in reg.expose()
    with pytest.raises(ValueError):
        c.labels(result="ok").inc(-1)  # counters only go up
    with pytest.raises(ValueError):
        c.inc()  # unlabeled use of a labeled metric


def test_gauge_set_function_is_read_at_render():
    reg = MetricsRegistry()
    box = {"v": 1.0}
    reg.gauge("live_g", "x").set_function(lambda: box["v"])
    assert "live_g 1" in reg.expose()
    box["v"] = 7.0
    assert "live_g 7" in reg.expose()
    # a broken callback degrades to 0, never breaks the scrape
    reg.gauge("live_g", "x").set_function(lambda: 1 / 0)
    assert "live_g 0" in reg.expose()


def test_label_overflow_collapses_to_other():
    reg = MetricsRegistry()
    c = reg.counter("many_total", "x", labels=("peer",))
    for i in range(MAX_LABEL_SETS + 10):
        c.labels(peer=f"p{i}").inc()
    assert c.value(peer="p0") == 1.0
    assert c.value(peer="other") == 10.0  # overflow series absorbs the rest
    text = reg.expose()
    assert text.count("many_total{") == MAX_LABEL_SETS + 1


def test_registry_get_or_create_and_mismatch():
    reg = MetricsRegistry()
    c1 = reg.counter("same_total", "x")
    assert reg.counter("same_total") is c1
    assert reg.get("same_total") is c1
    assert reg.get("nope") is None
    with pytest.raises(ValueError):
        reg.gauge("same_total")  # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("same_total", labels=("a",))  # label-set mismatch
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("ok_total", labels=("bad-label",))


def test_snapshot_flat_shapes():
    reg = MetricsRegistry()
    reg.counter("c_total", "x", labels=("k",)).labels(k="a").inc(3)
    reg.histogram("h_s", "x", buckets=(1.0,)).observe(0.5)
    flat = reg.snapshot_flat()
    assert flat["c_total{a}"] == 3
    assert flat["h_s_count"] == 1
    assert flat["h_s_sum"] == 0.5


# ----------------------------------------------------------------------
# span tracer
# ----------------------------------------------------------------------

def test_span_ring_truncates_oldest():
    tracer = SpanTracer(capacity=8)
    for i in range(20):
        tracer.record(f"s{i}", float(i), 0.5)
    spans = tracer.spans()
    assert [s.name for s in spans] == [f"s{i}" for i in range(12, 20)]
    assert tracer.dropped == 12


def test_span_context_manager_times_through_clock():
    clock = SimClock()
    obs = Observability(clock=clock)
    h = obs.histogram("span_h_seconds", "x")
    with obs.span("work", histogram=h, phase="p1"):
        clock.now += 0.25
    [sp] = obs.tracer.spans()
    assert sp.name == "work"
    assert sp.duration == 0.25
    assert sp.attrs == {"phase": "p1"}
    assert h.stats() == (1, 0.25)


def test_chrome_trace_export_shape():
    tracer = SpanTracer(capacity=8)
    tracer.record("a", 1.0, 0.5, {"k": "v"})
    tracer.record("b", 2.0, 0.25)
    doc = tracer.to_chrome_trace(pid=3)
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert len(meta) == 1 and meta[0]["name"] == "thread_name"
    assert [e["name"] for e in spans] == ["a", "b"]
    assert spans[0]["ts"] == 1e6 and spans[0]["dur"] == 5e5  # microseconds
    assert spans[0]["args"] == {"k": "v"}
    assert all(e["pid"] == 3 for e in evs)
    json.dumps(doc)  # must be directly serializable


# ----------------------------------------------------------------------
# headline determinism: same-seed sim runs give byte-identical
# commit-latency histograms (ISSUE 4 acceptance)
# ----------------------------------------------------------------------

def test_sim_commit_latency_histogram_deterministic():
    a = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    b = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    assert a["ok"] and b["ok"]
    # the histograms actually measured something: every live node saw
    # commits for transactions it submitted itself
    counts = [
        series["count"]
        for snap in a["commit_latency"].values()
        for series in snap["series"].values()
    ]
    assert counts and all(c > 0 for c in counts)
    # and the whole snapshot — counts, sums, bucket assignment — is
    # byte-identical across the two runs
    assert (
        json.dumps(a["commit_latency"], sort_keys=True)
        == json.dumps(b["commit_latency"], sort_keys=True)
    )
