"""Observability-layer tests (babble_tpu/obs/, docs/observability.md):
bucket math, Prometheus exposition format, bounded label cardinality,
registry get-or-create semantics, span-ring truncation, Chrome trace
export shape, and the headline determinism property — two same-seed
simulator runs produce byte-identical commit-latency histograms.
"""

import json

import pytest

from babble_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS,
    MAX_LABEL_SETS,
    Observability,
    SpanTracer,
    log_buckets,
)
from babble_tpu.obs.metrics import MetricsRegistry
from babble_tpu.sim import SimClock, run_one


# ----------------------------------------------------------------------
# bucket math
# ----------------------------------------------------------------------

def test_log_buckets_geometric():
    assert log_buckets(1, 2.0, 4) == (1.0, 2.0, 4.0, 8.0)
    bs = log_buckets(0.001, 2.0, 17)
    assert bs == DEFAULT_LATENCY_BUCKETS
    assert bs[0] == 0.001 and bs[-1] == pytest.approx(65.536)
    with pytest.raises(ValueError):
        log_buckets(0, 2.0, 4)
    with pytest.raises(ValueError):
        log_buckets(1, 1.0, 4)


def test_histogram_bucket_placement_and_render():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "x", buckets=(0.1, 1.0, 10.0))
    # boundary values land in the bucket whose bound they equal (le is
    # inclusive, as in Prometheus)
    for v in (0.05, 0.1, 0.5, 1.0, 10.0, 99.0):
        h.observe(v)
    assert h.stats() == (6, pytest.approx(110.65))
    text = reg.expose()
    assert '# TYPE h_seconds histogram' in text
    assert 'h_seconds_bucket{le="0.1"} 2' in text  # cumulative
    assert 'h_seconds_bucket{le="1"} 4' in text
    assert 'h_seconds_bucket{le="10"} 5' in text
    assert 'h_seconds_bucket{le="+Inf"} 6' in text
    assert 'h_seconds_count 6' in text
    assert text.endswith("\n")


def test_histogram_rejects_unsorted_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad_h", "x", buckets=(1.0, 0.5))


# ----------------------------------------------------------------------
# exposition format + labels
# ----------------------------------------------------------------------

def test_counter_gauge_exposition():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "counted things", labels=("result",))
    c.labels(result="ok").inc()
    c.labels(result="ok").inc(2)
    c.labels(result="error").inc()
    g = reg.gauge("g_now", "a level")
    g.set(2.5)
    text = reg.expose()
    assert "# HELP c_total counted things" in text
    assert "# TYPE c_total counter" in text
    assert 'c_total{result="error"} 1' in text
    assert 'c_total{result="ok"} 3' in text
    assert "# TYPE g_now gauge" in text
    assert "g_now 2.5" in text
    # integral floats render without the dot
    g.set(4.0)
    assert "g_now 4\n" in reg.expose()
    with pytest.raises(ValueError):
        c.labels(result="ok").inc(-1)  # counters only go up
    with pytest.raises(ValueError):
        c.inc()  # unlabeled use of a labeled metric


def test_gauge_set_function_is_read_at_render():
    reg = MetricsRegistry()
    box = {"v": 1.0}
    reg.gauge("live_g", "x").set_function(lambda: box["v"])
    assert "live_g 1" in reg.expose()
    box["v"] = 7.0
    assert "live_g 7" in reg.expose()
    # a broken callback degrades to 0, never breaks the scrape
    reg.gauge("live_g", "x").set_function(lambda: 1 / 0)
    assert "live_g 0" in reg.expose()


def test_label_overflow_collapses_to_other():
    reg = MetricsRegistry()
    c = reg.counter("many_total", "x", labels=("peer",))
    for i in range(MAX_LABEL_SETS + 10):
        c.labels(peer=f"p{i}").inc()
    assert c.value(peer="p0") == 1.0
    assert c.value(peer="other") == 10.0  # overflow series absorbs the rest
    text = reg.expose()
    assert text.count("many_total{") == MAX_LABEL_SETS + 1


def test_registry_get_or_create_and_mismatch():
    reg = MetricsRegistry()
    c1 = reg.counter("same_total", "x")
    assert reg.counter("same_total") is c1
    assert reg.get("same_total") is c1
    assert reg.get("nope") is None
    with pytest.raises(ValueError):
        reg.gauge("same_total")  # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("same_total", labels=("a",))  # label-set mismatch
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("ok_total", labels=("bad-label",))


def test_snapshot_flat_shapes():
    reg = MetricsRegistry()
    reg.counter("c_total", "x", labels=("k",)).labels(k="a").inc(3)
    reg.histogram("h_s", "x", buckets=(1.0,)).observe(0.5)
    flat = reg.snapshot_flat()
    assert flat["c_total{a}"] == 3
    assert flat["h_s_count"] == 1
    assert flat["h_s_sum"] == 0.5


# ----------------------------------------------------------------------
# span tracer
# ----------------------------------------------------------------------

def test_span_ring_truncates_oldest():
    tracer = SpanTracer(capacity=8)
    for i in range(20):
        tracer.record(f"s{i}", float(i), 0.5)
    spans = tracer.spans()
    assert [s.name for s in spans] == [f"s{i}" for i in range(12, 20)]
    assert tracer.dropped == 12


def test_span_context_manager_times_through_clock():
    clock = SimClock()
    obs = Observability(clock=clock)
    h = obs.histogram("span_h_seconds", "x")
    with obs.span("work", histogram=h, phase="p1"):
        clock.now += 0.25
    [sp] = obs.tracer.spans()
    assert sp.name == "work"
    assert sp.duration == 0.25
    assert sp.attrs == {"phase": "p1"}
    assert h.stats() == (1, 0.25)


def test_chrome_trace_export_shape():
    tracer = SpanTracer(capacity=8)
    tracer.record("a", 1.0, 0.5, {"k": "v"})
    tracer.record("b", 2.0, 0.25)
    doc = tracer.to_chrome_trace(pid=3)
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert len(meta) == 1 and meta[0]["name"] == "thread_name"
    assert [e["name"] for e in spans] == ["a", "b"]
    assert spans[0]["ts"] == 1e6 and spans[0]["dur"] == 5e5  # microseconds
    assert spans[0]["args"] == {"k": "v"}
    assert all(e["pid"] == 3 for e in evs)
    json.dumps(doc)  # must be directly serializable


# ----------------------------------------------------------------------
# headline determinism: same-seed sim runs give byte-identical
# commit-latency histograms (ISSUE 4 acceptance)
# ----------------------------------------------------------------------

def test_sim_commit_latency_histogram_deterministic():
    a = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    b = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    assert a["ok"] and b["ok"]
    # the histograms actually measured something: every live node saw
    # commits for transactions it submitted itself
    counts = [
        series["count"]
        for snap in a["commit_latency"].values()
        for series in snap["series"].values()
    ]
    assert counts and all(c > 0 for c in counts)
    # and the whole snapshot — counts, sums, bucket assignment — is
    # byte-identical across the two runs
    assert (
        json.dumps(a["commit_latency"], sort_keys=True)
        == json.dumps(b["commit_latency"], sort_keys=True)
    )


# ----------------------------------------------------------------------
# cross-node causal tracing (ISSUE 5): TraceStore lifecycle, bounded
# memory, wire absorption, filtered export, cluster assembly, watchdog
# ----------------------------------------------------------------------

import logging
import urllib.request

from babble_tpu.obs import (
    TraceStore,
    assemble_cluster_trace,
    span_id_for,
    trace_id_for,
)
from babble_tpu.node.watchdog import LivenessWatchdog
from babble_tpu.service import Service


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


class _Ev:
    """Minimal stand-in for a hashgraph event: just its payload."""

    def __init__(self, *txs):
        self._txs = list(txs)

    def transactions(self):
        return self._txs


def _stage_count(obs, name):
    snap = obs.registry.snapshot()
    return snap[name]["series"][""]["count"]


def test_trace_store_stage_flow_and_completion():
    clock = SimClock()
    obs = Observability(clock=clock, node_id=7)
    st = obs.traces
    tx = b"tx-bytes"
    tid = trace_id_for(tx)

    st.begin(tx)
    st.begin(tx)  # idempotent re-submit
    assert len(st) == 1
    ctx = st.get(tid)
    assert ctx.span_id == span_id_for(tid, 7)
    assert ctx.parent == "" and ctx.origin == 7

    clock.advance_to(1.0)
    st.mark_event([tx])
    st.mark_event([tx])  # idempotent per stage
    assert _stage_count(obs, "babble_trace_stage_submit_to_event_seconds") == 1
    clock.advance_to(1.5)
    st.mark_round([tx])
    clock.advance_to(2.0)
    st.mark_famous([tx])
    clock.advance_to(3.0)
    st.mark_commit([tx])
    # commit completes and removes the context — not a drop
    assert len(st) == 0 and st.get(tid) is None
    snap = obs.registry.snapshot()
    assert snap["obs_traces_dropped_total"]["series"].get("", 0.0) == 0.0
    assert snap["babble_trace_stage_famous_to_commit_seconds"]["series"][""]["sum"] == pytest.approx(1.0)
    # post-commit relays carry nothing (clean truncation downstream)
    assert st.contexts_for([_Ev(tx)]) == []
    # every stage span is tagged with the trace and chains to the base span
    spans = [s for s in obs.tracer.spans() if s.attrs and s.attrs.get("trace") == tid]
    assert [s.name for s in spans] == [
        "trace.submit", "trace.event", "trace.round",
        "trace.famous", "trace.commit",
    ]
    assert all(s.attrs["parent"] == ctx.span_id for s in spans if ":" in s.attrs["span"])


def test_trace_store_absorb_and_piggyback():
    clock = SimClock()
    sender = Observability(clock=clock, node_id=0)
    receiver = Observability(clock=clock, node_id=1)
    tx = b"cross-node"
    tid = trace_id_for(tx)
    sender.traces.begin(tx)

    wire = sender.traces.contexts_for([_Ev(tx, b"untraced-tx")])
    assert wire == [{"Id": tid, "Origin": 0, "Span": span_id_for(tid, 0)}]

    clock.advance_to(0.5)
    receiver.traces.absorb(wire)
    receiver.traces.absorb(wire)  # duplicate delivery is harmless
    ctx = receiver.traces.get(tid)
    assert ctx.parent == span_id_for(tid, 0)  # the cross-node causal edge
    assert ctx.span_id == span_id_for(tid, 1)
    assert ctx.marks == {"receive": 0.5}
    # malformed piggyback entries are ignored, not fatal
    receiver.traces.absorb([{"bogus": 1}, "junk", {"Id": ""}])
    assert len(receiver.traces) == 1


def test_trace_store_lru_bound_and_disabled_mode():
    clock = SimClock()
    obs = Observability(clock=clock, node_id=0, trace_capacity=2)
    st = obs.traces
    for i in range(4):
        st.begin(b"tx%d" % i)
    assert len(st) == 2
    snap = obs.registry.snapshot()
    assert snap["obs_traces_dropped_total"]["series"][""] == 2.0
    assert snap["obs_traces_live"]["series"][""] == 2.0
    # eviction is LRU: the two newest survive
    assert st.get(trace_id_for(b"tx3")) is not None
    assert st.get(trace_id_for(b"tx0")) is None

    off = Observability(clock=clock, node_id=0, tracing=False)
    off.traces.begin(b"tx")
    off.traces.absorb([{"Id": "ab", "Origin": 0, "Span": "cd"}])
    assert len(off.traces) == 0
    assert off.traces.contexts_for([_Ev(b"tx")]) == []


def test_chrome_trace_trace_id_filter():
    tracer = SpanTracer(capacity=8)
    tracer.record("trace.event", 1.0, 0.5, {"trace": "t1", "span": "a"})
    tracer.record("trace.event", 2.0, 0.5, {"trace": "t2", "span": "b"})
    tracer.record("gossip", 3.0, 0.5)
    doc = tracer.to_chrome_trace(pid=0, trace_id="t1")
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["args"]["trace"] for e in spans] == ["t1"]


def test_assemble_cluster_trace_reroots_unresolved_parents():
    doc_a = {"traceEvents": [
        {"ph": "X", "name": "trace.submit", "pid": 0, "ts": 0, "dur": 0,
         "args": {"trace": "t", "span": "s0", "parent": ""}},
    ]}
    doc_b = {"traceEvents": [
        {"ph": "X", "name": "trace.receive", "pid": 9, "ts": 1, "dur": 0,
         "args": {"trace": "t", "span": "s1", "parent": "s0"}},
        {"ph": "X", "name": "trace.receive", "pid": 9, "ts": 2, "dur": 0,
         "args": {"trace": "t", "span": "s2", "parent": "gone"}},
    ]}
    merged = assemble_cluster_trace([(0, doc_a), (3, doc_b)])
    evs = merged["traceEvents"]
    assert [e["pid"] for e in evs] == [0, 3, 3]  # sim path re-stamps pids
    by_span = {e["args"]["span"]: e["args"] for e in evs}
    assert by_span["s1"]["parent"] == "s0"  # resolvable edge kept
    assert by_span["s2"]["parent"] == "" and by_span["s2"]["truncated"]
    # the source documents were not mutated
    assert doc_b["traceEvents"][1]["args"]["parent"] == "gone"
    # None keeps the exporter's pid (the HTTP federation path)
    kept = assemble_cluster_trace([(None, doc_b)])
    assert [e["pid"] for e in kept["traceEvents"]] == [9, 9]


def test_watchdog_peer_labels_ride_registry_overflow():
    clock = SimClock()
    obs = Observability(clock=clock, node_id=0)
    wd = LivenessWatchdog(
        clock=clock, obs=obs, logger=logging.getLogger("test.wd"),
        deadline=5.0, round_fn=lambda: 1, pending_fn=lambda: 0,
    )
    for i in range(MAX_LABEL_SETS + 10):
        wd.note_sync(f"10.0.0.{i}:1337", ok=True)
    wd.check()
    snap = obs.registry.snapshot()
    for name in ("babble_peer_health", "babble_peer_sync_staleness_seconds"):
        series = snap[name]["series"]
        # novel peers past the cap collapse into the "other" series
        assert len(series) == MAX_LABEL_SETS + 1
        assert "other" in series
    assert snap["babble_peer_health"]["series"]["10.0.0.0:1337"] == 1.0


class _FakeNode:
    def __init__(self, node_id, obs):
        self.id = node_id
        self.obs = obs

    def get_stats(self):
        return {"id": str(self.id)}


def test_service_trace_filter_and_cluster_federation():
    tid = "ab" * 8
    obs0 = Observability(node_id=0)
    obs1 = Observability(node_id=1)
    s0 = span_id_for(tid, 0)
    s1 = span_id_for(tid, 1)
    obs0.tracer.record("trace.submit", 0.0, 0.0,
                       {"trace": tid, "span": s0, "parent": "", "node": 0})
    obs0.tracer.record("gossip", 0.0, 1.0)  # untraced noise
    obs1.tracer.record("trace.receive", 1.0, 0.0,
                       {"trace": tid, "span": s1, "parent": s0, "node": 1})
    obs1.tracer.record("trace.event", 1.0, 0.5,
                       {"trace": "ffff", "span": "x", "parent": ""})

    svc0 = Service("127.0.0.1:0", _FakeNode(0, obs0))
    svc1 = Service("127.0.0.1:0", _FakeNode(1, obs1))
    try:
        svc0.serve()
        svc1.serve()
        base = f"http://{svc0.local_addr()}"

        doc = _get(f"{base}/debug/trace?trace_id={tid}")
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert [e["name"] for e in spans] == ["trace.submit"]

        url = (f"{base}/debug/trace/cluster?trace_id={tid}"
               f"&peers={svc1.local_addr()},127.0.0.1:1")
        merged = _get(url)
        spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        assert sorted(e["name"] for e in spans) == [
            "trace.receive", "trace.submit",
        ]
        assert {e["pid"] for e in spans} == {0, 1}
        # the cross-node parent edge survived federation
        recv = next(e for e in spans if e["name"] == "trace.receive")
        assert recv["args"]["parent"] == s0
        assert merged["failed_peers"] == ["127.0.0.1:1"]
        assert merged["trace_id"] == tid
    finally:
        svc0.shutdown()
        svc1.shutdown()
