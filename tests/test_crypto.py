"""Crypto tests (reference: src/crypto/crypto_test.go)."""

from babble_tpu import crypto


def test_sign_verify_roundtrip():
    key = crypto.generate_key()
    digest = crypto.sha256(b"hello world")
    r, s = crypto.sign(key, digest)
    assert crypto.verify(key.public_key(), digest, r, s)
    assert not crypto.verify(key.public_key(), crypto.sha256(b"other"), r, s)


def test_sign_deterministic():
    """RFC 6979: same key + same digest => same signature bytes. The
    signature's r value is the Lamport tie-breaker in consensus ordering,
    so a validator re-signing an identical event body (crash replay,
    backend differential) must reproduce the same bytes — two separately
    constructed key objects over the same PEM material included."""
    key = crypto.generate_key()
    digest = crypto.sha256(b"determinism")
    assert crypto.sign(key, digest) == crypto.sign(key, digest)
    clone = crypto.key_from_pem(crypto.key_to_pem(key).encode())
    assert crypto.sign(clone, digest) == crypto.sign(key, digest)


def test_signature_encoding_roundtrip():
    key = crypto.generate_key()
    digest = crypto.sha256(b"payload")
    r, s = crypto.sign(key, digest)
    sig = crypto.encode_signature(r, s)
    assert "|" in sig
    r2, s2 = crypto.decode_signature(sig)
    assert (r, s) == (r2, s2)


def test_pub_key_roundtrip():
    key = crypto.generate_key()
    raw = crypto.pub_key_bytes(key)
    assert len(raw) == 65 and raw[0] == 0x04  # uncompressed point
    pub = crypto.pub_key_from_bytes(raw)
    assert crypto.pub_key_bytes(pub) == raw


def test_pem_roundtrip(tmp_path):
    key = crypto.generate_key()
    pk = crypto.PemKey(str(tmp_path))
    pk.write_key(key)
    key2 = pk.read_key()
    assert crypto.pub_key_bytes(key) == crypto.pub_key_bytes(key2)
    # a signature from the reloaded key verifies against the original pub
    digest = crypto.sha256(b"x")
    r, s = crypto.sign(key2, digest)
    assert crypto.verify(key.public_key(), digest, r, s)


def test_simple_hash_from_hashes():
    h1 = crypto.sha256(b"a")
    h2 = crypto.sha256(b"b")
    h3 = crypto.sha256(b"c")
    assert crypto.simple_hash_from_hashes([h1]) == h1
    combined = crypto.simple_hash_from_hashes([h1, h2, h3])
    # deterministic and sensitive to order
    assert combined == crypto.simple_hash_from_hashes([h1, h2, h3])
    assert combined != crypto.simple_hash_from_hashes([h2, h1, h3])
