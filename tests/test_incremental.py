"""Differential tests for the persistent incremental device pipeline:
appending gossip-sized batches to device-resident state must reproduce the
one-shot pipeline bit-exactly — rounds, lamports, witness flags and
round-received — including when batches are applied through the fused
multi-batch dispatch (scan + one decide pass)."""

import numpy as np
import pytest

from babble_tpu.tpu import synthetic_grid
from babble_tpu.tpu.engine import run_passes
from babble_tpu.tpu.incremental import (
    batches_from_grid,
    init_state,
    multi_step,
    multi_train,
    stack_batches,
    stack_trains,
    step,
    train_step,
    trains_from_grid,
)


@pytest.mark.parametrize("zipf", [0.0, 1.1])
def test_incremental_matches_one_shot(zipf):
    n, e = 8, 768
    grid = synthetic_grid(n, e, seed=3, zipf_a=zipf, record_fd_updates=True)
    batches = batches_from_grid(grid, 32, 8192, e)

    st = init_state(n, e, 64)
    for b in batches:
        st = step(st, b, grid.super_majority, n, e_win=512)

    ref = run_passes(grid)
    assert not bool(st.stale)
    assert not bool(st.fame_lag)
    np.testing.assert_array_equal(np.asarray(st.rounds)[:e], ref.rounds)
    np.testing.assert_array_equal(np.asarray(st.lamport)[:e], ref.lamport)
    np.testing.assert_array_equal(np.asarray(st.witness)[:e], ref.witness)
    np.testing.assert_array_equal(np.asarray(st.received)[:e], ref.received)
    assert int(st.last_round) == ref.last_round


def test_multi_step_matches_per_batch():
    """The K-batches-per-dispatch path must equal the one-by-one path."""
    n, e = 8, 512
    grid = synthetic_grid(n, e, seed=5, zipf_a=1.1, record_fd_updates=True)
    batches = batches_from_grid(grid, 32, 8192, e)

    one = init_state(n, e, 64)
    for b in batches:
        one = step(one, b, grid.super_majority, n, e_win=512)

    k = 4
    many = init_state(n, e, 64)
    for i in range(0, len(batches), k):
        many = multi_step(
            many, stack_batches(batches[i : i + k]),
            grid.super_majority, n, e_win=512,
        )

    for f in ("rounds", "lamport", "witness", "received"):
        np.testing.assert_array_equal(
            np.asarray(getattr(one, f)), np.asarray(getattr(many, f)), f
        )
    assert not bool(many.stale) and not bool(many.fame_lag)


@pytest.mark.parametrize("zipf", [0.0, 1.1])
def test_train_matches_per_batch(zipf):
    """The flattened-train program (MXU one-hot gathers, bulk post-scan
    registration) must reproduce the per-batch path bit-exactly across
    every decision array."""
    n, e = 8, 768
    grid = synthetic_grid(n, e, seed=3, zipf_a=zipf, record_fd_updates=True)

    ref = init_state(n, e, 64)
    for b in batches_from_grid(grid, 32, 8192, e):
        ref = step(ref, b, grid.super_majority, n, e_win=512)

    tr = init_state(n, e, 64)
    for t in trains_from_grid(grid, 256, 8192, e, w_cap=16, t_cap=96):
        tr = train_step(tr, t, grid.super_majority, n, e_win=512)

    assert not bool(tr.stale) and not bool(tr.fame_lag)
    for f in ("rounds", "lamport", "witness", "received", "wtable",
              "fame_decided", "famous", "rounds_decided"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(tr, f)), f
        )
    assert int(tr.last_round) == int(ref.last_round)


def test_multi_train_matches_train():
    """K stacked trains per dispatch must equal per-train dispatch."""
    n, e = 8, 512
    grid = synthetic_grid(n, e, seed=5, zipf_a=1.1, record_fd_updates=True)
    trains = trains_from_grid(grid, 128, 8192, e, w_cap=16, t_cap=64)

    one = init_state(n, e, 64)
    for t in trains:
        one = train_step(one, t, grid.super_majority, n, e_win=512)

    k = 2
    many = init_state(n, e, 64)
    for i in range(0, len(trains), k):
        group = trains[i : i + k]
        if len(group) < k:
            for t in group:
                many = train_step(many, t, grid.super_majority, n, e_win=512)
        else:
            many = multi_train(
                many, stack_trains(group), grid.super_majority, n, e_win=512
            )

    for f in ("rounds", "lamport", "witness", "received"):
        np.testing.assert_array_equal(
            np.asarray(getattr(one, f)), np.asarray(getattr(many, f)), f
        )
    assert not bool(many.stale) and not bool(many.fame_lag)


def test_stale_latch_fires_on_undersized_window():
    """An undetermined row sliding below the received window must latch
    the stale flag instead of silently never deciding."""
    n, e = 8, 512
    grid = synthetic_grid(n, e, seed=7, zipf_a=1.1, record_fd_updates=True)
    batches = batches_from_grid(grid, 32, 8192, e)
    st = init_state(n, e, 64)
    for b in batches:
        st = step(st, b, grid.super_majority, n, e_win=64)  # far too small
    assert bool(st.stale)


# -- frontier-live engine (incremental INV + frontier walk) ------------------


def frontier_replay(grid, train_size, e_cap=4096, l_cap=256, r_cap=64):
    from babble_tpu.tpu.frontier_live import (
        frontier_train_step, init_frontier_state,
    )

    trains = trains_from_grid(grid, train_size, 16384, e_cap)
    state = init_frontier_state(grid.n, e_cap, l_cap, r_cap)
    for t in trains:
        state = frontier_train_step(state, t, grid.super_majority, grid.n)
    assert not bool(state.l_over) and not bool(state.r_over)
    assert not bool(state.frozen_violation)
    return state


@pytest.mark.parametrize("zipf", [0.0, 1.1])
def test_frontier_live_matches_one_shot(zipf):
    """The frontier-live engine's final state after train-sized appends
    must equal the one-shot pipeline on the same DAG — the claim that
    incrementally-maintained INV/chain tables reproduce build_inv."""
    grid = synthetic_grid(16, 2048, seed=3, zipf_a=zipf, record_fd_updates=True)
    state = frontier_replay(grid, 256)
    ref = run_passes(grid, adaptive_r=True)
    e = grid.e
    np.testing.assert_array_equal(np.asarray(state.rounds)[:e], ref.rounds)
    np.testing.assert_array_equal(np.asarray(state.witness)[:e], ref.witness)
    np.testing.assert_array_equal(np.asarray(state.lamport)[:e], ref.lamport)
    np.testing.assert_array_equal(np.asarray(state.received)[:e], ref.received)
    assert int(state.last_round) == ref.last_round


def test_frontier_live_small_trains_match_large():
    """Train-size independence: appending 32 events at a time must land in
    exactly the same state as 512 at a time (INV closure and frontier
    decisions are pure functions of the accumulated tables)."""
    grid = synthetic_grid(8, 1024, seed=9, zipf_a=1.1, record_fd_updates=True)
    a = frontier_replay(grid, 32)
    b = frontier_replay(grid, 512)
    for field in ("rounds", "witness", "received", "wtable",
                  "fame_decided", "famous"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        )


def test_frontier_multi_train_matches_per_train():
    from babble_tpu.tpu.frontier_live import (
        frontier_multi_train, frontier_train_step, init_frontier_state,
    )
    from babble_tpu.tpu.incremental import stack_trains

    grid = synthetic_grid(8, 1024, seed=5, zipf_a=1.1, record_fd_updates=True)
    e_cap, l_cap, r_cap = 2048, 256, 64
    trains = trains_from_grid(grid, 128, 16384, e_cap)

    a = init_frontier_state(grid.n, e_cap, l_cap, r_cap)
    for t in trains:
        a = frontier_train_step(a, t, grid.super_majority, grid.n)

    b = init_frontier_state(grid.n, e_cap, l_cap, r_cap)
    b = frontier_multi_train(
        b, stack_trains(trains), grid.super_majority, grid.n
    )
    for field in ("rounds", "witness", "received", "last_round"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        )


def test_frontier_live_l_over_latch():
    """A chain outgrowing the index axis must latch l_over, not corrupt."""
    from babble_tpu.tpu.frontier_live import (
        frontier_train_step, init_frontier_state,
    )

    grid = synthetic_grid(8, 512, seed=2, zipf_a=2.0, record_fd_updates=True)
    l_cap = 16  # far below the hottest chain's length
    trains = trains_from_grid(grid, 128, 16384, 1024)
    state = init_frontier_state(grid.n, 1024, l_cap, 64)
    for t in trains:
        state = frontier_train_step(state, t, grid.super_majority, grid.n)
    assert bool(state.l_over)
