"""The embedding surface (reference: src/mobile/node.go contract): build
`Babble` engines from `BabbleConfig`, run them, submit transactions through
the engine object, observe commits through a registered handler, shut down.
Uses real TCP transports and datadir-based keys/peers — the full
composition-root path, in-process."""

import json
import os
import threading

from babble_tpu import Babble, BabbleConfig, keygen
from babble_tpu.crypto import pub_key_bytes
from babble_tpu.node import Config as NodeConfig
from babble_tpu.proxy import InmemDummyClient


def test_embedding_cluster(tmp_path):
    n = 3
    datadirs = [os.path.join(tmp_path, f"node{i}") for i in range(n)]
    keys = [keygen(d) for d in datadirs]

    # bind ephemeral listeners first so peers.json can carry real ports
    from babble_tpu.net import TCPTransport

    transports = [TCPTransport("127.0.0.1:0", timeout=1.0) for _ in range(n)]
    peers_json = [
        {
            "NetAddr": t.local_addr(),
            "PubKeyHex": "0x" + pub_key_bytes(k).hex().upper(),
        }
        for t, k in zip(transports, keys)
    ]
    for d in datadirs:
        with open(os.path.join(d, "peers.json"), "w") as f:
            json.dump(peers_json, f)

    engines = []
    committed = [[] for _ in range(n)]
    done = [threading.Event() for _ in range(n)]
    try:
        for i in range(n):
            config = BabbleConfig(
                data_dir=datadirs[i],
                proxy=InmemDummyClient(),
                node=NodeConfig(
                    heartbeat_timeout=0.01, tcp_timeout=1.0,
                    cache_size=1000, sync_limit=300,
                ),
            )
            engine = Babble(config)
            engine.config.key = keys[i]
            # run the init sequence by hand so the pre-bound ephemeral-port
            # transport is used instead of a fresh bind
            engine._init_peers()
            engine._init_store()
            engine.trans = transports[i]
            engine._init_key()
            engine._init_node()
            engine._init_service()

            base = engine.config.proxy.handler.commit_handler
            def handler(block, _idx=i, _base=base):
                committed[_idx].append(block.index())
                if block.index() >= 2:
                    done[_idx].set()
                return _base(block)

            engine.on_commit(handler)
            engines.append(engine)

        for e in engines:
            e.run_async()

        # blocks form only while events flow: keep a tx trickle going until
        # every engine's commit handler has seen block 2
        import time

        deadline = time.monotonic() + 150
        k = 0
        while not all(ev.is_set() for ev in done) and time.monotonic() < deadline:
            engines[k % n].submit_tx(f"embedding tx {k}".encode())
            k += 1
            time.sleep(0.02)
        for i, d in enumerate(done):
            assert d.is_set(), f"engine {i} never reached block 2"
        # every engine committed the same block 2 byte-for-byte
        ref = engines[0].node.get_block(2).body.marshal()
        for e in engines[1:]:
            assert e.node.get_block(2).body.marshal() == ref
    finally:
        for e in engines:
            e.shutdown()
