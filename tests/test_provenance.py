"""Consensus decision provenance + first-divergence bisection (ISSUE 14).

Four layers under test:

- the `ProvenanceRecorder` itself: cell capture, bounded retention with
  clean truncation, fingerprints, the dossier;
- the `DivergenceBisector`: causal ordering, missing-round handling,
  deterministic localization, the CI smoke;
- the cross-engine comparability contract: CPU oracle hooks vs every
  device path must converge to byte-identical table streams, and the
  seeded defect fixture (fixtures_divergence.py) must localize to its
  exact injected cell with byte-identical repeat-run artifacts;
- the integration surfaces: sim determinism fingerprint, fault-plan
  stream completeness, watchdog stall provenance, the commit-latency
  exemplar, `/debug/explain`, and the `explain` CLI.
"""

import json
import logging
import os
import urllib.request

import pytest

from babble_tpu.obs import (
    DivergenceBisector,
    Observability,
    ProvenanceRecorder,
    bisect_pass_results,
    capture_pass_results,
    run_bisector_smoke,
)
from babble_tpu.sim import SimClock

from fixtures_divergence import broken_fame_passes

H = [("%02x" % i) * 8 for i in range(16)]  # distinct stable cell keys


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


def test_recorder_cells_idempotent_and_fingerprints():
    prov = ProvenanceRecorder(clock=SimClock(), node_id=0)
    assert prov.note_event(H[0], 0, 3, [(1, "x"), (2, "y")])  # host tuples
    assert not prov.note_event(H[0], 0, 3, [1, 2])  # grid ints, same value
    assert prov.note_witness(H[0], 0, 1)
    assert prov.note_fame(H[0], 0, True, engine="cpu", voter=H[1], yays=3)
    assert not prov.note_fame(H[0], 0, True)  # unchanged -> no append
    assert prov.note_received(H[0], 0)
    fp1 = prov.round_fingerprint(0)
    assert fp1 and prov.round_fingerprint(7) is None
    # the why is engine-specific and excluded from the table fingerprint
    other = ProvenanceRecorder(clock=SimClock(), node_id=1)
    other.note_event(H[0], 0, 3, [1, 2])
    other.note_witness(H[0], 0, 1)
    other.note_fame(H[0], 0, True, engine="mesh2d")
    other.note_received(H[0], 0)
    assert other.round_fingerprint(0) == fp1
    assert other.table_bytes() == prov.table_bytes()
    # ... but the full stream differs (whys differ)
    assert other.stream_bytes() != prov.stream_bytes()
    doc = prov.explain_round(0)
    assert doc["known"] and doc["fingerprint"] == fp1
    assert doc["why"][H[0]]["voter"] == H[1]
    assert prov.explain_round(7) == {
        "node": 0, "round": 7, "known": False, "evicted_below": 0,
    }


def test_recorder_eviction_is_cleanly_truncated():
    prov = ProvenanceRecorder(clock=SimClock(), round_cap=4)
    for r in range(10):
        prov.note_witness(H[r % len(H)], r, r % 4)
        prov.settle_round(r)
    assert prov.rounds() == [6, 7, 8, 9]
    assert prov.evicted_rounds == 6 and prov.evicted_below == 6
    truncs = [m for m in prov.to_json()["marks"]
              if m["name"] == "prov.truncate"]
    assert [m["fields"]["round"] for m in truncs] == list(range(6))
    assert prov.verify_complete_or_truncated() == []


def test_recorder_integrity_flags_orphan_fame():
    prov = ProvenanceRecorder(clock=SimClock())
    prov.note_fame(H[0], 2, True)  # no witness cell backs it
    issues = prov.verify_complete_or_truncated()
    assert len(issues) == 1 and "no witness cell" in issues[0]


# ---------------------------------------------------------------------------
# bisector
# ---------------------------------------------------------------------------


def _two(mutate=None):
    a = ProvenanceRecorder(clock=SimClock())
    b = ProvenanceRecorder(clock=SimClock())
    for prov in (a, b):
        for r in range(3):
            for c in range(3):
                h = H[r * 3 + c]
                prov.note_event(h, r, r * 3 + c, [1, 2, 3])
                prov.note_witness(h, r, c)
                prov.note_fame(h, r, True, engine="x", voter=H[15])
                prov.note_received(h, r)
    if mutate:
        mutate(b)
    return a, b


def test_bisector_clean_pair_localizes_nothing():
    a, b = _two()
    assert DivergenceBisector().bisect(
        "a", a.to_json(), "b", b.to_json()
    ) is None


def test_bisector_pass_order_earliest_wins():
    # corrupt BOTH a round-1 lastAncestors cell and a round-1 fame cell:
    # causal pass order must name lastAncestors, the upstream table
    def mutate(b):
        rp = b.round_provenance(1)
        rp.tables["lastAncestors"][H[4]] = [99, 9, 9, 9]
        rp.tables["fame"][H[5]] = False

    a, b = _two(mutate)
    loc = DivergenceBisector().bisect("a", a.to_json(), "b", b.to_json())
    assert (loc["round"], loc["pass"], loc["table"], loc["cell"]) == (
        1, "divide", "lastAncestors", H[4],
    )
    assert loc["kind"] == "value-mismatch"
    # the fame divergence carries the deciding why context
    rp = b.round_provenance(1)
    rp.tables["lastAncestors"][H[4]] = [4, 1, 2, 3]  # heal upstream
    loc = DivergenceBisector().bisect("a", a.to_json(), "b", b.to_json())
    assert (loc["table"], loc["cell"]) == ("fame", H[5])
    assert loc["voter"] == H[15]
    assert loc["why"]["a"]["voter"] == H[15]


def test_bisector_skips_unretained_rounds_flags_missing_ones():
    # b evicted rounds 0-1 (bounded recorder): not comparable, skipped
    a = ProvenanceRecorder(clock=SimClock())
    b = ProvenanceRecorder(clock=SimClock(), round_cap=4)
    for prov, rounds in ((a, range(6)), (b, range(10))):
        for r in rounds:
            prov.note_witness(H[r % len(H)], r, 0)
    assert b.evicted_below == 6
    # common comparable window is empty of disagreement -> None
    assert DivergenceBisector().bisect(
        "a", a.to_json(), "b", b.to_json()
    ) is None
    # a hole INSIDE the window is a real finding
    a2, b2 = _two()
    del b2._rounds[1]  # white-box: simulate a dropped round
    loc = DivergenceBisector().bisect("a", a2.to_json(), "b", b2.to_json())
    assert loc["kind"] == "missing-round" and loc["round"] == 1
    assert (loc["a"], loc["b"]) == ("present", "absent")


def test_bisector_smoke_is_the_ci_gate():
    assert run_bisector_smoke(seeds=3) == []


# ---------------------------------------------------------------------------
# cross-engine comparability + the seeded defect fixture
# ---------------------------------------------------------------------------


def _cpu_vs_device(init):
    from babble_tpu.tpu import run_consensus_device
    from test_tpu_differential import clone_hashgraph

    r = init()
    hg = r[0] if isinstance(r, tuple) else r
    cpu, dev = clone_hashgraph(hg), clone_hashgraph(hg)
    cpu.commit_callback = lambda b: None
    dev.commit_callback = lambda b: None
    cpu.run_consensus()
    run_consensus_device(dev)
    return cpu, dev


@pytest.mark.parametrize("fixture", ["consensus", "funky"])
def test_cpu_and_device_table_streams_byte_identical(fixture):
    from dsl import init_consensus_hashgraph, init_funky_hashgraph

    init = {
        "consensus": init_consensus_hashgraph,
        "funky": lambda: init_funky_hashgraph(full=True),
    }[fixture]
    cpu, dev = _cpu_vs_device(init)
    pc, pd = cpu.obs.provenance, dev.obs.provenance
    assert pc.rounds() == pd.rounds() and pc.rounds()
    assert pc.table_bytes() == pd.table_bytes()
    assert pc.table_fingerprint() == pd.table_fingerprint()
    # the bisector agrees: nothing to localize between the engines
    assert DivergenceBisector().bisect(
        "cpu", pc.to_json(), "device", pd.to_json()
    ) is None
    # the CPU oracle recorded rich deciding context for the fame cells
    whys = [
        rp.why for r in pc.rounds()
        if (rp := pc.round_provenance(r)) and rp.why
    ]
    assert whys, "CPU oracle recorded no fame whys"
    some = next(iter(whys[0].values()))
    assert some["engine"] == "cpu"
    assert {"voter", "yays", "nays", "ss", "step"} <= set(some)


def test_seeded_defect_localizes_to_exact_cell(tmp_path):
    from babble_tpu.tpu import synthetic_grid

    grid = synthetic_grid(4, 120, seed=3)
    clean, _ = broken_fame_passes(grid, flip=False)
    # clean control arm: two captures of the same results -> zero findings
    loc, path = bisect_pass_results(
        grid, "a", clean, "b", clean, artifact_dir=str(tmp_path),
        label="clean",
    )
    assert loc is None and path is None and not os.listdir(tmp_path)

    broken, injected = broken_fame_passes(grid, flip=True, seed=3)
    inj_round, inj_hash = injected
    loc, path = bisect_pass_results(
        grid, "good", clean, "bad", broken, artifact_dir=str(tmp_path),
        label="seeded",
    )
    assert (loc["round"], loc["pass"], loc["table"], loc["cell"]) == (
        inj_round, "fame", "fame", inj_hash,
    )
    # deterministic artifact name, byte-identical across repeat runs
    assert os.path.basename(path) == "bisect-seeded-good-vs-bad.json"
    with open(path, "rb") as f:
        first = f.read()
    doc = json.loads(first)
    assert doc["kind"] == "babble-tpu-divergence-localization"
    assert doc["localized"]["cell"] == inj_hash
    _, path2 = bisect_pass_results(
        grid, "good", clean, "bad", broken, artifact_dir=str(tmp_path),
        label="seeded",
    )
    with open(path2, "rb") as f:
        assert f.read() == first


# ---------------------------------------------------------------------------
# sim integration
# ---------------------------------------------------------------------------


def test_sim_provenance_fingerprint_deterministic_per_backend():
    from babble_tpu.sim import run_one

    a = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    b = run_one(5, plan="lossy", n=4, until=None, target_block=3)
    assert a["ok"] and b["ok"]
    assert "provenance_fingerprint" in a
    assert a["provenance_fingerprint"] == b["provenance_fingerprint"]
    assert a["localized"] is None and a["bisect_artifact"] is None
    # a different seed moves the stream
    c = run_one(6, plan="lossy", n=4, until=None, target_block=3)
    assert c["provenance_fingerprint"] != a["provenance_fingerprint"]


@pytest.mark.parametrize("preset", ["lossy", "partition_heal"])
def test_fault_plans_keep_streams_complete_or_truncated(preset):
    from babble_tpu.sim import SimCluster, preset_plan

    cluster = SimCluster(n=4, seed=7, plan=preset_plan(preset, 4))
    try:
        cluster.run(until=None, target_block=3)
        for sn in cluster.sns:
            if sn.node is None:
                continue
            prov = sn.node.obs.provenance
            assert prov.verify_complete_or_truncated() == []
            assert prov.rounds(), f"{sn.name} recorded no provenance"
    finally:
        cluster.shutdown()


def test_sim_export_provenance_artifacts(tmp_path):
    from babble_tpu.sim import SimCluster, preset_plan

    cluster = SimCluster(n=4, seed=2, plan=preset_plan("clean", 4))
    try:
        cluster.run(until=None, target_block=2)
        paths = cluster.export_provenance(str(tmp_path))
        assert len(paths) == 4
        assert os.path.basename(paths[0]) == "provenance-seed2-node0.json"
        with open(paths[0]) as f:
            doc = json.load(f)
        assert doc["rounds"] and doc["evicted_below"] == 0
        # the exported docs are bisector food. Live nodes legitimately
        # trail each other at the unsettled tail, so the cross-node
        # agreement contract holds over the commonly SETTLED rounds:
        # restricted to those, all four nodes localize nothing.
        docs = []
        for p in paths:
            with open(p) as f:
                docs.append((os.path.basename(p), json.load(f)))
        finals = [
            {r for r, v in d["rounds"].items() if v["final"]}
            for _, d in docs
        ]
        common = set.intersection(*finals)
        assert common, "no commonly settled rounds across the cluster"
        views = [
            (name, {
                "evicted_below": 0,
                "rounds": {
                    r: v for r, v in d["rounds"].items() if r in common
                },
            })
            for name, d in docs
        ]
        assert DivergenceBisector().localize(views) is None
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# watchdog stall provenance + commit-latency exemplar
# ---------------------------------------------------------------------------


def test_watchdog_stall_carries_round_provenance():
    from babble_tpu.node.watchdog import LivenessWatchdog

    clock = SimClock()
    obs = Observability(clock=clock, node_id=0)
    obs.provenance.note_witness(H[0], 3, 0)  # the stuck round's table
    wd = LivenessWatchdog(
        clock=clock, obs=obs, logger=logging.getLogger("test.wd"),
        deadline=1.0, round_fn=lambda: 2, pending_fn=lambda: 5,
    )
    wd.check()
    clock.advance_to(2.0)
    assert wd.check() is True
    recs = [r for r in obs.flightrec.to_json()["records"]
            if r["name"] == "watchdog.stall"]
    assert len(recs) == 1
    f = recs[0]["fields"]
    assert f["last_decided_round"] == 2 and f["stuck_round"] == 3
    assert f["prov"] == obs.provenance.round_fingerprint(3)
    dump = obs.flightrec.dump_docs[-1]
    assert dump["reason"] == "consensus-stall"
    assert dump["context"]["stuck_round"] == 3
    assert dump["context"]["prov"] == f["prov"]


def test_commit_latency_exemplar_links_to_trace():
    from babble_tpu.obs.tracectx import trace_id_for
    from babble_tpu.sim import SimCluster, preset_plan

    cluster = SimCluster(n=4, seed=4, plan=preset_plan("clean", 4))
    try:
        cluster.run(until=None, target_block=2)
        linked = 0
        for sn in cluster.sns:
            hist = sn.node._m_commit_latency
            ex = hist.exemplar()
            if ex is None:
                continue  # node never committed its own traced tx
            linked += 1
            assert len(ex) == 16 and int(ex, 16) >= 0
            text = sn.node.obs.registry.expose()
            assert (
                f'# EXEMPLAR babble_commit_latency_seconds trace_id="{ex}"'
                in text
            )
            snap = sn.node.obs.registry.snapshot()
            assert (
                snap["babble_commit_latency_seconds"]["series"][""]["exemplar"]
                == ex
            )
        assert linked, "no node attached a commit-latency exemplar"
    finally:
        cluster.shutdown()


def test_histogram_exemplar_is_per_series_and_optional():
    obs = Observability()
    h = obs.histogram("x_seconds", "t", labels=("peer",))
    h.labels(peer="a").observe(0.1, exemplar="cafe")
    h.labels(peer="b").observe(0.2)
    assert h.exemplar(peer="a") == "cafe"
    assert h.exemplar(peer="b") is None
    lines = h.render()
    assert sum("# EXEMPLAR" in ln for ln in lines) == 1


# ---------------------------------------------------------------------------
# /debug/explain + CLI
# ---------------------------------------------------------------------------


class _Block:
    def __init__(self, index, rr):
        self._index, self._rr = index, rr

    def index(self):
        return self._index

    def round_received(self):
        return self._rr


class _FakeNode:
    def __init__(self, obs):
        self.id = 0
        self.obs = obs
        self.clock = obs.clock

    def get_stats(self):
        return {"id": "0"}

    def get_block(self, index):
        if index != 12:
            raise KeyError(index)
        return _Block(12, 3)


def _serve(node):
    from babble_tpu.service import Service

    return Service("127.0.0.1:0", node)


def test_debug_explain_endpoint():
    obs = Observability(node_id=0)
    obs.provenance.note_witness(H[0], 3, 1)
    obs.provenance.note_fame(H[0], 3, True, engine="cpu", voter=H[1],
                             yays=3, nays=0, ss=4, step=2)
    svc = _serve(_FakeNode(obs))
    try:
        svc.serve()
        base = f"http://{svc.local_addr()}"
        with urllib.request.urlopen(
            base + "/debug/explain?block=12", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert doc["block_index"] == 12 and doc["round"] == 3
        assert doc["known"] and doc["tables"]["fame"][H[0]] is True
        assert doc["why"][H[0]]["voter"] == H[1]
        with urllib.request.urlopen(
            base + "/debug/explain?round=9", timeout=5
        ) as r:
            doc = json.loads(r.read())
        assert doc["known"] is False and doc["round"] == 9
        # missing selector -> HTTP error, service stays up
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/debug/explain", timeout=5)
    finally:
        svc.shutdown()


def test_explain_cli_smoke_and_offline_bisect(tmp_path, capsys):
    from babble_tpu.cli import main

    assert main(["explain", "--smoke", "3"]) == 0
    assert "0 failures" in capsys.readouterr().out

    a, b = _two()
    rp = b.round_provenance(2)
    rp.tables["fame"][H[6]] = False
    pa, pb = tmp_path / "na.json", tmp_path / "nb.json"
    pa.write_text(json.dumps(a.to_json()))
    pb.write_text(json.dumps(b.to_json()))
    assert main([
        "explain", "--bisect", str(pa), str(pb),
        "--artifact-dir", str(tmp_path),
    ]) == 1
    out = capsys.readouterr().out
    loc = json.loads(out[: out.rindex("}") + 1])
    assert (loc["round"], loc["table"], loc["cell"]) == (2, "fame", H[6])
    assert (tmp_path / "bisect-na-vs-nb.json").exists()
    # agreeing streams exit 0
    pb.write_text(json.dumps(a.to_json()))
    assert main([
        "explain", "--bisect", str(pa), str(pb),
    ]) == 0
