"""Benchmark of record: events/sec through the device DivideRounds +
DecideFame + DecideRoundReceived pipeline at 64 validators (BASELINE.md
north-star config; reference harness: src/hashgraph/hashgraph_test.go:1522,
which publishes no absolute numbers — the target is BASELINE.json's
1M pending events/sec on a single chip).

The timed path is the round-frontier pipeline (babble_tpu/tpu/frontier.py);
its results are asserted bit-equal to the level-scan engine path
(run_passes) before the number is reported.

Prints the headline as the LAST line, carrying the metrics-registry
snapshot (the obs-layer view of the run: per-iteration latency
histogram + throughput gauge) inline under its "metrics" key:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "metrics": {...}}
vs_baseline is value / 1e6 (the BASELINE.json target, since the reference
publishes no numbers of its own). Drivers that parse the last stdout
line keep working unchanged.

`--slo` turns the perf trajectory from advisory into enforceable: the
throughput gauge is declared as an SLO objective (obs/slo.py) and the
process exits nonzero when the run breaches it. The SLO report goes to
stderr so the headline stays the last stdout line.

Runs on whatever JAX platform is available (real TPU under the driver).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_VALIDATORS = 64
N_EVENTS = 32768
SEED = 0
TARGET_EVENTS_PER_SEC = 1_000_000.0

CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "bench_cache",
    f"grid_{N_VALIDATORS}x{N_EVENTS}_seed{SEED}.npz",
)


def load_grid():
    import numpy as np

    from babble_tpu.tpu.grid import DagGrid, build_levels, synthetic_grid

    if os.path.exists(CACHE):
        from babble_tpu.tpu.grid import MIN_INT32

        z = np.load(CACHE)
        levels, num_levels = build_levels(
            N_VALIDATORS, z["self_parent"], z["other_parent"]
        )
        e = N_EVENTS
        return DagGrid(
            n=N_VALIDATORS,
            e=e,
            super_majority=2 * N_VALIDATORS // 3 + 1,
            creator=z["creator"],
            index=z["index"],
            self_parent=z["self_parent"],
            other_parent=z["other_parent"],
            last_ancestors=z["la"],
            first_descendants=z["fd"],
            coin_bit=z["coin"],
            fixed_round=np.where(
                (z["self_parent"] < 0) & (z["other_parent"] < 0), 0, -1
            ).astype(np.int32),
            ext_sp_round=np.full(e, -1, dtype=np.int32),
            ext_op_round=np.full(e, -1, dtype=np.int32),
            ext_sp_lamport=np.full(e, -1, dtype=np.int32),
            ext_op_lamport=np.full(e, MIN_INT32, dtype=np.int32),
            fixed_lamport=np.full(e, MIN_INT32, dtype=np.int32),
            levels=levels,
            num_levels=num_levels,
        )

    grid = synthetic_grid(N_VALIDATORS, N_EVENTS, seed=SEED, zipf_a=1.1)
    os.makedirs(os.path.dirname(CACHE), exist_ok=True)
    np.savez_compressed(
        CACHE,
        creator=grid.creator,
        index=grid.index,
        self_parent=grid.self_parent,
        other_parent=grid.other_parent,
        la=grid.last_ancestors,
        fd=grid.first_descendants,
        coin=grid.coin_bit,
    )
    return grid


def slo_gate(obs, min_events_per_sec: float):
    """Declare the throughput objective over the bench registry and
    evaluate it once (cumulative single-sample evaluation — see
    obs/slo.py). Returns (ok, status_doc). Factored out so tests can
    gate a synthetic registry without running the device pipeline."""
    from babble_tpu.obs import SLOEngine

    slo = SLOEngine(obs)
    slo.objective(
        "bench_throughput",
        series="babble_bench_events_per_second",
        kind="above", threshold=min_events_per_sec,
        description="benchmark throughput stays at or above the floor",
    )
    status = slo.evaluate()
    return not slo.breached(), status


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slo", action="store_true",
                    help="Gate the run on the throughput SLO: exit 1 "
                         "when events/s falls below the floor")
    ap.add_argument("--slo-min-events-per-sec", type=float,
                    default=TARGET_EVENTS_PER_SEC,
                    help="Throughput floor for --slo (default: the "
                         "BASELINE.json 1M events/s target)")
    args = ap.parse_args(argv)

    import jax

    from babble_tpu.tpu import kernels
    from babble_tpu.tpu.engine import run_passes

    grid = load_grid()

    # throughput measurement: the steady-state replay pattern — coordinate
    # matrices device-resident (uploaded once, as the incremental engine
    # keeps them), batches dispatched back-to-back, completion synced at
    # the end. Per-batch host syncs would only measure the host<->device
    # link latency, not the pipeline. This must compile BEFORE any
    # numpy-arg invocation of the same shapes: an executable compiled for
    # host-resident args gets layouts that penalize device-resident ones.
    dev = {
        k: jax.device_put(getattr(grid, k))
        for k in (
            "creator", "index", "last_ancestors", "first_descendants",
            "coin_bit",
        )
    }
    # flagship path: the round-frontier pipeline (sequential steps = round
    # count, not DAG depth; INV lookups as one-hot MXU einsums). INV and
    # the chain tables are functions of the persistent coordinate state —
    # a live engine maintains them incrementally at insert, so they stage
    # outside the timed loop like the coordinate matrices themselves.
    from babble_tpu.tpu.frontier import (
        build_inv, chain_table, frontier_pipeline, level_lamport, sp_index_of,
    )

    rows_by = chain_table(grid)
    dev["rows_by"] = jax.device_put(rows_by)
    dev["sp_index"] = jax.device_put(sp_index_of(grid))
    dev["lamport"] = jax.device_put(level_lamport(grid))
    inv = build_inv(dev["rows_by"], dev["last_ancestors"])

    # round axis: N-aligned floor (below the lane width tiles poorly); one
    # doubling retry if the DAG turns out deeper than the default
    r_fame = max(64, N_VALIDATORS)

    def run_batch():
        return frontier_pipeline(
            inv, dev["rows_by"], dev["creator"], dev["index"],
            dev["sp_index"], dev["last_ancestors"], dev["first_descendants"],
            dev["lamport"], dev["coin_bit"],
            grid.super_majority, grid.n, r_fame,
        )

    import jax.numpy as jnp
    import numpy as np

    out = run_batch()
    while int(np.asarray(out.last_round)) + 2 > r_fame:  # compile + sync
        r_fame *= 2
        out = run_batch()

    # sustained warm-up: the chip serves the first batch train at reduced
    # clocks; measure only the steady state
    warm = jnp.int32(0)
    for _ in range(50):
        warm = warm + run_batch().last_round
    int(np.asarray(warm))

    # block_until_ready does not reliably await remote execution on every
    # platform; accumulate a scalar that depends on EVERY batch's full
    # output and fetch it once — the only sync that cannot lie
    iters = 40
    start = time.perf_counter()
    acc = jnp.int32(0)
    for _ in range(iters):
        out = run_batch()
        acc = acc + out.last_round + jnp.sum(out.received) + jnp.sum(out.rounds)
    int(np.asarray(acc))
    elapsed = (time.perf_counter() - start) / iters

    # correctness gate: the full engine path (adaptive round axis, host
    # staging) must reproduce the device-loop results on this DAG
    res = run_passes(grid, adaptive_r=True)
    assert res.last_round > 0, "synthetic DAG failed to advance rounds"
    assert res.rounds_decided[: max(res.last_round - 6, 0)].all(), (
        "fame undecided in settled region"
    )
    try:
        np.testing.assert_array_equal(np.asarray(out.rounds), res.rounds)
        np.testing.assert_array_equal(np.asarray(out.received), res.received)
    except AssertionError:
        # first-divergence bisection (obs/provenance.py): name the
        # earliest divergent (pass, table, round, witness) cell before
        # re-raising, so the gate failure is localized, not just detected
        from babble_tpu.obs import bisect_pass_results

        loc, bisect_path = bisect_pass_results(
            grid, "device-loop", out, "engine", res, label="bench",
        )
        if loc is not None:
            print(
                "bisected: round %s %s/%s cell %s (%s)" % (
                    loc["round"], loc["pass"], loc["table"],
                    (loc.get("cell") or "")[:18], bisect_path,
                ),
                file=sys.stderr,
            )
        raise

    events_per_sec = grid.e / elapsed

    # obs-layer registry view of the run, embedded in the headline (the
    # driver parses the last stdout line, so everything rides in it)
    from babble_tpu.obs import Observability, log_buckets

    obs = Observability()
    # device-time ledger (ISSUE 19): one ledgered pass of the exact
    # batch the timed loop ran — outside the measurement so the seam
    # cost cannot perturb the headline; the executable is warm, so this
    # records a pure run cell plus the entry's byte traffic
    from babble_tpu.obs import ledger_call

    with obs.devledger.activate("frontier"):
        ledger_call(
            "frontier_pipeline", frontier_pipeline,
            inv, dev["rows_by"], dev["creator"], dev["index"],
            dev["sp_index"], dev["last_ancestors"],
            dev["first_descendants"], dev["lamport"], dev["coin_bit"],
            grid.super_majority, grid.n, r_fame,
        )
    bench_hist = obs.histogram(
        "babble_bench_iteration_seconds",
        "Per-iteration wall time of the benchmark device pipeline",
        buckets=log_buckets(0.0001, 2.0, 20),
    )
    bench_hist.observe(elapsed)
    obs.gauge(
        "babble_bench_events_per_second",
        "Benchmark throughput headline",
    ).set(events_per_sec)

    print(
        json.dumps(
            {
                "metric": (
                    "events ordered/sec through device "
                    "DivideRounds+DecideFame+DecideRoundReceived, "
                    f"{N_VALIDATORS} validators, {N_EVENTS} events, "
                    f"platform={jax.devices()[0].platform}"
                ),
                "value": round(events_per_sec, 1),
                "unit": "events/s",
                "vs_baseline": round(events_per_sec / TARGET_EVENTS_PER_SEC, 3),
                "ledger": {
                    "shares": obs.devledger.snapshot()["shares"],
                    "efficiency": obs.devledger.efficiency(),
                },
                "metrics": obs.registry.snapshot(),
            }
        )
    )

    if args.slo:
        ok, status = slo_gate(obs, args.slo_min_events_per_sec)
        print(
            "SLO gate:",
            json.dumps(status["objectives"], sort_keys=True),
            file=sys.stderr,
        )
        if not ok:
            print(
                f"SLO BREACH: {events_per_sec:.0f} events/s under the "
                f"{args.slo_min_events_per_sec:.0f} floor",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
