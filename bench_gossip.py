"""Host gossip benchmark: 4 full nodes over the in-memory transport run to
50 committed blocks with byte-equality verified — the reference's
BenchmarkGossip configuration (reference: src/node/node_test.go:800-807)
whose CI-enforced floor is 50 blocks in < 3 s (node_test.go:422-437).

Prints one JSON line like bench.py. Runs on CPU (host runtime only).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TARGET_BLOCKS = 50
REFERENCE_FLOOR_S = 3.0


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from test_node import (
        bombard_and_wait,
        check_gossip,
        init_nodes,
        run_nodes,
        shutdown_nodes,
    )

    t0 = time.perf_counter()
    nodes, proxies = init_nodes(4)
    run_nodes(nodes)
    try:
        bombard_and_wait(nodes, proxies, target_block=TARGET_BLOCKS, timeout_s=120)
        elapsed = time.perf_counter() - t0
        check_gossip(nodes, upto=TARGET_BLOCKS)
        # node 0's typed-registry view of the same run (sync/commit
        # latencies, trace stage histograms, ...) rides in the headline
        metrics = nodes[0].obs.registry.snapshot()
    finally:
        shutdown_nodes(nodes)

    print(
        json.dumps(
            {
                "metric": (
                    f"wall seconds for 4 nodes to commit {TARGET_BLOCKS} "
                    "byte-identical blocks (inmem transport)"
                ),
                "value": round(elapsed, 2),
                "unit": "s",
                # <1 means faster than the reference's CI floor
                "vs_baseline": round(elapsed / REFERENCE_FLOOR_S, 3),
                "metrics": metrics,
            }
        )
    )


if __name__ == "__main__":
    main()
