"""Dispatch-discipline benchmark: events/sec and blocked device ms/call
for the three ways a live node can drive the sharded mesh backend
(babble_tpu/tpu/dispatch.py; ROADMAP open item 1).

The workload is a stream of CALLS gossip syncs. Each sync does the real
O(E) host restage work (build_levels over the full coordinate arrays —
the 0.3 ms/call side of the MULTICHIP_r05 breakdown), then the dispatch
discipline decides when the device runs:

- sync        — every sync blocks on a full sharded three-pass pipeline
                (the r05 one-shot rung: 273.8 ms/call on device);
- pipelined   — single-slot overlap: dispatch sync i, block on sync i-1
                (tpu/live.py's original discipline applied to the mesh);
- queued_mesh — bounded multi-slot queue with cross-round batching: syncs
                accumulate while dispatches are in flight, and ONE
                execution covers every pending sync (the one-shot restage
                property: device cost is per-dispatch, not per-sync).

Because decisions are DAG facts, all three disciplines produce identical
pass results — asserted below — so the only thing that varies is when
the device runs, which is the whole point.

Prints the headline as the LAST line (driver-parsable), carrying the
per-discipline numbers and the metrics-registry snapshot:
  {"metric": ..., "value": <queued events/s>, "unit": "events/s",
   "vs_baseline": <queued/sync speedup>, "disciplines": {...},
   "metrics": {...}}

`--slo` gates the run: the queued-mesh discipline's blocked device time
per call is declared as a mean-below SLO objective (obs/slo.py) and the
process exits nonzero on breach (report on stderr; the headline stays
the last stdout line).

Runs on whatever JAX platform is available (real TPU under the driver);
the mesh uses up to 8 local devices.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_VALIDATORS = 8
N_EVENTS = 256
SEED = 11
CALLS = 16          # gossip syncs per discipline
QUEUE_DEPTH = 4     # queued_mesh: max dispatches in flight
BATCH_SYNCS = 4     # queued_mesh: syncs accumulated per dispatch
# gossip syncs arrive from the network at a finite cadence; a dispatch
# discipline that overlaps device work with this interval hides it, one
# that blocks serializes behind it. Without an arrival model every
# discipline is purely device-bound and overlap cannot show up at all.
GOSSIP_INTERVAL_S = 0.01


def slo_gate(obs, max_blocked_s: float):
    """Declare the queued-mesh blocked-time objective and evaluate once
    (cumulative single-sample evaluation). Returns (ok, status_doc)."""
    from babble_tpu.obs import SLOEngine

    slo = SLOEngine(obs)
    slo.objective(
        "dispatch_blocked",
        series="babble_bench_dispatch_blocked_seconds",
        kind="mean_below", threshold=max_blocked_s,
        labels={"path": "queued_mesh"},
        description="queued-mesh blocked device time per sync stays "
                    "under the ceiling",
    )
    # steady-state retrace budget (ISSUE 19): zero kernel retraces past
    # the warmup baseline — a nonzero delta means some staged callable
    # is being rebuilt per call and the compile cache never serves it
    slo.objective(
        "retrace_budget",
        series="babble_bench_retrace_delta",
        kind="below", threshold=1.0,
        description="steady-state kernel retraces past warmup stay at "
                    "zero",
    )
    status = slo.evaluate()
    return not slo.breached(), status


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slo", action="store_true",
                    help="Gate the run on the queued-mesh blocked-time "
                         "SLO: exit 1 when mean blocked s/call exceeds "
                         "the ceiling")
    ap.add_argument("--slo-max-blocked-ms", type=float, default=150.0,
                    help="Ceiling on queued-mesh mean blocked device "
                         "ms per gossip sync for --slo")
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from babble_tpu.tpu.dispatch import _AsyncPass
    from babble_tpu.tpu.grid import build_levels, synthetic_grid
    from babble_tpu.tpu.sharded import sharded_frontier_passes

    devices = jax.devices()
    n_dev = 1
    while n_dev * 2 <= min(8, len(devices)):
        n_dev *= 2
    from jax.sharding import Mesh

    mesh = Mesh(np.array(devices[:n_dev]), ("rounds",))
    grid = synthetic_grid(N_VALIDATORS, N_EVENTS, seed=SEED)

    def gossip_stage():
        # the per-sync work every discipline pays: the gossip arrival
        # interval (overlappable — this is where in-flight device work
        # hides) plus the O(E) restage of the level schedule
        time.sleep(GOSSIP_INTERVAL_S)
        return build_levels(N_VALIDATORS, grid.self_parent, grid.other_parent)

    from babble_tpu.obs import (
        Observability,
        log_buckets,
        retrace_baseline,
        retrace_delta,
    )

    obs = Observability()
    led = obs.devledger

    # compile + warm outside every timed loop (shapes are shared across
    # disciplines, so this is the only compilation in the process). The
    # device-time ledger watches the warmup so every legitimate compile
    # lands here; anything after the baseline below is a silent retrace.
    with led.activate("sharded"):
        ref = sharded_frontier_passes(mesh, grid)
        sharded_frontier_passes(mesh, grid)
    retrace_base = retrace_baseline(obs)

    results = {}
    blocked = {}

    # -- sync: block on the device every call -----------------------------
    t0 = time.perf_counter()
    b = 0.0
    for _ in range(CALLS):
        gossip_stage()
        tb = time.perf_counter()
        with led.activate("sharded"):
            out = sharded_frontier_passes(mesh, grid)
        b += time.perf_counter() - tb
    results["sync"] = time.perf_counter() - t0
    blocked["sync"] = b

    # -- pipelined: single-slot overlap (dispatch i, wait for i-1) --------
    t0 = time.perf_counter()
    b = 0.0
    prev = None
    for _ in range(CALLS):
        gossip_stage()
        task = _AsyncPass(mesh, grid, ledger=led)
        if prev is not None:
            tb = time.perf_counter()
            out = prev.result()
            b += time.perf_counter() - tb
        prev = task
    tb = time.perf_counter()
    out = prev.result()
    b += time.perf_counter() - tb
    results["pipelined"] = time.perf_counter() - t0
    blocked["pipelined"] = b

    # -- queued_mesh: bounded queue + cross-round batching ----------------
    t0 = time.perf_counter()
    b = 0.0
    inflight = []
    pending = 0
    for _ in range(CALLS):
        gossip_stage()
        pending += 1
        while len(inflight) >= QUEUE_DEPTH:
            tb = time.perf_counter()
            out = inflight.pop(0).result()
            b += time.perf_counter() - tb
        if pending >= BATCH_SYNCS or not inflight:
            # one dispatch covers every pending sync: the one-shot
            # restage stages the whole graph, so integration of this
            # result lands the rounds for all of them at once
            inflight.append(_AsyncPass(mesh, grid, ledger=led))
            pending = 0
    while inflight:
        tb = time.perf_counter()
        out = inflight.pop(0).result()
        b += time.perf_counter() - tb
    results["queued_mesh"] = time.perf_counter() - t0
    blocked["queued_mesh"] = b

    # steady-state retrace budget (ISSUE 19): shapes are shared across
    # disciplines, so after the warmup the compile cache must serve every
    # timed call — any retrace here is a staging bug
    retraces = retrace_delta(obs, retrace_base)

    # correctness gate: dispatch discipline must not change results
    np.testing.assert_array_equal(np.asarray(out.rounds), np.asarray(ref.rounds))
    np.testing.assert_array_equal(
        np.asarray(out.received), np.asarray(ref.received)
    )
    assert out.last_round == ref.last_round

    # each sync delivers N_EVENTS / CALLS new events; a discipline's
    # throughput is how fast it moves the whole stream through ordering
    disciplines = {
        name: {
            "events_per_sec": round(N_EVENTS / results[name], 1),
            "ms_per_call": round(blocked[name] / CALLS * 1e3, 2),
            "wall_s": round(results[name], 3),
        }
        for name in ("sync", "pipelined", "queued_mesh")
    }

    eps = {k: v["events_per_sec"] for k, v in disciplines.items()}
    assert eps["queued_mesh"] >= eps["pipelined"] >= eps["sync"], (
        f"dispatch disciplines out of order: {eps}"
    )

    lat = obs.histogram(
        "babble_bench_dispatch_blocked_seconds",
        "Blocked device wall time per gossip sync, by dispatch discipline",
        labels=("path",),
        buckets=log_buckets(0.0001, 4.0, 20),
    )
    thr = obs.gauge(
        "babble_bench_dispatch_events_per_second",
        "Dispatch benchmark throughput, by dispatch discipline",
        labels=("path",),
    )
    for name in disciplines:
        lat.labels(path=name).observe(blocked[name] / CALLS)
        thr.labels(path=name).set(eps[name])
    # SLO-visible gauge for the retrace budget (the objective below
    # reads it; operators see the same series on /metrics)
    obs.gauge(
        "babble_bench_retrace_delta",
        "Steady-state kernel retraces past the warmup baseline "
        "(budget: zero)",
    ).set(float(sum(retraces.values())))

    led_snap = led.snapshot()
    print(
        json.dumps(
            {
                "metric": (
                    "events ordered/sec through the queued sharded mesh "
                    f"dispatch, {N_VALIDATORS} validators, {N_EVENTS} "
                    f"events, {CALLS} gossip syncs, mesh={n_dev}dev, "
                    f"platform={devices[0].platform}"
                ),
                "value": eps["queued_mesh"],
                "unit": "events/s",
                "vs_baseline": round(
                    eps["queued_mesh"] / max(eps["sync"], 1e-9), 2
                ),
                "disciplines": disciplines,
                "ledger": {
                    "shares": led_snap["shares"],
                    "compiles": sum(
                        e["compiles"] for e in led_snap["entries"].values()
                    ),
                    "retraces": sum(
                        e["retraces"] for e in led_snap["entries"].values()
                    ),
                    "retrace_delta": retraces,
                },
                "metrics": obs.registry.snapshot(),
            }
        )
    )

    if args.slo:
        ok, status = slo_gate(obs, args.slo_max_blocked_ms / 1e3)
        print(
            "SLO gate:",
            json.dumps(status["objectives"], sort_keys=True),
            file=sys.stderr,
        )
        if not ok:
            if retraces:
                # name the offending entry points and dump the flight
                # ring — the last dispatch lifecycle records are the
                # context an operator needs to see WHICH dispatch pattern
                # forced the rebuild
                print(
                    "RETRACE BUDGET BLOWN: "
                    + ", ".join(
                        f"{e} (+{int(d)})"
                        for e, d in sorted(retraces.items())
                    ),
                    file=sys.stderr,
                )
                print(
                    "flight ring: "
                    + json.dumps(obs.flightrec.to_json(), sort_keys=True),
                    file=sys.stderr,
                )
            print(
                f"SLO BREACH: queued_mesh blocked "
                f"{disciplines['queued_mesh']['ms_per_call']} ms/call over "
                f"the {args.slo_max_blocked_ms} ms ceiling"
                if disciplines["queued_mesh"]["ms_per_call"]
                > args.slo_max_blocked_ms
                else "SLO BREACH: steady-state retrace budget exceeded",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
