"""Cross-round perf-trend gate: read every BENCH_r*.json and
MULTICHIP_r*.json the driver has archived at the repo root, print the
events/s and blocked-device-ms/call trajectories, and exit nonzero when
the latest round regressed more than 10% against the best prior round.

This is the trend half of the SLO story (ISSUE 7 satellite): bench.py
--slo gates one run against an absolute floor; this script gates the
run-to-run trajectory so a regression that still clears the floor is
caught before it compounds. Wired as `make trend`.

Artifact shapes handled (oldest rounds predate the structured headline):
- BENCH_r*.json: {"rc", "tail", "parsed": {"value", "unit", ...}} —
  value from "parsed", falling back to the last JSON line of "tail".
- MULTICHIP_r*.json: {"rc", "ok", "tail"} — blocked ms/call from the
  JSON headline (unit "ms/call") once it exists, else regexes over the
  human OK line ("device-blocked N ms/call", then "device N ms/call").
- BENCH_INGEST_r*.json: same shape as BENCH; gated twice — committed
  tx/s (higher is better) and submit->commit p99 seconds (lower is
  better), both read from the bench_ingest.py headline.
- BENCH_INGEST/BENCH_MESH headlines additionally carry a
  "cluster_health" summary (ISSUE 20); its max_commit_skew_blocks is
  gated lower-is-better so a fabric that converges with growing
  frontier skew counts as a regression even when throughput holds.
Rounds with rc != 0 or no extractable number are reported and skipped.
"""

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REGRESSION_TOLERANCE = 0.10


def _round_of(path):
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def _last_json_line(tail):
    """The benches print their headline as the LAST stdout line; logs may
    trail it, so scan from the bottom for the first parsable JSON object."""
    for line in reversed((tail or "").splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def bench_value(doc):
    """events/s of one BENCH round, or None."""
    if doc.get("rc") != 0:
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("value"), (int, float)
    ):
        return float(parsed["value"])
    headline = _last_json_line(doc.get("tail"))
    if headline and isinstance(headline.get("value"), (int, float)):
        return float(headline["value"])
    return None


def multichip_value(doc):
    """blocked device ms/call of one MULTICHIP round, or None."""
    if doc.get("rc") != 0 or not doc.get("ok", True):
        return None
    headline = _last_json_line(doc.get("tail"))
    if (
        headline
        and headline.get("unit") == "ms/call"
        and isinstance(headline.get("value"), (int, float))
    ):
        return float(headline["value"])
    tail = doc.get("tail") or ""
    for pat in (
        r"device-blocked ([0-9.]+) ms/call",
        r"device ([0-9.]+) ms/call",
    ):
        m = re.search(pat, tail)
        if m:
            return float(m.group(1))
    return None


def ingest_p99_value(doc):
    """submit->commit p99 seconds of one BENCH_INGEST round, or None.
    The ingest headline carries the latency estimate alongside the
    throughput value; a missing/None p99 (no commits) is unextractable."""
    if doc.get("rc") != 0:
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("p99_s"), (int, float)
    ):
        return float(parsed["p99_s"])
    headline = _last_json_line(doc.get("tail"))
    if headline and isinstance(headline.get("p99_s"), (int, float)):
        return float(headline["p99_s"])
    return None


def cluster_skew_value(doc):
    """Worst-case cluster commit skew (blocks) of one round's headline
    `cluster_health` summary (ISSUE 20), or None for rounds predating
    the health plane. Gated lower-is-better: a bench round whose fabric
    converged with growing frontier skew regressed even if throughput
    held."""
    if doc.get("rc") != 0:
        return None
    headline = _last_json_line(doc.get("tail"))
    if headline is None:
        headline = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else None
    if not headline:
        return None
    ch = headline.get("cluster_health")
    if not isinstance(ch, dict):
        return None
    skew = ch.get("max_commit_skew_blocks")
    return float(skew) if isinstance(skew, (int, float)) else None


def load_series(pattern, extract):
    """[(round, value-or-None, doc-or-None)] sorted by round, one entry
    per artifact. The doc rides along so a regression verdict can read
    the headline's device-time ledger for attribution."""
    series = []
    for path in sorted(glob.glob(os.path.join(ROOT, pattern)), key=_round_of):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trend: unreadable {os.path.basename(path)}: {e}")
            series.append((_round_of(path), None, None))
            continue
        series.append((_round_of(path), extract(doc), doc))
    return series


def ledger_shares(doc):
    """Per-(rung, pass, layout) share map from an artifact's headline
    ledger (ISSUE 19), or None when the round predates the ledger."""
    if not isinstance(doc, dict):
        return None
    headline = _last_json_line(doc.get("tail"))
    if not headline:
        return None
    ledger = headline.get("ledger")
    if not isinstance(ledger, dict):
        return None
    shares = ledger.get("shares")
    return shares if isinstance(shares, dict) else None


def attribute_regression(latest_doc, prior_doc):
    """Name the (rung, pass) whose ledger share moved most between the
    best prior round and the regressed latest round. Returns
    (cell_key, delta, latest_share, prior_share) or None when either
    round carries no ledger."""
    latest = ledger_shares(latest_doc)
    prior = ledger_shares(prior_doc)
    if not latest or not prior:
        return None
    movers = []
    for key in set(latest) | set(prior):
        a = float(prior.get(key, 0.0))
        b = float(latest.get(key, 0.0))
        movers.append((abs(b - a), key, b - a, b, a))
    movers.sort(reverse=True)
    if not movers or movers[0][0] == 0.0:
        return None
    _mag, key, delta, b, a = movers[0]
    return key, delta, b, a


def check(name, series, unit, better):
    """Print one trajectory; return False when the latest valid round is
    >10% worse than the best prior valid round. `better` is max for
    higher-is-better series, min for lower-is-better. On a regression,
    diff the latest round's device-time ledger against the best prior
    round's and name the (rung, pass) whose share moved most."""
    valid = [(r, v, d) for r, v, d in series if v is not None]
    line = "  " + " -> ".join(
        f"r{r:02d}:{v:g}" if v is not None else f"r{r:02d}:-"
        for r, v, _d in series
    )
    print(f"{name} ({unit}):")
    print(line if series else "  (no artifacts)")
    if len(valid) < 2:
        print("  fewer than two valid rounds — nothing to gate")
        return True
    latest_r, latest, latest_doc = valid[-1]
    best_r, best, best_doc = (
        max(valid[:-1], key=lambda t: t[1]) if better is max
        else min(valid[:-1], key=lambda t: t[1])
    )
    if better is max:
        ok = latest >= best * (1.0 - REGRESSION_TOLERANCE)
        rel = latest / best - 1.0
    else:
        ok = latest <= best * (1.0 + REGRESSION_TOLERANCE)
        rel = best / latest - 1.0 if latest else 0.0
    verdict = "ok" if ok else "REGRESSION"
    print(
        f"  latest r{latest_r:02d} = {latest:g} vs best prior {best:g} "
        f"({rel:+.1%}): {verdict}"
    )
    if not ok:
        attr = attribute_regression(latest_doc, best_doc)
        if attr is not None:
            key, delta, b, a = attr
            print(
                f"  attribution: ledger share of {key} moved "
                f"{delta:+.1%} (r{best_r:02d} {a:.1%} -> "
                f"r{latest_r:02d} {b:.1%}) — the pass to profile first"
            )
        else:
            print(
                "  attribution: no device-time ledger in one or both "
                "rounds — rerun the bench to get per-pass shares"
            )
    return ok


def main():
    gates = (
        ("bench throughput", "BENCH_r*.json", bench_value, "events/s", max),
        (
            "multichip blocked device time", "MULTICHIP_r*.json",
            multichip_value, "ms/call", min,
        ),
        (
            "catchup cold-ingest throughput", "BENCH_CATCHUP_r*.json",
            bench_value, "events/s", max,
        ),
        (
            "mesh scale throughput", "BENCH_MESH_r*.json", bench_value,
            "events/s", max,
        ),
        (
            "ingest throughput", "BENCH_INGEST_r*.json", bench_value,
            "tx/s", max,
        ),
        (
            "mesh packed throughput", "BENCH_PACKED_r*.json", bench_value,
            "events/s", max,
        ),
        (
            "ingest submit->commit p99", "BENCH_INGEST_r*.json",
            ingest_p99_value, "s", min,
        ),
        # cluster health plane (ISSUE 20): the benches' worst-case
        # commit-frontier skew must not trend upward round-over-round
        (
            "ingest cluster commit skew", "BENCH_INGEST_r*.json",
            cluster_skew_value, "blocks", min,
        ),
        (
            "mesh cluster commit skew", "BENCH_MESH_r*.json",
            cluster_skew_value, "blocks", min,
        ),
    )
    failed = [
        name
        for name, pattern, extract, unit, better in gates
        if not check(name, load_series(pattern, extract), unit, better)
    ]
    if failed:
        # name the offending series so the failure is actionable straight
        # from the CI log, without rereading every trajectory above
        print(
            f"trend: {', '.join(failed)} regressed >"
            f"{REGRESSION_TOLERANCE:.0%} against the best prior round"
        )
        return 1
    print("trend: no >10% regression against best prior round")
    return 0


if __name__ == "__main__":
    sys.exit(main())
