"""Cross-round perf-trend gate: read every BENCH_r*.json and
MULTICHIP_r*.json the driver has archived at the repo root, print the
events/s and blocked-device-ms/call trajectories, and exit nonzero when
the latest round regressed more than 10% against the best prior round.

This is the trend half of the SLO story (ISSUE 7 satellite): bench.py
--slo gates one run against an absolute floor; this script gates the
run-to-run trajectory so a regression that still clears the floor is
caught before it compounds. Wired as `make trend`.

Artifact shapes handled (oldest rounds predate the structured headline):
- BENCH_r*.json: {"rc", "tail", "parsed": {"value", "unit", ...}} —
  value from "parsed", falling back to the last JSON line of "tail".
- MULTICHIP_r*.json: {"rc", "ok", "tail"} — blocked ms/call from the
  JSON headline (unit "ms/call") once it exists, else regexes over the
  human OK line ("device-blocked N ms/call", then "device N ms/call").
- BENCH_INGEST_r*.json: same shape as BENCH; gated twice — committed
  tx/s (higher is better) and submit->commit p99 seconds (lower is
  better), both read from the bench_ingest.py headline.
Rounds with rc != 0 or no extractable number are reported and skipped.
"""

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REGRESSION_TOLERANCE = 0.10


def _round_of(path):
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def _last_json_line(tail):
    """The benches print their headline as the LAST stdout line; logs may
    trail it, so scan from the bottom for the first parsable JSON object."""
    for line in reversed((tail or "").splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def bench_value(doc):
    """events/s of one BENCH round, or None."""
    if doc.get("rc") != 0:
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("value"), (int, float)
    ):
        return float(parsed["value"])
    headline = _last_json_line(doc.get("tail"))
    if headline and isinstance(headline.get("value"), (int, float)):
        return float(headline["value"])
    return None


def multichip_value(doc):
    """blocked device ms/call of one MULTICHIP round, or None."""
    if doc.get("rc") != 0 or not doc.get("ok", True):
        return None
    headline = _last_json_line(doc.get("tail"))
    if (
        headline
        and headline.get("unit") == "ms/call"
        and isinstance(headline.get("value"), (int, float))
    ):
        return float(headline["value"])
    tail = doc.get("tail") or ""
    for pat in (
        r"device-blocked ([0-9.]+) ms/call",
        r"device ([0-9.]+) ms/call",
    ):
        m = re.search(pat, tail)
        if m:
            return float(m.group(1))
    return None


def ingest_p99_value(doc):
    """submit->commit p99 seconds of one BENCH_INGEST round, or None.
    The ingest headline carries the latency estimate alongside the
    throughput value; a missing/None p99 (no commits) is unextractable."""
    if doc.get("rc") != 0:
        return None
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("p99_s"), (int, float)
    ):
        return float(parsed["p99_s"])
    headline = _last_json_line(doc.get("tail"))
    if headline and isinstance(headline.get("p99_s"), (int, float)):
        return float(headline["p99_s"])
    return None


def load_series(pattern, extract):
    """[(round, value-or-None)] sorted by round, one entry per artifact."""
    series = []
    for path in sorted(glob.glob(os.path.join(ROOT, pattern)), key=_round_of):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trend: unreadable {os.path.basename(path)}: {e}")
            series.append((_round_of(path), None))
            continue
        series.append((_round_of(path), extract(doc)))
    return series


def check(name, series, unit, better):
    """Print one trajectory; return False when the latest valid round is
    >10% worse than the best prior valid round. `better` is max for
    higher-is-better series, min for lower-is-better."""
    valid = [(r, v) for r, v in series if v is not None]
    line = "  " + " -> ".join(
        f"r{r:02d}:{v:g}" if v is not None else f"r{r:02d}:-"
        for r, v in series
    )
    print(f"{name} ({unit}):")
    print(line if series else "  (no artifacts)")
    if len(valid) < 2:
        print("  fewer than two valid rounds — nothing to gate")
        return True
    latest_r, latest = valid[-1]
    best = better(v for _, v in valid[:-1])
    if better is max:
        ok = latest >= best * (1.0 - REGRESSION_TOLERANCE)
        rel = latest / best - 1.0
    else:
        ok = latest <= best * (1.0 + REGRESSION_TOLERANCE)
        rel = best / latest - 1.0 if latest else 0.0
    verdict = "ok" if ok else "REGRESSION"
    print(
        f"  latest r{latest_r:02d} = {latest:g} vs best prior {best:g} "
        f"({rel:+.1%}): {verdict}"
    )
    return ok


def main():
    gates = (
        ("bench throughput", "BENCH_r*.json", bench_value, "events/s", max),
        (
            "multichip blocked device time", "MULTICHIP_r*.json",
            multichip_value, "ms/call", min,
        ),
        (
            "catchup cold-ingest throughput", "BENCH_CATCHUP_r*.json",
            bench_value, "events/s", max,
        ),
        (
            "mesh scale throughput", "BENCH_MESH_r*.json", bench_value,
            "events/s", max,
        ),
        (
            "ingest throughput", "BENCH_INGEST_r*.json", bench_value,
            "tx/s", max,
        ),
        (
            "mesh packed throughput", "BENCH_PACKED_r*.json", bench_value,
            "events/s", max,
        ),
        (
            "ingest submit->commit p99", "BENCH_INGEST_r*.json",
            ingest_p99_value, "s", min,
        ),
    )
    failed = [
        name
        for name, pattern, extract, unit, better in gates
        if not check(name, load_series(pattern, extract), unit, better)
    ]
    if failed:
        # name the offending series so the failure is actionable straight
        # from the CI log, without rereading every trajectory above
        print(
            f"trend: {', '.join(failed)} regressed >"
            f"{REGRESSION_TOLERANCE:.0%} against the best prior round"
        )
        return 1
    print("trend: no >10% regression against best prior round")
    return 0


if __name__ == "__main__":
    sys.exit(main())
