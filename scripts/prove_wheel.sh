#!/usr/bin/env bash
# Prove the packaging end to end (VERDICT r4 #9): build the wheel, install
# it into a CLEAN venv (--system-site-packages so the baked-in heavyweight
# deps — jax, numpy, cryptography — are not re-downloaded; the wheel itself
# installs with --no-deps --no-index, i.e. fully offline), then run a
# 2-node testnet FROM THE WHEEL's console script with the demo bot as the
# app, and require committed, byte-identical blocks over the HTTP service.
#
# Every babble-tpu import resolves from the venv: the working directory is
# $WORK, not the repo, so the checkout cannot shadow the installed package
# (the bot runs under the venv interpreter for the same reason).
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="${1:-/tmp/babble-tpu-wheel-proof}"
PY="${PY:-python3}"

rm -rf "$WORK"
mkdir -p "$WORK"

echo "== build wheel =="
(cd "$REPO" && $PY -m pip wheel --no-deps --no-build-isolation -w "$WORK/dist" . -q)
WHEEL=$(ls "$WORK"/dist/babble_tpu-*.whl)
echo "built: $WHEEL"

echo "== clean venv install (offline) =="
$PY -m venv --system-site-packages "$WORK/venv"
# the heavyweight deps are baked into the INVOKING interpreter's
# site-packages (which may itself be a venv, invisible to
# --system-site-packages); bridge them with a .pth instead of downloading
BAKED=$($PY -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
VSITE=$("$WORK/venv/bin/python" -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
echo "$BAKED" > "$VSITE/zz_baked_deps.pth"
"$WORK/venv/bin/pip" install --no-deps --no-index -q "$WHEEL"
test -x "$WORK/venv/bin/babble-tpu"
VPY="$WORK/venv/bin/python"

echo "== 2-node conf from the wheel's keygen =="
cd "$WORK"
PEERS="["
for i in 0 1; do
  mkdir -p "$WORK/node$i"
  PUB=$("$WORK/venv/bin/babble-tpu" keygen --datadir "$WORK/node$i" | sed -n 's/^Public Key: //p')
  [ "$i" -gt 0 ] && PEERS+=","
  PEERS+="{\"NetAddr\":\"127.0.0.1:$((23770 + i))\",\"PubKeyHex\":\"$PUB\"}"
done
PEERS+="]"
for i in 0 1; do echo "$PEERS" > "$WORK/node$i/peers.json"; done
"$WORK/venv/bin/babble-tpu" version

echo "== launch bots + nodes from the wheel =="
pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup EXIT
for i in 0 1; do
  JAX_PLATFORMS=cpu $VPY "$REPO/demo/dummy_bot.py" --name "w$i" \
    --client-listen "127.0.0.1:$((23790 + i))" \
    --proxy-connect "127.0.0.1:$((23780 + i))" --rate 5 \
    > "$WORK/node$i/bot.log" 2>&1 &
  pids+=($!)
  JAX_PLATFORMS=cpu "$WORK/venv/bin/babble-tpu" run \
    --datadir "$WORK/node$i" \
    --listen "127.0.0.1:$((23770 + i))" \
    --proxy-listen "127.0.0.1:$((23780 + i))" \
    --client-connect "127.0.0.1:$((23790 + i))" \
    --service-listen "127.0.0.1:$((23870 + i))" \
    --heartbeat 0.02 --timeout 0.5 --log warn \
    > "$WORK/node$i/log" 2>&1 &
  pids+=($!)
done

echo "== wait for committed blocks =="
last=-1
for _ in $(seq 1 90); do
  sleep 1
  last=$(curl -s "127.0.0.1:23870/stats" 2>/dev/null \
    | $VPY -c "import json,sys;print(json.load(sys.stdin)['last_block_index'])" 2>/dev/null || echo -1)
  [ "${last:--1}" -ge 2 ] 2>/dev/null && break
done
if [ "${last:--1}" -lt 2 ]; then
  echo "FAIL: wheel testnet never reached block 2"; tail -5 "$WORK"/node*/log; exit 1
fi

echo "== cross-node block byte-equality =="
if ! diff <(curl -s 127.0.0.1:23870/block/1) <(curl -s 127.0.0.1:23871/block/1) > /dev/null; then
  echo "FAIL: block 1 differs between wheel nodes"; exit 1
fi
echo "PASS: wheel-installed babble-tpu committed block $last; block 1 byte-identical across nodes"
