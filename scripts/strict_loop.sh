#!/usr/bin/env bash
# Loop the two strict fast-sync recovery tests (VERDICT r4 #1: done =
# 10/10 consecutive passes). Saves per-iteration logs; on failure keeps
# the full pytest output for the post-mortem.
set -u
N="${1:-10}"
OUT="${2:-/tmp/strict_loop}"
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
pass=0
for i in $(seq 1 "$N"); do
    log="$OUT/iter_${i}.log"
    timeout 2400 python -m pytest \
        tests/test_device_backend.py::test_mixed_backend_fast_sync_byte_identical \
        tests/test_device_backend.py::test_live_engine_reattaches_after_fast_sync \
        -q -p no:faulthandler --log-level=INFO > "$log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
        pass=$((pass + 1))
        echo "iter $i: PASS ($pass/$i)" | tee -a "$OUT/summary.txt"
        tail -1 "$log" >> "$OUT/summary.txt"
    else
        echo "iter $i: FAIL rc=$rc — log kept at $log" | tee -a "$OUT/summary.txt"
        cp "$log" "$OUT/FAIL_iter_${i}.log"
    fi
done
echo "DONE: $pass/$N passed" | tee -a "$OUT/summary.txt"
