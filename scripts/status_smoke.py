#!/usr/bin/env python3
"""Status-dashboard smoke (ISSUE 20, hard gate in ci_lint.sh / `make
status-smoke`): boot a 3-node in-process cluster, gossip it to a few
committed blocks, serve the cluster health plane over a real HTTP
Service, and assert the `babble-tpu status` renderer shows a converged
fleet — 3 nodes, zero commit skew, full frontier agreement, no
partition suspicion.

This is the end-to-end acceptance path for the health plane: digest
piggyback over live gossip -> fleet federation -> GET /debug/cluster
over TCP -> the exact dashboard strings an operator reads. A pull of
GET /health/digest rides along to cover the no-gossip fallback.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from babble_tpu.cli import render_status  # noqa: E402
from babble_tpu.crypto import generate_key, pub_key_bytes  # noqa: E402
from babble_tpu.hashgraph import InmemStore  # noqa: E402
from babble_tpu.net import InmemTransport  # noqa: E402
from babble_tpu.node import Config, Node  # noqa: E402
from babble_tpu.peers import Peer, Peers  # noqa: E402
from babble_tpu.proxy import InmemDummyClient  # noqa: E402
from babble_tpu.service import Service  # noqa: E402

N = 3
TARGET_BLOCK = 2
BUDGET_S = 60.0


def fail(msg: str) -> None:
    print(f"status_smoke: FAIL — {msg}")
    sys.exit(1)


def boot():
    conf = Config(
        heartbeat_timeout=0.005, tcp_timeout=1.0, cache_size=1000,
        sync_limit=300, cluster_staleness_deadline=2.0,
    )
    keys = [generate_key() for _ in range(N)]
    participants = Peers()
    peer_of_key = []
    for i, key in enumerate(keys):
        pub_hex = "0x" + pub_key_bytes(key).hex().upper()
        peer = Peer(net_addr=f"127.0.0.1:{9950 + i}", pub_key_hex=pub_hex)
        participants.add_peer(peer)
        peer_of_key.append(peer)
    nodes, transports, proxies = [], [], []
    for i, key in enumerate(keys):
        trans = InmemTransport(peer_of_key[i].net_addr)
        prox = InmemDummyClient()
        node = Node(
            conf, peer_of_key[i].id, key, participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes.append(node)
        transports.append(trans)
        proxies.append(prox)
    for t in transports:
        for u in transports:
            if t is not u:
                t.connect(u.local_addr(), u)
    return nodes, proxies


def main() -> int:
    nodes, proxies = boot()
    svc = Service("127.0.0.1:0", nodes[0])
    try:
        for node in nodes:
            node.run_async(True)
        svc.serve()
        addr = svc.local_addr()

        # drive a few blocks through, then wait for full convergence:
        # every node at the same frontier AND node 0's fleet table
        # showing all three digests at zero skew
        deadline = time.monotonic() + BUDGET_S
        tx = 0
        doc = None
        while time.monotonic() < deadline:
            for i in range(N):
                if len(nodes[i].core.transaction_pool) < 50:
                    proxies[i].submit_tx(f"smoke tx {tx} via {i}".encode())
                    tx += 1
            blocks = [n.core.get_last_block_index() for n in nodes]
            if min(blocks) >= TARGET_BLOCK and len(set(blocks)) == 1:
                with urllib.request.urlopen(
                    f"http://{addr}/debug/cluster", timeout=5.0
                ) as resp:
                    doc = json.loads(resp.read().decode())
                d = doc["derived"]
                if (
                    len(doc["fleet"]) == N
                    and d["babble_cluster_commit_skew_blocks"] == 0.0
                    and d["babble_cluster_frontier_agreement"] == 1.0
                    and not doc["suspicion"]["suspected"]
                ):
                    break
                doc = None
            time.sleep(0.01)
        if doc is None:
            fail(
                f"cluster did not converge to {N} nodes at zero skew "
                f"within {BUDGET_S:.0f}s "
                f"(blocks={[n.core.get_last_block_index() for n in nodes]})"
            )

        # the renderer itself is part of the gate: assert the exact
        # operator-facing strings, not just the JSON
        out = render_status(doc)
        print(out)
        if f"{len(doc['fleet'])} nodes" not in out:
            fail("renderer did not show the fleet size")
        if "commit skew: 0 blocks" not in out:
            fail("renderer did not show zero commit skew")
        if "frontier agreement: 1" not in out:
            fail("renderer did not show full frontier agreement")
        if "partition: none suspected" not in out:
            fail("renderer shows partition suspicion on a healthy cluster")

        # pull fallback: GET /health/digest serves the node's own digest
        with urllib.request.urlopen(
            f"http://{addr}/health/digest", timeout=5.0
        ) as resp:
            digest = json.loads(resp.read().decode())
        if digest.get("addr") != nodes[0].local_addr:
            fail(f"/health/digest addr mismatch: {digest.get('addr')!r}")
        if not isinstance(digest.get("block"), int) or digest["block"] < TARGET_BLOCK:
            fail(f"/health/digest block not converged: {digest.get('block')!r}")

        print(
            f"status_smoke: PASS — {N} nodes converged at block "
            f"{digest['block']}, zero skew, dashboard + /health/digest "
            f"served over {addr}"
        )
        return 0
    finally:
        svc.shutdown()
        for node in nodes:
            node.shutdown()


if __name__ == "__main__":
    sys.exit(main())
