#!/usr/bin/env python3
"""Measured all-gather volume per round-step of the chains-sharded
frontier walk (VERDICT r4 #6: make the v5e-8 projection arithmetic).

Compiles the sharded walk for a given (N validators, ndev, L window) on
the virtual CPU mesh, then reads the all-gather shapes OUT OF THE
COMPILED HLO — measured from the artifact XLA will run, not asserted
from the source. Prints one JSON line with bytes/step, bytes/dispatch
and the ICI time model.

Usage:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python scripts/mesh_comm_model.py [N] [ndev] [L] [r_cap]
"""

import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
NDEV = int(sys.argv[2]) if len(sys.argv) > 2 else 8
L = int(sys.argv[3]) if len(sys.argv) > 3 else 64
R_CAP = int(sys.argv[4]) if len(sys.argv) > 4 else 32

DTYPE_BYTES = {"f32": 4, "s32": 4, "u32": 4, "pred": 1, "s8": 1, "u8": 1,
               "bf16": 2, "f16": 2, "s64": 8, "u64": 8, "f64": 8}


def main():
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh

    from babble_tpu.tpu.sharded import _frontier_walk_fn

    devs = jax.devices("cpu")[:NDEV]
    mesh = Mesh(np.array(devs), ("shard",))
    sm = 2 * N // 3 + 1
    e = N * L  # worst case: every chain full

    fn = _frontier_walk_fn(mesh, "shard", sm, R_CAP, L)
    import jax.numpy as jnp

    b = N // NDEV
    lowered = fn.lower(
        jnp.zeros((N, N, L), jnp.float32),      # inv (sharded over chains)
        jnp.zeros((N, L), jnp.int32),           # rows_by
        jnp.zeros((e, N), jnp.int32),           # fd (replicated)
        jnp.zeros((e, N), jnp.int32),           # la (replicated)
        jnp.zeros((N,), jnp.int32),             # x0
    )
    hlo = lowered.compile().as_text()

    # every all-gather in the compiled module, with its RESULT shape
    # (HLO prints `%name = s32[256,256]{1,0} all-gather(...)`)
    gathers = re.findall(r"=\s*(\w+)\[([\d,]+)\][^=\n]*\ball-gather\(", hlo)
    per_step = []
    for dtype, shape in gathers:
        elems = 1
        for d in shape.split(","):
            elems *= int(d)
        per_step.append((dtype, shape, elems * DTYPE_BYTES.get(dtype, 4)))

    # the walk is a scan over R_CAP steps: each textual all-gather inside
    # the scan body executes once per step
    step_bytes = sum(b for _, _, b in per_step)
    out = {
        "config": f"N={N} validators, ndev={NDEV}, L={L}, r_cap={R_CAP}",
        "all_gathers_per_step": [
            {"dtype": d, "shape": s, "bytes": by} for d, s, by in per_step
        ],
        "bytes_per_round_step": step_bytes,
        "bytes_per_dispatch": step_bytes * R_CAP,
        # v5e ICI ~ 4x 100 GB/s links per chip; one all-gather moves
        # (ndev-1)/ndev of the result through the ring
        "ici_us_per_step_at_100GBps": round(step_bytes / 100e9 * 1e6, 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
