"""Minimal repro: jax.lax.associative_scan(min, reverse=True) silently
produced corrupt suffix minima on the TPU platform at ~2800-length axes
(observed on v5e, jax 0.9.0) — the reason babble_tpu.tpu.kernels.suffix_min
exists as an explicit log-step shift-doubling instead.

Run on a TPU host:
    python scripts/repro_associative_scan_corruption.py
Healthy output ends with "associative_scan MATCHES numpy" on every shape;
the corruption manifests as a nonzero mismatch count at the larger shapes
(no exception — that is what makes it dangerous).

Pinned by tests/test_frontier.py::test_suffix_min_matches_numpy, which
asserts the replacement (suffix_min) against a numpy oracle at the same
shapes, so the workaround cannot be "simplified" back to associative_scan
without the suite noticing.
"""

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform)
    rng = np.random.default_rng(0)
    for shape in [(4, 5, 128), (4, 5, 1024), (4, 5, 2048), (4, 5, 2801),
                  (4, 5, 4096)]:
        x = rng.integers(0, 3000, size=shape).astype(np.int32)
        got = np.asarray(
            jax.lax.associative_scan(jnp.minimum, jnp.asarray(x),
                                     reverse=True, axis=2)
        )
        want = np.minimum.accumulate(x[:, :, ::-1], axis=2)[:, :, ::-1]
        bad = int((got != want).sum())
        verdict = "MATCHES numpy" if bad == 0 else f"CORRUPT ({bad} cells)"
        print(f"shape {shape}: associative_scan {verdict}")


if __name__ == "__main__":
    main()
