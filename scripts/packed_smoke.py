"""CI smoke for the bit-packed voting layout (ISSUE 17, tpu/packed.py).

Two seeded synthetic grids — one non-lane-aligned (n=7: 25 padding lanes
in play), one crossing a word boundary (n=33) — run through the one-shot
and frontier pipelines in BOTH layouts; every pass output must be
byte-equal. On divergence the PR 11 bisector localizes the earliest
divergent (pass, table, round, witness) cell to stderr before the
nonzero exit, so a CI failure is triage-ready. A few seconds on CPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURES = (
    (7, 160, 9),   # non-lane-aligned validator count
    (33, 320, 4),  # crosses the uint32 word boundary
)


def main() -> int:
    import numpy as np

    # a byte-equality smoke over kernels whose contract violations were
    # baselined instead of fixed proves nothing (ISSUE 18): refuse until
    # the baseline carries no kernel-* entry
    from babble_tpu.analysis.staged import kernel_baseline_entries

    stale = kernel_baseline_entries()
    if stale:
        rules = ", ".join(sorted({e.get("rule", "?") for e in stale}))
        print(
            f"packed_smoke: REFUSING to run — the lint baseline carries "
            f"{len(stale)} kernel-* finding(s) ({rules}). Fix them "
            f"(`babble-tpu lint --staged`) rather than baselining; the "
            f"packed/wide equality gate must only run over "
            f"contract-proven kernels.",
            file=sys.stderr,
        )
        return 2

    from babble_tpu.obs import bisect_pass_results
    from babble_tpu.tpu.engine import run_frontier_passes, run_passes
    from babble_tpu.tpu.grid import synthetic_grid

    failures = 0
    for n, e, seed in FIXTURES:
        grid = synthetic_grid(n, e, seed=seed)
        for name, fn in (("oneshot", run_passes),
                         ("frontier", run_frontier_passes)):
            wide = fn(grid, packed=False)
            packed = fn(grid, packed=True)
            try:
                for f in ("rounds", "witness", "lamport", "fame_decided",
                          "rounds_decided", "received"):
                    np.testing.assert_array_equal(
                        np.asarray(getattr(wide, f)),
                        np.asarray(getattr(packed, f)), f,
                    )
                np.testing.assert_array_equal(
                    np.asarray(wide.famous) & np.asarray(wide.fame_decided),
                    np.asarray(packed.famous)
                    & np.asarray(packed.fame_decided),
                )
                assert int(wide.last_round) == int(packed.last_round)
            except AssertionError as exc:
                failures += 1
                print(
                    f"packed_smoke: DIVERGENCE n={n} seed={seed} {name}: "
                    f"{exc}",
                    file=sys.stderr,
                )
                loc, path = bisect_pass_results(
                    grid, "wide", wide, "packed", packed,
                    label=f"packed-smoke-n{n}-{name}",
                )
                if loc is not None:
                    print(
                        "packed_smoke: bisected to round %s %s/%s cell %s"
                        % (loc["round"], loc["pass"], loc["table"],
                           (loc.get("cell") or "")[:18]),
                        file=sys.stderr,
                    )
                continue
            print(f"packed_smoke: n={n} seed={seed} {name}: "
                  "packed == wide on all pass outputs")
    if failures:
        print(f"packed_smoke: FAIL ({failures} divergent arms)",
              file=sys.stderr)
        return 1
    print("packed_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
