#!/usr/bin/env python3
"""Adversarial-timing soak harness for the fast-sync state machine
(VERDICT r3 #5: the committed repro path for wedge-family bugs).

Two scenarios, both derived from the /tmp instrumented harness that found
round 3's three fast-sync livelocks (unservable anchors, mass-flip
refusals, chain rewinds — commit 57ea9c7):

- ``chained``: three phases ending with a joiner whose ONLY donor is a
  node that itself fast-synced (chained-donor fast-forward: the donor
  serves a section assembled from its own post-reset store).
- ``reattach``: a device-backend node is killed, left behind past the
  sync limit, recycled, and must fast-sync back in and re-attach its
  live device engine under trickle traffic.

On stall: per-node state lines (node state, block index, core-lock
state, work-queue depth, sync errors) plus full faulthandler thread
dumps, repeated over several minutes to show whether the cluster is
wedged or merely slow. A watchdog thread dump fires every 10 minutes
regardless.

Usage:
    python scripts/soak_fastsync.py [chained|reattach|all] [--iters N]
    make soak            # 10 iterations of both scenarios

The reference's analog is demo/watch.sh polling /stats on a long-running
testnet (reference: README.md:270-300); this harness compresses the
adversarial timing (die-offs, recycles, saturation) into a repeatable
in-process scenario instead of waiting for production timing to produce
it.
"""

import argparse
import copy
import faulthandler
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

faulthandler.dump_traceback_later(600, repeat=True, file=sys.stderr)
logging.basicConfig(level=logging.WARNING)

import conftest  # noqa: F401,E402 — forces the virtual CPU platform

from babble_tpu.hashgraph import InmemStore  # noqa: E402
from babble_tpu.net.inmem_transport import InmemTransport  # noqa: E402
from babble_tpu.node.node import Node  # noqa: E402
from babble_tpu.proxy import InmemDummyClient  # noqa: E402


class Stall(Exception):
    pass


def dump_states(nodes, tag):
    print(f"--- {tag} ---", flush=True)
    for i, n in enumerate(nodes):
        try:
            print(
                f"  node{i}: state={n.get_state().name} "
                f"block={n.core.get_last_block_index()} "
                f"app_block={n._app_committed_index} "
                f"core_locked={n.core_lock.locked()} "
                f"commit_q={n.commit_ch.qsize()} sync_err={n.sync_errors} "
                f"bounces={n.fast_forward_bounces}",
                flush=True,
            )
        except Exception as e:  # noqa: BLE001 — a dead node is still a data point
            print(f"  node{i}: <{e}>", flush=True)


def check_spread(nodes, tag, limit=200):
    """Runaway tripwire (VERDICT r4: survivor minting to 33,613 while its
    peers sat at ~361): no live node's chain may run `limit` blocks past
    the slowest live node — consensus needs >2/3 participation, so a
    spread like that means re-minted or fabricated rounds, not speed."""
    idx = [
        n.core.get_last_block_index()
        for n in nodes
        if n is not None and n.get_state().name != "SHUTDOWN"
    ]
    if idx and max(idx) - min(idx) > limit:
        dump_states(nodes, f"runaway[{tag}]")
        raise Stall(f"{tag}: runaway chain spread {idx}")


def watched_wait(nodes, alive, prox, target, budget, tag):
    """bombard_and_wait that converts a timeout into a diagnosed stall."""
    from test_node import bombard_and_wait

    try:
        bombard_and_wait(alive, prox, target_block=target, timeout_s=budget)
        check_spread(nodes, tag)
    except AssertionError as e:
        print(f"STALL[{tag}]: {e}", flush=True)
        dump_states(nodes, "stall")
        faulthandler.dump_traceback(file=sys.stderr)
        for k in range(6):
            time.sleep(30)
            dump_states(nodes, f"post-stall +{30 * (k + 1)}s")
        faulthandler.dump_traceback(file=sys.stderr)
        raise Stall(tag) from e


def scenario_chained():
    """Chained-donor fast-forward under die-off: the final joiner's only
    donor has itself fast-synced."""
    from test_fastsync import build_cluster, make_config
    from test_node import run_nodes, shutdown_nodes

    conf = make_config()
    nodes, proxies, keys, peer_list, participants, transports = build_cluster(
        4, conf
    )
    try:
        # phase 1: 3 nodes run past the sync limit; node 3 joins late
        run_nodes(nodes[:3])
        target = 3
        while True:
            watched_wait(nodes, nodes[:3], proxies[:3], target, 180, "p1-base")
            total = sum(i + 1 for i in nodes[0].core.known_events().values())
            if total > conf.sync_limit + 50:
                break
            target += 1
        nodes[3].run_async(True)
        target = max(n.core.get_last_block_index() for n in nodes[:3]) + 2
        watched_wait(nodes, nodes, proxies, target, 240, "p1-join")

        # phase 2: kill node 2; the rest run past the sync limit again so
        # node 3 (a fast-synced node) accumulates an anchor of its own
        victim_addr = peer_list[2].net_addr
        nodes[2].shutdown()
        transports[2].disconnect_all()
        for t in (transports[0], transports[1], transports[3]):
            t.disconnect(victim_addr)
        alive = [nodes[0], nodes[1], nodes[3]]
        alive_prox = [proxies[0], proxies[1], proxies[3]]
        goal = max(n.core.get_last_block_index() for n in alive) + 3
        while True:
            watched_wait(nodes, alive, alive_prox, goal, 240, "p2")
            total = sum(i + 1 for i in nodes[0].core.known_events().values())
            if total > conf.sync_limit + 50:
                break
            goal += 1
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if nodes[3].core.hg.anchor_block is not None:
                break
            watched_wait(
                nodes, alive, alive_prox,
                max(n.core.get_last_block_index() for n in alive) + 1,
                120, "p2-anchor",
            )
        if nodes[3].core.hg.anchor_block is None:
            raise Stall("p2: node 3 never gained an anchor")

        # phase 3: halt nodes 0/1; recycle node 2 connected ONLY to node 3
        for i in (0, 1):
            nodes[i].shutdown()
            transports[i].disconnect_all()
            transports[3].disconnect(peer_list[i].net_addr)
        trans = InmemTransport(victim_addr, timeout=5.0)
        trans.connect(transports[3].local_addr(), transports[3])
        transports[3].connect(victim_addr, trans)
        transports[2] = trans
        prox = InmemDummyClient()
        store = InmemStore(participants, conf.cache_size)
        node = Node(
            copy.copy(conf), peer_list[2].id, keys[2], participants, store,
            trans, prox,
        )
        node.init()
        nodes[2] = node
        proxies[2] = prox
        node.run_async(True)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if node.core.get_last_block_index() >= 0:
                break
            time.sleep(0.25)
        if node.core.get_last_block_index() < 0:
            print("STALL[p3]: joiner never fast-synced", flush=True)
            dump_states(nodes, "stall")
            faulthandler.dump_traceback(file=sys.stderr)
            raise Stall("p3: chained-donor fast-forward never completed")
    finally:
        shutdown_nodes([n for n in nodes if n is not None])


def scenario_reattach():
    """Device-backend recycle + fast-sync + live-engine re-attach under
    trickle traffic (the test_device_backend reattach scenario, soaked)."""
    from test_device_backend import build_mixed_cluster, make_config
    from test_fastsync import connect_transport
    from test_node import run_nodes, shutdown_nodes

    nodes, proxies, keys, peer_list, participants, transports = (
        build_mixed_cluster(["tpu"] * 4)
    )
    conf = make_config()
    try:
        run_nodes(nodes)
        watched_wait(nodes, nodes, proxies, 2, 180, "base")

        nodes[3].shutdown()
        transports[3].disconnect_all()
        for t in transports[:3]:
            t.disconnect(transports[3].local_addr())
        goal = max(n.core.get_last_block_index() for n in nodes[:3]) + 3
        while True:
            watched_wait(nodes, nodes[:3], proxies[:3], goal, 180, "ahead")
            total = sum(i + 1 for i in nodes[0].core.known_events().values())
            if total > conf.sync_limit + 50:
                break
            goal += 1

        trans = InmemTransport(peer_list[3].net_addr, timeout=5.0)
        connect_transport(transports[:3], trans)
        transports[3] = trans
        prox = InmemDummyClient()
        node = Node(
            conf, peer_list[3].id, keys[3], participants,
            InmemStore(participants, conf.cache_size), trans, prox,
        )
        node.init()
        nodes[3] = node
        proxies[3] = prox
        node.run_async(True)

        import random

        deadline = time.monotonic() + 300
        target = goal + 5
        while time.monotonic() < deadline:
            if min(n.core.get_last_block_index() for n in nodes) >= target:
                break
            proxies[random.randrange(3)].submit_tx(
                f"soak-{time.monotonic()}".encode()
            )
            time.sleep(0.1)
        if min(n.core.get_last_block_index() for n in nodes) < target:
            print("STALL[reattach]: joiner failed to catch up", flush=True)
            dump_states(nodes, "stall")
            faulthandler.dump_traceback(file=sys.stderr)
            raise Stall("reattach: joiner failed to catch up")

        # the engine must re-attach with traffic flowing
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if getattr(node.core.hg, "_live_device_engine", None) is not None:
                break
            target += 1
            watched_wait(nodes, nodes, proxies, target, 240, "reattach-poll")
        if getattr(node.core.hg, "_live_device_engine", None) is None:
            raise Stall("reattach: live engine never re-attached")
    finally:
        shutdown_nodes(nodes)


def scenario_snapshot_race():
    """Fast-forward serving under a SATURATED commit channel (VERDICT r4
    #2): every donor's app commit is artificially slowed so the hashgraph
    anchor runs far ahead of the app's committed height. Before the
    app-height anchor cap, the donor's get_snapshot raced the commit loop
    ("snapshot N not found") and starved every joiner; with the cap the
    join must succeed by construction."""
    from test_fastsync import build_cluster, make_config
    from test_node import run_nodes, shutdown_nodes

    conf = make_config()
    nodes, proxies, keys, peer_list, participants, transports = build_cluster(
        4, conf
    )

    def slow_commit(state, dt=0.05):
        orig = state.commit_handler

        def commit(block):
            time.sleep(dt)
            return orig(block)

        state.commit_handler = commit

    for prox in proxies[:3]:
        slow_commit(prox.state)
    try:
        run_nodes(nodes[:3])
        target = 2
        while True:
            watched_wait(nodes[:3], nodes[:3], proxies[:3], target, 240, "sat-base")
            total = sum(i + 1 for i in nodes[0].core.known_events().values())
            if total > conf.sync_limit + 50:
                break
            target += 1
        # the race window must be OPEN when the joiner arrives: hashgraph
        # anchors ahead of the app's committed height on some donor
        lag_open = any(
            n.core.hg.anchor_block is not None
            and n.core.hg.anchor_block > n._app_committed_index
            for n in nodes[:3]
        )
        print(f"  snapshot-race window open: {lag_open}", flush=True)

        nodes[3].run_async(True)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if nodes[3].core.get_last_block_index() >= 0:
                break
            time.sleep(0.25)
        if nodes[3].core.get_last_block_index() < 0:
            print("STALL[snapshot-race]: joiner never fast-synced", flush=True)
            dump_states(nodes, "stall")
            faulthandler.dump_traceback(file=sys.stderr)
            raise Stall("snapshot-race: joiner starved by commit-lagged donors")
        check_spread(nodes, "snapshot-race")
    finally:
        shutdown_nodes(nodes)


SCENARIOS = {
    "chained": scenario_chained,
    "reattach": scenario_reattach,
    "snapshot-race": scenario_snapshot_race,
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("scenario", nargs="?", default="all",
                    choices=[*SCENARIOS, "all"])
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    for i in range(args.iters):
        for name in names:
            t0 = time.monotonic()
            try:
                SCENARIOS[name]()
            except Stall as e:
                print(f"iter {i} {name}: STALLED after "
                      f"{time.monotonic() - t0:.0f}s — {e}", flush=True)
                return 1
            print(f"iter {i} {name}: clean in {time.monotonic() - t0:.0f}s",
                  flush=True)
    print("soak complete: all iterations clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
