#!/usr/bin/env bash
# Static-analysis CI gate (docs/analysis.md) — deliberately SEPARATE from
# the tier-1 pytest gate so a lint finding never masks (or is masked by)
# a test regression.
#
# Tier 1 (hard, stdlib-only): the consensus-grade analyzers in
#   babble_tpu/analysis/ — determinism lint, lock-discipline checker,
#   JAX staging audit, staged-kernel contract checker (--staged:
#   kernel-* rules over tpu/), observability lint (obs-* rules: metric
#   names must be static literals, label sets declared literally). New
#   findings (not in the checked-in baseline) fail the build, and the
#   gate must finish inside a 30s wall-time budget.
# Tier 2 (advisory): ruff/mypy per the pyproject.toml baseline config,
#   run only where installed (pip install -e '.[lint]'); absence is a
#   skip, not a failure, because the node image ships without them.
set -u
cd "$(dirname "$0")/.."

rc=0

echo "== babble-tpu lint (hard gate) =="
lint_start=$(date +%s)
python -m babble_tpu lint --staged || rc=1
lint_elapsed=$(( $(date +%s) - lint_start ))
if [ "$lint_elapsed" -ge 30 ]; then
    echo "ci_lint: FAIL — lint gate took ${lint_elapsed}s, over the 30s wall-time budget"
    rc=1
fi

# Dynamic concurrency certification (hard gate, ISSUE 12): a seeded sim
# sweep under lockset/lock-order instrumentation. Seeds are env-tunable:
# the full `make race` acceptance sweep runs 50; CI defaults to a small
# smoke so the gate stays fast (the detectors are deterministic per seed).
echo "== babble-tpu race certification (hard gate) =="
python -m babble_tpu lint --races --race-seeds "${BABBLE_RACE_SEEDS:-5}" || rc=1

# Divergence-bisector self-test (hard gate, ISSUE 14): per seed, a clean
# synthetic provenance stream pair must localize nothing and a seeded
# single-cell fame flip must localize to exactly the injected
# (pass, table, round, witness) cell. Sub-second and jax-free.
echo "== babble-tpu bisector smoke (hard gate) =="
python -m babble_tpu explain --smoke "${BABBLE_BISECT_SEEDS:-3}" || rc=1

# Ingress pipeline smoke (hard gate, ISSUE 16): a short-horizon open-loop
# run through the submit pipeline — SLO-gated p50/p99, shed/dedup counters,
# and the batched-vs-single-tx digest-equality check. Deterministic from
# the seed, a few seconds of wall clock.
echo "== babble-tpu ingest smoke (hard gate) =="
JAX_PLATFORMS=cpu python bench_ingest.py --smoke --slo || rc=1

# Packed-voting smoke (hard gate, ISSUE 17): two seeded grids through the
# one-shot + frontier pipelines in both voting-table layouts — uint32
# lane packing must be byte-equal to wide on every pass output; a
# divergence is bisected to its exact cell before the nonzero exit.
echo "== babble-tpu packed-voting smoke (hard gate) =="
JAX_PLATFORMS=cpu python scripts/packed_smoke.py || rc=1

# Status-dashboard smoke (hard gate, ISSUE 20): a 3-node in-process
# cluster gossips health digests to convergence, then GET /debug/cluster
# + /health/digest are served over real TCP and the `babble-tpu status`
# renderer must show the converged fleet at zero skew with no partition
# suspicion. A few seconds of wall clock.
echo "== status smoke (hard gate) =="
JAX_PLATFORMS=cpu python scripts/status_smoke.py || rc=1

echo "== ruff (advisory) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check babble_tpu/ || echo "ci_lint: ruff reported findings (advisory)"
else
    echo "ci_lint: ruff not installed — skipped"
fi

echo "== mypy (advisory) =="
if command -v mypy >/dev/null 2>&1; then
    mypy --config-file pyproject.toml || echo "ci_lint: mypy reported findings (advisory)"
else
    echo "ci_lint: mypy not installed — skipped"
fi

if [ "$rc" -ne 0 ]; then
    echo "ci_lint: FAIL (new static-analysis findings — see above;"
    echo "  fix, waive with a reasoned # <tag>-ok: comment, or baseline"
    echo "  via 'python -m babble_tpu lint --write-baseline')"
else
    echo "ci_lint: PASS"
fi
exit "$rc"
