#!/usr/bin/env bash
# Poll every node's /stats (reference: demo/scripts/watch.sh).
set -euo pipefail
N=${1:-4}
while true; do
  clear 2>/dev/null || true
  for i in $(seq 0 $((N - 1))); do
    echo "--- node$i (127.0.0.1:$((8000 + i))) ---"
    curl -s -m 1 "http://127.0.0.1:$((8000 + i))/stats" || echo "(unreachable)"
    echo
  done
  sleep 1
done
