#!/usr/bin/env bash
# Burst extra transactions at random nodes (reference: demo/scripts/bombard.sh).
set -euo pipefail
N=${1:-4}
COUNT=${2:-200}
exec python3 "$(dirname "$0")/bombard.py" --nodes "$N" --count "$COUNT"
