#!/usr/bin/env python3
"""Fire a burst of transactions at random nodes' proxy listeners
(reference: demo/scripts/bombard.sh). Uses the same app->babble JSON-RPC
verb the socket clients use (Babble.SubmitTx).

    python3 demo/bombard.py --nodes 4 --count 200
"""

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from babble_tpu.proxy.jsonrpc import JSONRPCClient  # noqa: E402
from babble_tpu.utils.codec import b64e  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--count", type=int, default=100)
    p.add_argument("--base-port", type=int, default=1338,
                   help="proxy-listen of node0; node i is base+10*i")
    args = p.parse_args()

    clients = [
        JSONRPCClient(f"127.0.0.1:{args.base_port + 10 * i}", timeout=2.0)
        for i in range(args.nodes)
    ]
    sent = 0
    for k in range(args.count):
        c = random.choice(clients)
        try:
            c.call("Babble.SubmitTx", b64e(f"bombard tx {k}".encode()))
            sent += 1
        except Exception as e:  # noqa: BLE001
            print(f"submit {k} failed: {e}", file=sys.stderr)
    print(f"submitted {sent}/{args.count} transactions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
