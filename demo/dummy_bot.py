#!/usr/bin/env python3
"""Demo app bot: the app side of one node's socket proxy split. Handles
CommitBlock/Snapshot/Restore like the chat client, and (optionally)
submits a steady trickle of transactions so the testnet makes blocks
(the role the reference demo gives its dummy containers + bombard.sh).

    python3 demo/dummy_bot.py --name node0 \
        --client-listen 127.0.0.1:1339 --proxy-connect 127.0.0.1:1338 --rate 5
"""

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from babble_tpu.proxy import DummySocketClient  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--name", default="bot")
    p.add_argument("--client-listen", required=True)
    p.add_argument("--proxy-connect", required=True)
    p.add_argument("--rate", type=float, default=0.0,
                   help="transactions per second to submit (0 = commit-only)")
    args = p.parse_args()

    logging.basicConfig(level=logging.WARNING)
    client = DummySocketClient(
        node_addr=args.proxy_connect,
        bind_addr=args.client_listen,
        logger=logging.getLogger(args.name),
    )

    n = 0
    while True:
        if args.rate > 0:
            try:
                client.submit_tx(f"{args.name} tx {n}".encode())
                n += 1
            except Exception as e:  # noqa: BLE001 — node may still be starting
                print(f"{args.name}: submit failed: {e}", file=sys.stderr)
            time.sleep(1.0 / args.rate)
        else:
            time.sleep(1.0)


if __name__ == "__main__":
    sys.exit(main())
