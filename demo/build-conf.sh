#!/usr/bin/env bash
# Build a localhost testnet configuration: one datadir per node with
# priv_key.pem + a shared peers.json
# (reference: demo/scripts/build-conf.sh — docker IPs become localhost ports).
set -euo pipefail

N=${1:-4}
CONF=${CONF:-/tmp/babble-tpu-demo}
PY=${PY:-python3}
REPO="$(cd "$(dirname "$0")/.." && pwd)"

rm -rf "$CONF"
mkdir -p "$CONF"

PEERS="["
for i in $(seq 0 $((N - 1))); do
  DATADIR="$CONF/node$i"
  mkdir -p "$DATADIR"
  PUB=$(cd "$REPO" && $PY -m babble_tpu keygen --datadir "$DATADIR" | sed -n 's/^Public Key: //p')
  PORT=$((1337 + i * 10))
  # ADDR_PATTERN overrides the localhost scheme (e.g. 'node%I%:1337' for
  # the docker-compose network, where each container gets a hostname)
  PATTERN=${ADDR_PATTERN:-127.0.0.1:%PORT%}
  ADDR=${PATTERN//%PORT%/$PORT}
  ADDR=${ADDR//%I%/$i}
  [ "$i" -gt 0 ] && PEERS+=","
  PEERS+="{\"NetAddr\":\"$ADDR\",\"PubKeyHex\":\"$PUB\"}"
done
PEERS+="]"

for i in $(seq 0 $((N - 1))); do
  echo "$PEERS" >"$CONF/node$i/peers.json"
done

echo "Configuration for $N nodes written under $CONF"
