#!/usr/bin/env bash
# Launch the localhost testnet built by build-conf.sh: N nodes over the
# socket proxy split, each with a dummy app bot that commits blocks and
# trickles transactions (reference: demo/scripts/run-testnet.sh —
# heartbeat 10ms, timeout 200ms, cache-size 50000).
set -euo pipefail

N=${1:-4}
CONF=${CONF:-/tmp/babble-tpu-demo}
PY=${PY:-python3}
BACKEND=${BACKEND:-cpu}
MESH=${MESH:-0}          # BACKEND=tpu MESH=K shards consensus over K chips
QUEUE_DEPTH=${QUEUE_DEPTH:-4}
BATCH_DEADLINE=${BATCH_DEADLINE:-0}
RATE=${RATE:-5}
REPO="$(cd "$(dirname "$0")/.." && pwd)"

pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup EXIT INT TERM

for i in $(seq 0 $((N - 1))); do
  PORT=$((1337 + i * 10))
  PROXY=$((1338 + i * 10))
  CLIENT=$((1339 + i * 10))
  SERVICE=$((8000 + i))
  # app bot first: the node dials the client at startup
  $PY "$REPO/demo/dummy_bot.py" --name "node$i" \
    --client-listen "127.0.0.1:$CLIENT" --proxy-connect "127.0.0.1:$PROXY" \
    --rate "$RATE" >"$CONF/node$i/bot.log" 2>&1 &
  pids+=($!)
  (cd "$REPO" && exec $PY -m babble_tpu run \
    --datadir "$CONF/node$i" \
    --listen "127.0.0.1:$PORT" \
    --proxy-listen "127.0.0.1:$PROXY" \
    --client-connect "127.0.0.1:$CLIENT" \
    --service-listen "127.0.0.1:$SERVICE" \
    --heartbeat 0.01 --timeout 0.2 --cache-size 50000 --sync-limit 500 \
    --consensus-backend "$BACKEND" \
    --mesh-devices "$MESH" \
    --dispatch-queue-depth "$QUEUE_DEPTH" \
    --dispatch-batch-deadline "$BATCH_DEADLINE" \
    --log warn) >"$CONF/node$i/log" 2>&1 &
  pids+=($!)
done

echo "testnet up: nodes on 1337/1347/..., /stats on http://127.0.0.1:800{0..$((N - 1))}"
echo "Ctrl-C to stop; logs under $CONF/node*/log"
wait
