# babble-tpu build/dev targets (reference: makefile — glide/go build becomes
# pytest/demo orchestration; there is nothing to compile).

PY ?= python3
N ?= 4

.PHONY: test lint race status-smoke bench bench-mesh bench-ingest bench-packed trend soak dist wheel-proof demo-conf demo demo-watch demo-bombard multichip version

test:
	$(PY) -m pytest tests/ -q

# concurrency certification (ISSUE 12, docs/analysis.md): the full tier-1
# suite under lockset/lock-order instrumentation (BABBLE_RACE_CERTIFY=1
# wraps the session in analysis/lockruntime.certify()), then the 50-seed
# sim sweep under the same instrumentation via the lint CLI. Zero race
# candidates and an acyclic lock graph are the acceptance bar.
RACE_SEEDS ?= 50
race:
	BABBLE_RACE_CERTIFY=1 $(PY) -m pytest tests/ -q -m 'not slow'
	$(PY) -m babble_tpu lint --races --race-seeds $(RACE_SEEDS)

# consensus-grade static analysis (babble_tpu/analysis/, docs/analysis.md):
# determinism lint + lock-discipline checker + JAX staging audit +
# staged-kernel contract checker (--staged: kernel-* rules over tpu/) +
# observability lint (obs-*: static metric names, literal label sets).
# Hard gate, with a hard <30s wall-time budget so it stays cheap enough
# to run on every edit. ruff/mypy are an advisory second tier — they run
# only where installed (pip install -e '.[lint]'); the container image
# does not ship them.
lint:
	@start=$$(date +%s); \
	$(PY) -m babble_tpu lint --staged || exit 1; \
	elapsed=$$(( $$(date +%s) - start )); \
	if [ "$$elapsed" -ge 30 ]; then \
		echo "lint: FAIL — hard gate took $${elapsed}s, over the 30s wall-time budget"; \
		exit 1; \
	fi
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check babble_tpu/; \
	else \
		echo "lint: ruff not installed — skipping advisory tier"; \
	fi
	@if command -v mypy >/dev/null 2>&1; then \
		mypy --config-file pyproject.toml || true; \
	else \
		echo "lint: mypy not installed — skipping advisory tier"; \
	fi

# cluster health plane end-to-end (ISSUE 20, docs/observability.md):
# 3-node in-proc cluster -> digest piggyback over live gossip -> GET
# /debug/cluster + /health/digest over TCP -> the `babble-tpu status`
# renderer must show 3 nodes at zero skew, full agreement, no suspicion
status-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/status_smoke.py

bench:
	$(PY) bench.py

# validator sweep across dispatch disciplines (round-batched mesh rung);
# archived as BENCH_MESH_r*.json, gated by the trend series below
bench-mesh:
	$(PY) bench_mesh_scale.py --slo

# bit-packed voting-table bench (ISSUE 17): the same validator sweep with
# the packed discipline as the headline — wide-vs-packed byte-equality
# gate per rung and the packed-speedup SLO floor from 1024 validators up
# (the floor objective arms only when the sweep reaches --slo-packed-n;
# the default CPU sweep stays under it because the WIDE baseline at 1024
# already exhausts host memory on the 8-device virtual mesh — run
# `--validators 64,256,1024` on real hardware to arm the crossover gate);
# archived as BENCH_PACKED_r*.json, gated by the trend series below
bench-packed:
	$(PY) bench_mesh_scale.py --headline packed --validators 8,64,128 --slo

# open-loop ingest bench (ISSUE 16): offered load through the ingress
# pipeline on the sim fabric, gated on submit->commit p50/p99 and on
# batched-vs-single-tx digest equality; archived as BENCH_INGEST_r*.json
bench-ingest:
	$(PY) bench_ingest.py --slo

# cross-round perf-trend gate over the archived BENCH_r*/MULTICHIP_r*
# artifacts: fails on a >10% regression against the best prior round
trend:
	$(PY) scripts/bench_trend.py

# adversarial-timing fast-sync soak (VERDICT r3 #5): chained-donor
# fast-forward + device-engine reattach scenarios with stall diagnostics
soak:
	$(PY) scripts/soak_fastsync.py all --iters 10

# wheel build (reference: makefile:5-21 / scripts/dist.sh); docker/
# installs from dist/
dist:
	$(PY) -m pip wheel --no-deps --no-build-isolation -w dist .

# install-and-run from the wheel in a clean venv: 2 nodes + bots from the
# console script, committed byte-identical blocks over HTTP (VERDICT r4 #9)
wheel-proof:
	./scripts/prove_wheel.sh

multichip:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

demo-conf:
	./demo/build-conf.sh $(N)

demo: demo-conf
	./demo/run-testnet.sh $(N)

demo-watch:
	./demo/watch.sh $(N)

demo-bombard:
	./demo/bombard.sh $(N)

version:
	$(PY) -m babble_tpu version
