"""Validator set (reference: src/peers/peers.go:11-16,120-150).

Sorted by ID; the sorted position is the peer's dense coordinate (the column
index of every (events x validators) grid on device).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .peer import Peer


class Peers:
    def __init__(self):
        self._lock = threading.RLock()
        self.sorted: List[Peer] = []  # guarded-by: _lock
        self.by_pub_key: Dict[str, Peer] = {}  # guarded-by: _lock
        self.by_id: Dict[int, Peer] = {}  # guarded-by: _lock

    @classmethod
    def from_slice(cls, source: List[Peer]) -> "Peers":
        # fresh object, not yet shared — lock-free mutation is safe here
        peers = cls()
        for p in source:
            peers._add_raw(p)
        peers._sort()
        return peers

    def _add_raw(self, peer: Peer) -> None:  # requires-lock: _lock
        if peer.id == 0:
            peer.compute_id()
        self.by_pub_key[peer.pub_key_hex] = peer
        self.by_id[peer.id] = peer

    def _sort(self) -> None:  # requires-lock: _lock
        self.sorted = sorted(self.by_pub_key.values(), key=lambda p: p.id)

    def add_peer(self, peer: Peer) -> None:
        with self._lock:
            self._add_raw(peer)
            self._sort()

    def remove_peer(self, peer: Optional[Peer]) -> None:
        with self._lock:
            if peer is None or peer.pub_key_hex not in self.by_pub_key:
                return
            del self.by_pub_key[peer.pub_key_hex]
            del self.by_id[peer.id]
            self._sort()

    def remove_peer_by_pub_key(self, pub_key: str) -> None:
        # unguarded-ok: lookup is re-validated by remove_peer under _lock
        self.remove_peer(self.by_pub_key.get(pub_key))

    def remove_peer_by_id(self, pid: int) -> None:
        # unguarded-ok: lookup is re-validated by remove_peer under _lock
        self.remove_peer(self.by_id.get(pid))

    def to_peer_slice(self) -> List[Peer]:
        # unguarded-ok: _sort rebinds a fresh list; readers see old or new
        return self.sorted

    def to_pub_key_slice(self) -> List[str]:
        # unguarded-ok: _sort rebinds a fresh list; readers see old or new
        return [p.pub_key_hex for p in self.sorted]

    def to_id_slice(self) -> List[int]:
        # unguarded-ok: _sort rebinds a fresh list; readers see old or new
        return [p.id for p in self.sorted]

    def __len__(self) -> int:
        # unguarded-ok: len() on a dict is a single atomic read
        return len(self.by_pub_key)

    def __iter__(self):
        # unguarded-ok: _sort rebinds a fresh list; readers see old or new
        return iter(self.sorted)
