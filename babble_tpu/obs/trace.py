"""Bounded ring-buffer span tracer with Chrome trace-event export.

Spans are recorded into a fixed-capacity ring: constant memory, O(1)
record, oldest spans silently dropped once the ring wraps. The export
shape is the Chrome trace-event JSON format (complete "X" events), so
`GET /debug/trace` output loads directly in Perfetto / chrome://tracing.

Timestamps come exclusively from the injected Clock seam — the tracer
itself never touches wall time, so it is byte-deterministic under the
simulator's SimClock.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..common.clock import Clock, SYSTEM_CLOCK

DEFAULT_SPAN_CAPACITY = 4096


class Span:
    __slots__ = ("name", "start", "duration", "attrs", "thread")

    def __init__(self, name: str, start: float, duration: float,
                 attrs: Optional[dict], thread: str):
        self.name = name
        self.start = start
        self.duration = duration
        self.attrs = attrs
        self.thread = thread


class SpanTracer:
    """Fixed-capacity span ring. Thread-safe; wraps by overwriting."""

    def __init__(self, clock: Optional[Clock] = None,
                 capacity: int = DEFAULT_SPAN_CAPACITY):
        if capacity < 1:
            raise ValueError("span capacity must be >= 1")
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: List[Optional[Span]] = [None] * capacity  # guarded-by: _lock
        self._next = 0  # guarded-by: _lock — total spans ever recorded
        self.dropped = 0  # guarded-by: _lock — overwritten by ring wrap

    def record(self, name: str, start: float, duration: float,
               attrs: Optional[dict] = None) -> None:
        sp = Span(name, start, duration, attrs,
                  threading.current_thread().name)
        with self._lock:
            if self._next >= self.capacity and \
                    self._ring[self._next % self.capacity] is not None:
                self.dropped += 1
            self._ring[self._next % self.capacity] = sp
            self._next += 1

    @contextmanager
    def span(self, name: str, histogram=None, **attrs):
        """Time a block: one clock-read pair records a span and (if given)
        feeds the same duration into `histogram.observe`."""
        start = self.clock.monotonic()
        try:
            yield
        finally:
            duration = self.clock.monotonic() - start
            self.record(name, start, duration, attrs or None)
            if histogram is not None:
                histogram.observe(duration)

    def spans(self) -> List[Span]:
        """Retained spans, oldest first."""
        with self._lock:
            if self._next <= self.capacity:
                return [s for s in self._ring[: self._next] if s is not None]
            head = self._next % self.capacity
            return [s for s in self._ring[head:] + self._ring[:head]
                    if s is not None]

    def to_chrome_trace(self, pid: int = 0,
                        trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON: complete ("X") events, µs timestamps,
        plus thread_name metadata so Perfetto shows real thread names.
        `trace_id` narrows the export to spans carrying that causal-trace
        id in their attrs (the /debug/trace?trace_id= filter)."""
        spans = self.spans()
        if trace_id is not None:
            spans = [sp for sp in spans
                     if sp.attrs and sp.attrs.get("trace") == trace_id]
        tids: Dict[str, int] = {}
        events: List[dict] = []
        for sp in spans:
            tid = tids.setdefault(sp.thread, len(tids))
            ev = {
                "name": sp.name,
                "ph": "X",
                "ts": round(sp.start * 1e6, 3),
                "dur": round(sp.duration * 1e6, 3),
                "pid": pid,
                "tid": tid,
            }
            if sp.attrs:
                ev["args"] = sp.attrs
            events.append(ev)
        meta = [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": thread}}
            for thread, tid in sorted(tids.items(), key=lambda kv: kv[1])
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
