"""Device-time ledger (ISSUE 19): per-pass kernel cost attribution,
compile/retrace accounting, and the unified host+device timeline.

The obs stack used to stop at the host boundary: the engine rungs
recorded one opaque `babble_device_run_seconds` per dispatch. This
module decomposes that wall time into a typed cost ledger — one cell
per (rung, pass, layout, component) with component one of

    stage     host restage work before the dispatch
    compile   trace+lower+backend-compile time attributed to a seam call
    run       device execution time of one staged kernel-contract entry
    fetch     blocked device->host result wait
    integrate host write-back of pass results

— by wrapping every host call into a staged callable in a *seam*
(`ledger_call` / `DeviceLedger.call`). The 23 `# kernel-contract:`
entry points (analysis/staged.py, PR 18) map onto seams via
`ENTRY_INFO`: entries whose trace lives inside another staged body
(e.g. `_divide_rounds` inside `consensus_pipeline`) carry a
`covered_by` pointer instead of their own seam, so ledger coverage of
the contract surface is total and testable (tests/test_devledger.py).

Determinism contract: every duration is read through the ledger's
clock policy — the REAL `SystemClock` is read directly; under any
injected virtual clock (the sim) the ledger records 0.0 durations and
never touches the clock object at all, so worker-thread seams
(tpu/dispatch.py's `mesh-dispatch` workers) cannot violate the
"virtual clock is serve-thread-only" discipline and same-seed sim runs
produce byte-identical ledger snapshots. `fingerprint()` joins the
SimCluster determinism contract alongside digest/trace/flightrec.

Compile/retrace accounting hooks `jax.monitoring`: the three
`/jax/core/compile/*` event-duration events fire per compilation and
are silent on executable-cache hits. A seam keeps a per-entry mirror
of the abstract call signature (shapes/dtypes/statics/layout); compile
events on a NEW signature are legitimate compiles
(`babble_kernel_compiles_total{entry}`), compile/trace events on a
signature already seen are silent retraces
(`babble_kernel_retraces_total{entry}`) — the dynamic truth backing
the static `kernel-retrace-hazard` lint rule. Seconds attributed to
compilation come from the injected-clock delta around the call (0.0 in
the sim), never from the monitoring payload, preserving determinism.

The static cost-model sidecar estimates bytes moved per entry exactly
from the abstract signature (deterministic, in the snapshot) and
lazily probes XLA's `lower().compile().cost_analysis()` for FLOPs on
the real clock only (`efficiency()`; excluded from the fingerprint).

Entry/rung/pass names on ledger receivers are static string literals,
enforced by the `obs-ledger-static-name` lint rule (analysis/obs.py).
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from ..common.clock import SystemClock

# lifecycle components a dispatch's wall time decomposes into
COMPONENTS = ("stage", "compile", "run", "fetch", "integrate", "sync")

# bounded ring of recent seam events feeding the /debug/timeline device
# lanes (the cells above are cumulative; the ring is the time-ordered view)
TIMELINE_CAPACITY = 2048

# ---------------------------------------------------------------------------
# kernel-contract entry registry
# ---------------------------------------------------------------------------
#
# Every `# kernel-contract:` entry point (analysis/staged.py) maps to
# (default rung, pass name, covered_by). `covered_by` names the seam
# whose traced body contains this entry — those entries execute inside
# another staged callable and cannot carry their own host-side timing
# seam; their cost is attributed to the covering entry's pass.
# tests/test_devledger.py asserts this table matches the parsed
# contract surface exactly, so a new contract without a ledger decision
# fails tests, not silently drops out of attribution.
ENTRY_INFO: Dict[str, Tuple[str, str, Optional[str]]] = {
    # tpu/kernels.py — fused level-scan pipeline (one-shot rung)
    "consensus_pipeline": ("oneshot", "pipeline", None),
    "_divide_rounds": ("oneshot", "rounds", "consensus_pipeline"),
    "_decide_fame": ("oneshot", "fame", "consensus_pipeline"),
    "_decide_round_received": ("oneshot", "received", "consensus_pipeline"),
    # tpu/frontier.py — round-frontier pipeline
    "build_inv": ("frontier", "inv", None),
    "_frontier_rounds": ("frontier", "walk", "frontier_pipeline"),
    "frontier_pipeline": ("frontier", "pipeline", None),
    # tpu/frontier_live.py — frontier train steps
    "_decide": ("frontier_live", "decide", "frontier_train_step"),
    "frontier_train_step": ("frontier_live", "train", None),
    "frontier_multi_train": ("frontier_live", "multi_train", None),
    # tpu/incremental.py — resident live-engine steps
    "_step_full": ("incremental", "step", None),
    "multi_step": ("incremental", "multi_step", None),
    "train_step": ("incremental", "train", None),
    "multi_train": ("incremental", "multi_train", None),
    # tpu/doubling.py — log-diameter cold path
    "_closure_la": ("doubling", "closure", None),
    "_walk_chunk": ("doubling", "walk", None),
    "_fame_received": ("doubling", "fame_received", None),
    "_lamport_levels_scan": ("doubling", "levels", None),
    # tpu/live.py — packed result fetch program
    "_pack_results": ("live", "pack", None),
    # tpu/sharded.py — mesh-partitioned stages
    "local_fame": ("sharded", "fame", None),
    "local_received": ("sharded", "received", None),
    "_fame_tables": ("sharded", "fame_tables", None),
    "local_walk": ("sharded", "walk", None),
}


def seam_entries() -> List[str]:
    """Entries that carry their own host-side timing seam."""
    return sorted(e for e, (_, _, cov) in ENTRY_INFO.items() if cov is None)


def covered_entries() -> Dict[str, str]:
    """{covered entry: covering seam} for contract entries whose trace
    lives inside another staged body."""
    return {
        e: cov for e, (_, _, cov) in ENTRY_INFO.items() if cov is not None
    }


# ---------------------------------------------------------------------------
# jax.monitoring hook — process-wide, armed only inside seams
# ---------------------------------------------------------------------------

# thread-local stack of per-seam accumulators; the listener is a no-op
# on threads with an empty stack (and before the first ledger exists)
_MON = threading.local()

_LISTENER_LOCK = threading.Lock()
_LISTENER_REGISTERED = False

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_jax_event(name: str, secs: float, **_kw) -> None:
    stack = getattr(_MON, "stack", None)
    if not stack:
        return
    acc = stack[-1]
    if name == _TRACE_EVENT:
        acc["traces"] += 1
    elif name == _COMPILE_EVENT:
        acc["compiles"] += 1


def _ensure_listener() -> None:
    global _LISTENER_REGISTERED
    if _LISTENER_REGISTERED:
        return
    with _LISTENER_LOCK:
        if _LISTENER_REGISTERED:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_jax_event)
        except Exception:  # noqa: BLE001 — jax absent/old: counting degrades
            pass
        _LISTENER_REGISTERED = True


def _monitor_begin() -> dict:
    stack = getattr(_MON, "stack", None)
    if stack is None:
        stack = _MON.stack = []
    acc = {"traces": 0, "compiles": 0}
    stack.append(acc)
    return acc


def _monitor_end(acc: dict) -> Tuple[int, int]:
    stack = getattr(_MON, "stack", None)
    if stack and stack[-1] is acc:
        stack.pop()
        # nested seams: bubble the inner events up so the outer seam's
        # view of "did anything compile under me" stays complete
        if stack:
            stack[-1]["traces"] += acc["traces"]
            stack[-1]["compiles"] += acc["compiles"]
    return acc["compiles"], acc["traces"]


# ---------------------------------------------------------------------------
# ambient activation context (rung + layout, per thread)
# ---------------------------------------------------------------------------

_TL = threading.local()


class _Ctx:
    __slots__ = ("ledger", "rung", "layout", "seam_seconds")

    def __init__(self, ledger: "DeviceLedger", rung: str, layout: str):
        self.ledger = ledger
        self.rung = rung
        self.layout = layout
        # wall seconds the seams below this activation already accounted
        # for; activate(measure_sync=True) subtracts it from the block's
        # total wall time to expose the host-sync residual
        self.seam_seconds = 0.0


def active_ledger() -> Optional["DeviceLedger"]:
    ctx = getattr(_TL, "ctx", None)
    return ctx.ledger if ctx is not None else None


def ledger_call(entry: str, fn, *args, **kwargs):
    """Module-level seam for call sites without an obs handle (deep in
    tpu/): times `fn(*args, **kwargs)` into the thread's active ledger,
    or passes straight through when none is active. `entry` must be a
    static literal (obs-ledger-static-name)."""
    ctx = getattr(_TL, "ctx", None)
    if ctx is None:
        return fn(*args, **kwargs)
    return ctx.ledger.call(entry, fn, *args, **kwargs)  # obs-ok: delegate, entry checked at ledger_call sites


def _sig_of(value) -> Any:
    shape = getattr(value, "shape", None)
    if shape is not None:
        return ("a", tuple(shape), str(getattr(value, "dtype", "?")))
    if isinstance(value, (int, float, bool, str, bytes, type(None))):
        return value
    return type(value).__name__


def _abstract_sig(args, kwargs) -> Tuple:
    return (
        tuple(_sig_of(a) for a in args),
        tuple(sorted((k, _sig_of(v)) for k, v in kwargs.items())),
    )


def _nbytes(value) -> int:
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(getattr(dtype, "itemsize", 4))


def _tree_bytes(value) -> int:
    if isinstance(value, (tuple, list)):
        return sum(_tree_bytes(v) for v in value)
    if hasattr(value, "_fields"):  # NamedTuple results (PassResults etc.)
        return sum(_tree_bytes(getattr(value, f)) for f in value._fields)
    return _nbytes(value)


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


class DeviceLedger:
    """Per-node device-time cost ledger.

    Cells are cumulative [calls, seconds] keyed by
    (rung, pass, layout, component); per-entry stats carry the
    compile/retrace accounting and the byte-exact cost sidecar. All
    mutation happens under one small lock — seams run on the serve
    thread AND on dispatch workers."""

    def __init__(self, obs):
        self.obs = obs
        self.clock = obs.clock
        # clock policy: only the real wall clock is ever read. Any
        # injected virtual clock (sim) yields 0.0 durations WITHOUT a
        # clock read, keeping worker-thread seams off the SimClock and
        # same-seed snapshots byte-identical.
        self._real = isinstance(self.clock, SystemClock)
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, str, str, str], List[float]] = {}  # guarded-by: _lock
        self._entries: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock
        self._seen: Dict[str, set] = {}  # guarded-by: _lock
        # unguarded-ok: write-once memo keyed by entry; a racing double
        # probe writes the same deterministic cost doc twice
        self._cost: Dict[str, Optional[dict]] = {}
        self._ring: deque = deque(maxlen=TIMELINE_CAPACITY)  # guarded-by: _lock
        self._m_pass = obs.histogram(
            "babble_kernel_pass_seconds",
            "Device-time ledger: seconds per kernel pass / lifecycle "
            "component, by engine rung and voting-table layout",
            labels=("rung", "pass", "layout"),
        )
        self._c_compiles = obs.counter(
            "babble_kernel_compiles_total",
            "Seam calls that compiled a new executable for a new abstract "
            "signature, per kernel-contract entry point",
            labels=("entry",),
        )
        self._c_retraces = obs.counter(
            "babble_kernel_retraces_total",
            "Seam calls that re-traced an abstract signature already "
            "seen (a silent retrace — the dynamic kernel-retrace-hazard)",
            labels=("entry",),
        )
        self._h_compile = obs.histogram(
            "babble_kernel_compile_seconds",
            "Wall seconds of seam calls that compiled, per entry point",
            labels=("entry",),
        )
        _ensure_listener()

    # -- clock policy ------------------------------------------------------

    def now(self) -> float:
        return self.clock.monotonic() if self._real else 0.0

    # -- activation --------------------------------------------------------

    @contextmanager
    def activate(self, rung: str, layout: str = "wide",
                 measure_sync: bool = False):
        """Bind this ledger + (rung, layout) to the current thread so
        `ledger_call` seams below this frame attribute to it. The rung
        name must be a static literal (obs-ledger-static-name).

        With `measure_sync=True` the activation also times the whole
        block and books the residual — wall seconds NOT accounted for by
        the seams inside it — under the `sync` component. On an async
        dispatch rung that residual is where the device compute actually
        completes: each seam returns at dispatch, and the deferred work
        is paid at the unseamed host syncs (np.asarray fetches) between
        passes, so per-pass run cells alone under-count the blocked wall
        time. run + compile + sync covers it."""
        prev = getattr(_TL, "ctx", None)
        ctx = _Ctx(self, rung, layout)
        _TL.ctx = ctx
        t0 = self.now() if measure_sync else 0.0
        try:
            yield self
        finally:
            _TL.ctx = prev
            if measure_sync:
                residual = max(0.0, self.now() - t0 - ctx.seam_seconds)
                self.component(rung, "sync", residual, layout=layout)

    # -- the seam ----------------------------------------------------------

    def call(self, entry: str, fn, *args, **kwargs):
        """Time one host call into a staged callable and attribute it.

        Duration goes to the entry's (rung, pass, layout) cell — under
        the `compile` component when jax compiled during the call, else
        under `run`. Compile events on a signature this ledger has seen
        before count as a retrace, not a compile."""
        info = ENTRY_INFO.get(entry)
        pass_name = info[1] if info else entry
        ctx = getattr(_TL, "ctx", None)
        if ctx is not None and ctx.ledger is self:
            rung, layout = ctx.rung, ctx.layout
        else:
            rung = info[0] if info else "unknown"
            layout = "wide"
        sig = (layout,) + _abstract_sig(args, kwargs)
        acc = _monitor_begin()
        t0 = self.now()
        try:
            out = fn(*args, **kwargs)
        finally:
            compiles, traces = _monitor_end(acc)
        dt = self.now() - t0
        if ctx is not None and ctx.ledger is self:
            ctx.seam_seconds += dt  # thread-local; no lock needed
        bytes_in = sum(_nbytes(a) for a in args)
        bytes_out = _tree_bytes(out)
        with self._lock:
            seen = self._seen.setdefault(entry, set())
            fresh = sig not in seen
            seen.add(sig)
            est = self._entries.setdefault(entry, {
                "calls": 0, "seconds": 0.0, "compiles": 0, "retraces": 0,
                "compile_seconds": 0.0, "bytes_in": 0, "bytes_out": 0,
            })
            est["calls"] += 1
            est["seconds"] += dt
            est["bytes_in"] += bytes_in
            est["bytes_out"] += bytes_out
            compiled = compiles > 0 and fresh
            retraced = (compiles > 0 or traces > 0) and not fresh
            if compiled:
                est["compiles"] += 1
            if retraced:
                est["retraces"] += 1
            # the compile COMPONENT is "time spent compiling", which a
            # silent retrace also pays — the counters above keep legit
            # compiles (new signature) and retraces (seen one) apart
            comp = "compile" if compiles > 0 else "run"
            if compiles > 0:
                est["compile_seconds"] += dt
            cell = self._cells.setdefault(
                (rung, pass_name, layout, comp), [0, 0.0]
            )
            cell[0] += 1
            cell[1] += dt
            self._ring.append({
                "entry": entry, "rung": rung, "pass": pass_name,
                "layout": layout, "component": comp, "t0": t0, "dt": dt,
                "compiles": compiles, "traces": traces,
            })
        if compiled:
            self._c_compiles.labels(entry=entry).inc()
            self._h_compile.labels(entry=entry).observe(dt)
        if retraced:
            self._c_retraces.labels(entry=entry).inc()
        self._m_pass.labels(
            rung=rung, layout=layout, **{"pass": pass_name}
        ).observe(dt)
        return out

    # -- lifecycle components ----------------------------------------------

    def component(self, rung: str, component: str, seconds: float,
                  layout: str = "wide", calls: int = 1) -> None:
        """Record host-side lifecycle time (stage/fetch/integrate) for a
        dispatch on `rung`. `rung` and `component` must be static
        literals (obs-ledger-static-name). Callers measure `seconds`
        with the ledger's own clock policy (`now()`), so the sim records
        deterministic zeros."""
        if component not in COMPONENTS:
            raise ValueError(f"unknown ledger component {component!r}")
        with self._lock:
            cell = self._cells.setdefault(
                (rung, "dispatch", layout, component), [0, 0.0]
            )
            cell[0] += calls
            cell[1] += seconds
        self._m_pass.labels(
            rung=rung, **{"pass": component}, layout=layout
        ).observe(seconds)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Canonical ledger document: cumulative cells, per-entry
        compile/retrace stats, and per-(rung, pass) shares of total
        attributed seconds (the trend-attribution input). Deterministic
        under the sim clock policy; feeds `fingerprint()`."""
        with self._lock:
            cells = {
                "/".join(k): [c[0], round(c[1], 9)]
                for k, c in sorted(self._cells.items())
            }
            entries = {
                e: {
                    "calls": st["calls"],
                    "seconds": round(st["seconds"], 9),
                    "compiles": st["compiles"],
                    "retraces": st["retraces"],
                    "compile_seconds": round(st["compile_seconds"], 9),
                    "bytes_in": st["bytes_in"],
                    "bytes_out": st["bytes_out"],
                }
                for e, st in sorted(self._entries.items())
            }
            total = sum(c[1] for c in self._cells.values())
            shares = {}
            for (rung, pass_name, layout, _comp), c in self._cells.items():
                key = f"{rung}/{pass_name}/{layout}"
                shares[key] = shares.get(key, 0.0) + c[1]
            shares = {
                k: round(v / total, 6) if total > 0 else 0.0
                for k, v in sorted(shares.items())
            }
        return {
            "cells": cells,
            "entries": entries,
            "total_seconds": round(total, 9),
            "shares": shares,
        }

    def fingerprint(self) -> str:
        """sha256 of the canonical snapshot — joins the SimCluster
        determinism contract (digest/trace/flightrec/provenance)."""
        doc = json.dumps(self.snapshot(), sort_keys=True,
                         separators=(",", ":"))
        return hashlib.sha256(doc.encode()).hexdigest()

    def entry_stats(self, entry: str) -> Optional[Dict[str, float]]:
        with self._lock:
            st = self._entries.get(entry)
            return dict(st) if st is not None else None

    def recent(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # -- cost-model sidecar -------------------------------------------------

    def probe_cost(self, entry: str, fn, *args, **kwargs) -> Optional[dict]:
        """One-shot XLA cost-analysis probe for `entry` (FLOPs / bytes
        accessed). Runs OUTSIDE the monitoring seam (its trace events
        must not count as retraces) and only on the real clock — probe
        results never enter the fingerprint."""
        if entry in self._cost:
            return self._cost[entry]
        cost: Optional[dict] = None
        if self._real and hasattr(fn, "lower"):
            try:
                ca = fn.lower(*args, **kwargs).compile().cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else None
                if isinstance(ca, dict):
                    cost = {
                        "flops": float(ca.get("flops", 0.0)),
                        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
                    }
            except Exception:  # noqa: BLE001 — backend without cost model
                cost = None
        self._cost[entry] = cost
        return cost

    def efficiency(self) -> Dict[str, Any]:
        """Measured time next to the static cost model, per entry: bytes
        moved per second (exact, from abstract signatures) and FLOPs per
        second where an XLA cost probe ran. The efficiency ratio the
        mesh-scaling work reads before trusting a rung's headline."""
        out: Dict[str, Any] = {}
        with self._lock:
            items = [(e, dict(st)) for e, st in sorted(self._entries.items())]
        for entry, st in items:
            run_s = st["seconds"] - st["compile_seconds"]
            moved = st["bytes_in"] + st["bytes_out"]
            doc: Dict[str, Any] = {
                "calls": st["calls"],
                "run_seconds": round(run_s, 9),
                "bytes_moved": moved,
                "gbytes_per_sec": (
                    round(moved / run_s / 1e9, 3) if run_s > 0 else None
                ),
            }
            cost = self._cost.get(entry)
            if cost:
                doc["flops_est"] = cost["flops"] * st["calls"]
                doc["gflops_per_sec"] = (
                    round(cost["flops"] * st["calls"] / run_s / 1e9, 3)
                    if run_s > 0 else None
                )
            out[entry] = doc
        return out


# ---------------------------------------------------------------------------
# retrace budget gate (queued-mesh benches)
# ---------------------------------------------------------------------------


def retrace_baseline(obs) -> Dict[str, float]:
    """Per-entry retrace counts at warmup time — subtract from a later
    reading to get the steady-state delta the budget gate asserts on."""
    return _retrace_values(obs)


def _retrace_values(obs) -> Dict[str, float]:
    out: Dict[str, float] = {}
    counter = obs.registry.get("babble_kernel_retraces_total")
    if counter is None:
        return out
    for entry in ENTRY_INFO:
        try:
            v = counter.value(entry=entry)
        except Exception:  # noqa: BLE001 — series not materialized yet
            v = 0.0
        if v:
            out[entry] = v
    return out


def retrace_delta(obs, baseline: Dict[str, float]) -> Dict[str, float]:
    """Entries whose retrace counter moved past the warmup baseline.
    Non-empty = the steady-state retrace budget (zero) is blown; the
    caller names the offenders and dumps the flight ring."""
    now = _retrace_values(obs)
    out = {}
    for entry, v in now.items():
        d = v - baseline.get(entry, 0.0)
        if d > 0:
            out[entry] = d
    return out


# ---------------------------------------------------------------------------
# unified host+device timeline (GET /debug/timeline)
# ---------------------------------------------------------------------------

# device lanes start above any real host thread id the span tracer used
_DEVICE_TID_BASE = 1 << 20
_QUEUE_TID = _DEVICE_TID_BASE - 1


def build_timeline(obs, trace_id: Optional[str] = None) -> dict:
    """One Chrome-trace/Perfetto document merging three sources:

    - host lanes: the SpanTracer ring (gossip/serve/integrate spans),
      exactly as `GET /debug/trace` renders them;
    - device pass lanes: the ledger's seam ring, one lane per
      (rung, pass) with compile/retrace annotations per slice;
    - queue lane: `dispatch.enqueue`/`dispatch.integrate` flight
      records as instant events plus a queue-occupancy counter track.

    All timestamps share the node's monotonic clock, so host blocking
    and device execution line up on one axis."""
    doc = obs.tracer.to_chrome_trace(
        pid=getattr(obs, "node_id", 0), trace_id=trace_id,
    )
    events = doc.setdefault("traceEvents", [])
    pid = getattr(obs, "node_id", 0)

    ledger = getattr(obs, "devledger", None)
    if ledger is not None:
        lanes: Dict[Tuple[str, str], int] = {}
        for ev in ledger.recent():
            lane_key = (ev["rung"], ev["pass"])
            tid = lanes.get(lane_key)
            if tid is None:
                tid = _DEVICE_TID_BASE + len(lanes)
                lanes[lane_key] = tid
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid,
                    "args": {"name": f"device:{lane_key[0]}/{lane_key[1]}"},
                })
            events.append({
                "name": f"{ev['entry']}[{ev['layout']}]",
                "cat": "device," + ev["component"],
                "ph": "X",
                "ts": round(ev["t0"] * 1e6, 3),
                "dur": round(ev["dt"] * 1e6, 3),
                "pid": pid, "tid": tid,
                "args": {
                    "component": ev["component"],
                    "compiles": ev["compiles"],
                    "traces": ev["traces"],
                },
            })

    flightrec = getattr(obs, "flightrec", None)
    if flightrec is not None:
        queue_named = False
        for rec in flightrec.records():
            if rec.name not in ("dispatch.enqueue", "dispatch.integrate"):
                continue
            if not queue_named:
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": _QUEUE_TID, "args": {"name": "dispatch-queue"},
                })
                queue_named = True
            ts = round(rec.t * 1e6, 3)
            events.append({
                "name": rec.name, "cat": "dispatch", "ph": "i", "s": "t",
                "ts": ts, "pid": pid, "tid": _QUEUE_TID,
                "args": dict(rec.fields),
            })
            depth = rec.fields.get("depth")
            if depth is not None:
                events.append({
                    "name": "queue_depth", "cat": "dispatch", "ph": "C",
                    "ts": ts, "pid": pid,
                    "args": {"depth": depth},
                })
    return doc
