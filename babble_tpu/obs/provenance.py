"""Consensus decision provenance + first-divergence bisection (ISSUE 14).

Every divergence this repo has found so far was caught as a whole-run
digest mismatch and triaged by hand from flight-ring dumps. This module
turns the sweep from a divergence *detector* into a divergence
*debugger*: a `ProvenanceRecorder` records, per consensus round, the
content of the four voting tables plus *why* each fame decision landed,
and a `DivergenceBisector` diffs two recorders' streams and names the
earliest divergent (pass, table, round, witness) cell.

Capture seams — one per engine family, all host-side:

- the CPU hashgraph oracle hooks its three passes directly
  (divide_rounds / decide_fame at the decision point / the reception
  stamp in decide_round_received), which also captures the decision
  *why*: deciding voter, yay/nay tallies, strongly-seen count, deciding
  step (round diff) and coin-round traversals;
- every device engine (one-shot, doubling cold path, sharded mesh,
  queued dispatch) funnels through `engine.integrate_pass_results`,
  and the live engine through `live._integrate` — both capture from
  the ALREADY-FETCHED host numpy integration buffers, so provenance
  adds zero device work and zero host syncs to the staged paths (the
  jax-staging audit stays clean by construction).

Comparability contract: a table cell is keyed by event hash and holds
an engine-independent value (creator position, fame verdict, received
round, [lamport, *lastAncestors]). Cell writes are last-write-wins and
append nothing when the value is unchanged, so two engines that agree
converge to byte-identical per-round tables (``table_bytes()``) while
the full stream (``stream_bytes()``, which adds the whys and marks)
stays deterministic per backend and joins ``SimCluster.result()``'s
determinism fingerprint next to the flight recorder's.

The recorder is bounded: at most ``round_cap`` rounds are retained,
oldest-settled evicted first, and every eviction is recorded as a
``prov.truncate`` mark — so a stream is always complete or *cleanly*
truncated (``verify_complete_or_truncated()``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..common.clock import Clock, SYSTEM_CLOCK

# retained-round bound: at consensus rates this is minutes of history
# while keeping a full stream document comfortably small
DEFAULT_PROV_ROUND_CAP = 512

# bounded mark list (truncation/capture markers), drop-oldest
MAX_MARKS = 1024

# bisection compares tables in causal pass order within a round:
# DivideRounds assigns lastAncestors/lamport and the witness set, fame
# votes over witnesses, receptions require decided fame
PASS_TABLES: Tuple[Tuple[str, str], ...] = (
    ("divide", "lastAncestors"),
    ("divide", "witness"),
    ("fame", "fame"),
    ("received", "received"),
)
TABLES = tuple(t for _, t in PASS_TABLES)
PASS_OF_TABLE = {t: p for p, t in PASS_TABLES}


class RoundProvenance:
    """Per-round decision record: the four comparable tables plus the
    per-witness *why* metadata (engine-specific, excluded from the
    cross-engine table fingerprint)."""

    __slots__ = ("round", "final", "tables", "why")

    def __init__(self, round_number: int):
        self.round = round_number
        self.final = False
        self.tables: Dict[str, Dict[str, Any]] = {t: {} for t in TABLES}
        self.why: Dict[str, Dict[str, Any]] = {}

    def set_cell(self, table: str, key: str, value: Any) -> bool:
        """Last-write-wins cell write; returns True when the value is new
        or changed (idempotent re-stamps append nothing)."""
        cells = self.tables[table]
        if cells.get(key) == value:
            return False
        cells[key] = value
        return True

    def table_doc(self) -> Dict[str, Any]:
        """Engine-independent comparable content (sorted-key canonical)."""
        return {t: dict(self.tables[t]) for t in TABLES}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "round": self.round,
            "final": self.final,
            "tables": self.table_doc(),
            "why": {h: dict(w) for h, w in self.why.items()},
        }

    def fingerprint(self) -> str:
        """sha256 of the canonical table content — the unit the bisector
        (and the watchdog's stall triage) compares across engines."""
        blob = json.dumps(
            self.table_doc(), sort_keys=True, separators=(",", ":")
        ).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()


class ProvenanceRecorder:
    """Bounded per-node store of RoundProvenance keyed by ABSOLUTE round
    number, with FlightRecorder-style determinism guarantees."""

    def __init__(self, clock: Optional[Clock] = None, node_id: int = 0,
                 round_cap: int = DEFAULT_PROV_ROUND_CAP):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.node_id = node_id
        self.round_cap = max(4, round_cap)
        self._lock = threading.Lock()
        # guarded-by: _lock — round number -> RoundProvenance
        self._rounds: Dict[int, RoundProvenance] = {}
        self._marks: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._mark_seq = 0  # guarded-by: _lock
        self._marks_dropped = 0  # guarded-by: _lock
        self.evicted_rounds = 0  # guarded-by: _lock
        # rounds strictly below this may have been evicted (truncation
        # floor; 0 == nothing evicted, the stream is complete)
        self.evicted_below = 0  # guarded-by: _lock

    # ------------------------------------------------------------------
    # cell capture (engine hooks)
    # ------------------------------------------------------------------

    def _round_locked(self, r: int) -> RoundProvenance:  # requires-lock: _lock
        rp = self._rounds.get(r)
        if rp is None:
            rp = self._rounds[r] = RoundProvenance(r)
            self._evict_locked()
        return rp

    def _evict_locked(self) -> None:  # requires-lock: _lock
        while len(self._rounds) > self.round_cap:
            # oldest-first: settled history goes before the live tail
            oldest = min(self._rounds)
            del self._rounds[oldest]
            self.evicted_rounds += 1
            self.evicted_below = max(self.evicted_below, oldest + 1)
            self._mark_locked("prov.truncate", round=oldest,
                             evicted=self.evicted_rounds)

    def note_event(self, h: str, round_number: int, lamport: int,
                   last_ancestors: Iterable[Any]) -> bool:
        """DivideRounds: event -> round assignment with its lamport stamp
        and lastAncestors row. `last_ancestors` accepts either the host
        coordinate tuples (index, hash) or the grid's int row."""
        la = [
            int(c[0]) if isinstance(c, (tuple, list)) else int(c)
            for c in last_ancestors
        ]
        with self._lock:
            return self._round_locked(round_number).set_cell(
                "lastAncestors", h, [int(lamport)] + la
            )

    def note_witness(self, h: str, round_number: int, creator: int) -> bool:
        """DivideRounds: witness flag (cell value = creator position)."""
        with self._lock:
            return self._round_locked(round_number).set_cell(
                "witness", h, int(creator)
            )

    def note_fame(self, h: str, round_number: int, famous: bool,
                  **why: Any) -> bool:
        """DecideFame: a landed fame verdict. `why` carries the deciding
        context (engine, voter, yays, nays, ss, step, coins, flips) and
        is stored per witness — outside the comparable tables, so
        engines with different levels of introspection still produce
        byte-identical table streams."""
        with self._lock:
            rp = self._round_locked(round_number)
            changed = rp.set_cell("fame", h, bool(famous))
            if changed and why:
                rp.why[h] = {
                    k: v for k, v in sorted(why.items()) if v is not None
                }
            return changed

    def note_received(self, h: str, round_received: int) -> bool:
        """DecideRoundReceived: event h received at round_received."""
        with self._lock:
            return self._round_locked(round_received).set_cell(
                "received", h, int(round_received)
            )

    def settle_round(self, round_number: int) -> None:
        """ProcessDecidedRounds materialized this round into a frame —
        its tables are now part of committed history."""
        with self._lock:
            rp = self._rounds.get(round_number)
            if rp is not None:
                rp.final = True

    # ------------------------------------------------------------------
    # marks (bounded, Clock-timestamped stream annotations)
    # ------------------------------------------------------------------

    def _mark_locked(self, name: str, **fields: Any) -> None:  # requires-lock: _lock
        self._marks.append({
            "seq": self._mark_seq,
            "t": round(self.clock.monotonic(), 9),
            "name": name,
            "fields": fields,
        })
        self._mark_seq += 1
        if len(self._marks) > MAX_MARKS:
            self._marks.pop(0)
            self._marks_dropped += 1

    def mark(self, name: str, **fields: Any) -> None:
        """Append one named stream marker. `name` must be a static string
        literal at the call site (obs-prov-static-name); fields must be
        deterministic values."""
        with self._lock:
            self._mark_locked(name, **fields)

    # ------------------------------------------------------------------
    # reading / fingerprints
    # ------------------------------------------------------------------

    def rounds(self) -> List[int]:
        with self._lock:
            return sorted(self._rounds)

    def round_provenance(self, r: int) -> Optional[RoundProvenance]:
        with self._lock:
            return self._rounds.get(r)

    def round_fingerprint(self, r: int) -> Optional[str]:
        with self._lock:
            rp = self._rounds.get(r)
        return None if rp is None else rp.fingerprint()

    def explain_round(self, r: int) -> Dict[str, Any]:
        """One round's full dossier (`GET /debug/explain`, CLI explain)."""
        with self._lock:
            rp = self._rounds.get(r)
            evicted_below = self.evicted_below
        if rp is None:
            return {
                "node": self.node_id, "round": r, "known": False,
                "evicted_below": evicted_below,
            }
        doc = rp.to_dict()
        doc.update({
            "node": self.node_id, "known": True,
            "fingerprint": rp.fingerprint(),
        })
        return doc

    def table_doc(self) -> Dict[str, Any]:
        """Engine-comparable stream: the per-round tables only."""
        with self._lock:
            rounds = {
                str(r): rp.table_doc() for r, rp in sorted(self._rounds.items())
            }
            return {"evicted_below": self.evicted_below, "rounds": rounds}

    def table_bytes(self) -> bytes:
        return json.dumps(self.table_doc(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    def table_fingerprint(self) -> str:
        return hashlib.sha256(self.table_bytes()).hexdigest()

    def to_json(self) -> Dict[str, Any]:
        """Full stream document (export artifacts, /debug/explain?all)."""
        with self._lock:
            rounds = {
                str(r): rp.to_dict() for r, rp in sorted(self._rounds.items())
            }
            marks = [dict(m) for m in self._marks]
            doc = {
                "node": self.node_id,
                "round_cap": self.round_cap,
                "evicted_rounds": self.evicted_rounds,
                "evicted_below": self.evicted_below,
                "marks_dropped": self._marks_dropped,
                "rounds": rounds,
                "marks": marks,
            }
        return doc

    def stream_bytes(self) -> bytes:
        """Canonical byte serialization of the full stream — the unit of
        the sim's byte-identical-replay guarantee for provenance."""
        return json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    def fingerprint(self) -> str:
        """sha256 of ``stream_bytes()`` — joins ``SimCluster.result()``'s
        determinism fingerprint."""
        return hashlib.sha256(self.stream_bytes()).hexdigest()

    # ------------------------------------------------------------------
    # integrity
    # ------------------------------------------------------------------

    def verify_complete_or_truncated(self) -> List[str]:
        """The fault-plan stream contract: every retained round is above
        the truncation floor, every eviction left a ``prov.truncate``
        mark (unless the mark ring itself wrapped), every fame cell
        names a witness the same round knows, and the store respects its
        bound. Returns human-readable issues (empty == holds)."""
        issues: List[str] = []
        with self._lock:
            rounds = dict(self._rounds)
            evicted = self.evicted_rounds
            evicted_below = self.evicted_below
            marks = list(self._marks)
            marks_dropped = self._marks_dropped
        if len(rounds) > self.round_cap:
            issues.append(
                f"{len(rounds)} rounds retained > cap {self.round_cap}"
            )
        for r in rounds:
            if r < evicted_below:
                issues.append(
                    f"round {r} retained below truncation floor "
                    f"{evicted_below}"
                )
        if evicted > 0 and marks_dropped == 0:
            if not any(m["name"] == "prov.truncate" for m in marks):
                issues.append(
                    f"{evicted} rounds evicted but no prov.truncate mark"
                )
        for r, rp in rounds.items():
            witnesses = rp.tables["witness"]
            for h in rp.tables["fame"]:
                if h not in witnesses:
                    issues.append(
                        f"round {r}: fame cell {h[:18]}… has no witness cell"
                    )
        return issues


# ----------------------------------------------------------------------
# PassResults capture (benches / standalone engine comparisons)
# ----------------------------------------------------------------------

def grid_cell_keys(grid) -> List[str]:
    """Row -> stable cell key. Real grids carry event hashes; synthetic
    bench grids don't, so fall back to the row ordinal — rows are built
    identically on both sides of a byte-equality gate, so the keys still
    line up cell-for-cell."""
    hashes = getattr(grid, "hashes", None)
    if hashes:
        return hashes
    return ["row%08d" % r for r in range(grid.e)]


def capture_pass_results(grid, res, recorder: Optional[ProvenanceRecorder]
                         = None, engine: str = "device",
                         clock: Optional[Clock] = None) -> ProvenanceRecorder:
    """Fingerprint a raw PassResults against its DagGrid — the seam the
    benches' byte-equality gates bisect through. Reads only the staged
    host numpy buffers (no device work, no extra syncs)."""
    prov = recorder if recorder is not None else ProvenanceRecorder(
        clock=clock
    )
    keys = grid_cell_keys(grid)
    for row in range(grid.e):
        rnum = int(res.rounds[row])
        if rnum < 0:
            continue
        h = keys[row]
        prov.note_event(h, rnum, int(res.lamport[row]),
                        grid.last_ancestors[row])
        if bool(res.witness[row]):
            prov.note_witness(h, rnum, int(grid.creator[row]))
        rr = int(res.received[row])
        if rr >= 0:
            prov.note_received(h, rr)
    # kernel-level results (PipelineResult) have no rebasing offset
    round_offset = int(getattr(res, "round_offset", 0))
    for ti in range(res.witness_table.shape[0]):
        rnum = ti + round_offset
        for c in range(res.witness_table.shape[1]):
            wrow = int(res.witness_table[ti, c])
            if wrow < 0 or not bool(res.fame_decided[ti, c]):
                continue
            prov.note_fame(keys[wrow], rnum,
                           bool(res.famous[ti, c]), engine=engine)
    prov.mark("prov.capture", engine=engine, rounds=int(res.last_round) + 1)
    return prov


# ----------------------------------------------------------------------
# bisection
# ----------------------------------------------------------------------

class DivergenceBisector:
    """Diff two provenance streams; name the earliest divergent cell.

    Ordering is causal: rounds ascend, and within a round the tables are
    visited in pass order (divide:lastAncestors, divide:witness, fame,
    received) — a wrong witness set explains a wrong fame verdict
    explains a wrong reception, so the first difference in this order is
    the cell to debug. Cell keys tie-break lexicographically, so the
    localization (and its triage artifact) is deterministic."""

    def __init__(self, artifact_dir: str = "docs/artifacts"):
        self.artifact_dir = artifact_dir

    @staticmethod
    def _rounds_of(doc: Dict[str, Any]) -> Dict[int, Dict[str, Any]]:
        """Accepts a full `to_json()` doc or a bare `table_doc()`."""
        out: Dict[int, Dict[str, Any]] = {}
        for k, v in doc.get("rounds", {}).items():
            tables = v.get("tables", v if isinstance(v, dict) else {})
            out[int(k)] = {
                "tables": tables,
                "why": v.get("why", {}),
            }
        return out

    def bisect(self, a_name: str, a_doc: Dict[str, Any], b_name: str,
               b_doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Earliest divergent cell between two streams, or None when they
        agree over their common round window. Rounds outside one side's
        retained window (bounded recorder, truncation floor) are not
        comparable and are skipped, not flagged."""
        ra = self._rounds_of(a_doc)
        rb = self._rounds_of(b_doc)
        if not ra or not rb:
            return None
        floor_a = int(a_doc.get("evicted_below", 0))
        floor_b = int(b_doc.get("evicted_below", 0))
        lo = max(min(ra), min(rb), floor_a, floor_b)
        hi = min(max(ra), max(rb))
        for r in range(lo, hi + 1):
            in_a, in_b = r in ra, r in rb
            if not in_a and not in_b:
                continue
            if in_a != in_b:
                return self._loc(
                    r, "divide", "witness", None, a_name, b_name,
                    kind="missing-round",
                    a=("present" if in_a else "absent"),
                    b=("present" if in_b else "absent"),
                )
            ta, tb = ra[r]["tables"], rb[r]["tables"]
            for pass_name, table in PASS_TABLES:
                ca = ta.get(table, {})
                cb = tb.get(table, {})
                if ca == cb:
                    continue
                for key in sorted(set(ca) | set(cb)):
                    if ca.get(key) == cb.get(key):
                        continue
                    kind = ("value-mismatch" if key in ca and key in cb
                            else ("only-" + (a_name if key in ca else b_name)))
                    loc = self._loc(
                        r, pass_name, table, key, a_name, b_name,
                        kind=kind, a=ca.get(key), b=cb.get(key),
                    )
                    wa = ra[r]["why"].get(key, {})
                    wb = rb[r]["why"].get(key, {})
                    if wa or wb:
                        loc["why"] = {a_name: wa, b_name: wb}
                        voter = wa.get("voter") or wb.get("voter")
                        if voter is not None:
                            loc["voter"] = voter
                    return loc
        return None

    @staticmethod
    def _loc(r: int, pass_name: str, table: str, key: Optional[str],
             a_name: str, b_name: str, **extra: Any) -> Dict[str, Any]:
        loc: Dict[str, Any] = {
            "round": r,
            "pass": pass_name,
            "table": table,
            "cell": key,
            "a_name": a_name,
            "b_name": b_name,
        }
        loc.update(extra)
        return loc

    def localize(self, views: List[Tuple[str, Dict[str, Any]]]
                 ) -> Optional[Dict[str, Any]]:
        """First divergence across many streams: every stream is compared
        to the first; the earliest localization (by round, then pass
        order) wins."""
        if len(views) < 2:
            return None
        ref_name, ref_doc = views[0]
        best: Optional[Dict[str, Any]] = None
        order = {pt: i for i, pt in enumerate(PASS_TABLES)}
        for name, doc in views[1:]:
            loc = self.bisect(ref_name, ref_doc, name, doc)
            if loc is None:
                continue
            key = (loc["round"], order.get((loc["pass"], loc["table"]), 99))
            if best is None or key < (
                best["round"], order.get((best["pass"], best["table"]), 99)
            ):
                best = loc
        return best

    # -- artifacts ------------------------------------------------------

    def flight_fields(self, loc: Dict[str, Any]) -> Dict[str, Any]:
        """Compact deterministic field set for the `divergence.localized`
        flight record."""
        cell = loc.get("cell")
        return {
            "round": loc["round"],
            "pass_name": loc["pass"],
            "table": loc["table"],
            "cell": (cell[:18] if isinstance(cell, str) else ""),
            "kind": loc.get("kind", ""),
            "a_name": loc["a_name"],
            "b_name": loc["b_name"],
        }

    def export(self, loc: Dict[str, Any], filename: str,
               context: Optional[Dict[str, Any]] = None,
               directory: Optional[str] = None) -> str:
        """Write the triage artifact. The filename is the caller's and
        must be deterministic (seed/block/label — never timestamps); the
        JSON is canonical sorted-key, so repeat runs are byte-identical."""
        directory = directory if directory is not None else self.artifact_dir
        os.makedirs(directory, exist_ok=True)
        doc = {
            "kind": "babble-tpu-divergence-localization",
            "localized": loc,
            "context": dict(context or {}),
        }
        path = os.path.join(directory, filename)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        return path


def bisect_pass_results(grid, a_name: str, res_a, b_name: str, res_b,
                        artifact_dir: str = "docs/artifacts",
                        label: str = "bench") -> Tuple[Optional[Dict[str, Any]],
                                                       Optional[str]]:
    """Bench byte-equality gate hook: capture both engines' PassResults
    against the same grid, bisect, export the triage artifact. Returns
    (localization, artifact_path) — (None, None) when the streams agree
    (the arrays differed some other way, e.g. padding)."""
    prov_a = capture_pass_results(grid, res_a, engine=a_name)
    prov_b = capture_pass_results(grid, res_b, engine=b_name)
    bis = DivergenceBisector(artifact_dir)
    loc = bis.bisect(a_name, prov_a.to_json(), b_name, prov_b.to_json())
    if loc is None:
        return None, None
    path = bis.export(
        loc, f"bisect-{label}-{a_name}-vs-{b_name}.json",
        context={"label": label},
    )
    return loc, path


# ----------------------------------------------------------------------
# CI smoke (scripts/ci_lint.sh: 3-seed bisector self-test)
# ----------------------------------------------------------------------

def _smoke_recorder(seed: int) -> ProvenanceRecorder:
    """A deterministic synthetic stream: N witnesses per round over a few
    rounds, cells derived from a seeded PRNG (stdlib random so the smoke
    stays jax-free and sub-second)."""
    import random

    rng = random.Random(seed)
    prov = ProvenanceRecorder(node_id=0)
    n = 4
    for r in range(6):
        for c in range(n):
            h = "%016x" % rng.getrandbits(64)
            prov.note_event(h, r, r * n + c,
                            [rng.randrange(16) for _ in range(n)])
            prov.note_witness(h, r, c)
            prov.note_fame(h, r, rng.random() < 0.8, engine="smoke",
                           voter="%016x" % rng.getrandbits(64),
                           yays=3, nays=0, step=2)
        if r >= 2:
            prov.settle_round(r - 2)
    return prov


def run_bisector_smoke(seeds: int = 3) -> List[str]:
    """Per seed: identical streams must bisect to None; one seeded
    single-cell fame flip must localize to exactly that cell. Returns
    failure strings (empty == pass)."""
    import random

    failures: List[str] = []
    bis = DivergenceBisector()
    for seed in range(seeds):
        clean = _smoke_recorder(seed)
        if bis.bisect("a", clean.to_json(), "b",
                      _smoke_recorder(seed).to_json()) is not None:
            failures.append(f"seed {seed}: clean streams reported divergent")
            continue
        mutated = _smoke_recorder(seed)
        rng = random.Random(seed + 1000)
        target_round = rng.randrange(3, 6)
        rp = mutated.round_provenance(target_round)
        target_cell = sorted(rp.tables["fame"])[
            rng.randrange(len(rp.tables["fame"]))
        ]
        rp.tables["fame"][target_cell] = not rp.tables["fame"][target_cell]
        loc = bis.bisect("clean", clean.to_json(),
                         "mutated", mutated.to_json())
        if loc is None:
            failures.append(f"seed {seed}: injected flip not detected")
        elif (loc["round"], loc["table"], loc["cell"]) != (
            target_round, "fame", target_cell
        ):
            failures.append(
                f"seed {seed}: localized {loc['round']}/{loc['table']}/"
                f"{loc['cell']} != injected {target_round}/fame/{target_cell}"
            )
    return failures
