"""Cross-node causal trace contexts (ISSUE 5).

A hashgraph transaction's life is inherently cross-node: submitted on
one node, gossiped and re-gossiped, minted into an event, assigned a
round, voted famous, and finally committed everywhere. A `TraceContext`
follows one transaction across that whole path — **out-of-band**: trace
identifiers ride the sync RPC payloads as extra optional JSON fields
(`Traces` on SyncResponse/EagerSyncRequest, net/commands.py) and are
NEVER part of the signed event bytes, so event hashes, signatures and
wire compatibility with trace-unaware nodes are untouched. The
`obs-ctx-in-event` lint rule (babble_tpu/analysis/obs.py) enforces the
invariant statically; `tests/test_sim.py` proves it differentially
(traced and untraced same-seed clusters commit identical digests).

Determinism is by construction, not by luck:

- ``trace_id = sha256(tx)[:16]`` — any node can derive it from the
  transaction bytes alone, so consensus-side hooks (hashgraph passes)
  need no side channel to find the context for an event's payload;
- ``span_id = sha256(trace_id|node_id)[:16]`` — reproducible per hop;
- every stage mark reads the injected Clock, so under the simulator's
  virtual time two same-seed runs produce byte-identical cluster
  traces (`SimCluster.trace_fingerprint()`).

Memory is bounded: the store holds at most `capacity` live contexts,
LRU by Clock time; an eviction increments `obs_traces_dropped_total`.
Contexts complete (and are removed) at commit, so steady state is the
in-flight transaction window, not history.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from hashlib import sha256
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..common.clock import Clock

DEFAULT_TRACE_CAPACITY = 4096

# per-node pipeline stages, in causal order. `submit` exists only on the
# origin node; `receive` only on nodes that learned the context over the
# wire; the rest are marked wherever the event carrying the transaction
# is inserted and decided.
STAGES = ("submit", "receive", "event", "round", "famous", "commit")


def trace_id_for(tx: bytes) -> str:
    """Deterministic trace id: any node derives it from the tx bytes."""
    return sha256(bytes(tx)).hexdigest()[:16]


def span_id_for(trace_id: str, node_id: int) -> str:
    """Deterministic per-node base span id for one trace."""
    return sha256(f"{trace_id}|{node_id}".encode()).hexdigest()[:16]


class TraceContext:
    """One transaction's live trace state on one node.

    `parent` is the SENDING node's base span id (empty at the origin):
    the cross-node causal edge. Stage spans within a node parent to the
    node's own base span id.
    """

    __slots__ = ("trace_id", "origin", "span_id", "parent", "marks")

    def __init__(self, trace_id: str, origin: int, span_id: str,
                 parent: str):
        self.trace_id = trace_id
        self.origin = origin
        self.span_id = span_id
        self.parent = parent
        self.marks: Dict[str, float] = {}

    def to_wire(self) -> dict:
        """The out-of-band wire form piggybacked on sync payloads. The
        receiver chains to OUR span id — `Span` becomes its `parent`."""
        return {"Id": self.trace_id, "Origin": self.origin,
                "Span": self.span_id}


class TraceStore:
    """Bounded per-node store of live TraceContexts, LRU by Clock time.

    Thread-safe: gossip handler threads absorb contexts while the babble
    loop marks consensus stages. All public methods are cheap no-ops when
    the store is disabled or empty, so trace-unaware workloads pay one
    dict check per hook.
    """

    def __init__(self, clock: Clock, node_id: int, registry, tracer,
                 capacity: int = DEFAULT_TRACE_CAPACITY,
                 enabled: bool = True):
        if capacity < 1:
            raise ValueError("trace capacity must be >= 1")
        self.clock = clock
        self.node_id = node_id
        self.tracer = tracer
        self.capacity = capacity
        self.enabled = enabled
        self._lock = threading.Lock()
        # guarded-by: _lock — insertion order IS recency (LRU)
        self._ctxs: "OrderedDict[str, TraceContext]" = OrderedDict()
        self._dropped = registry.counter(
            "obs_traces_dropped_total",
            "Live trace contexts evicted by the LRU capacity bound",
        )
        registry.gauge(
            "obs_traces_live", "Live trace contexts currently held",
        ).set_function(lambda: len(self._ctxs))
        # end-to-end stage decomposition, one histogram per causal edge
        # (ISSUE 5: part of the sim determinism contract)
        self._h_submit_event = registry.histogram(
            "babble_trace_stage_submit_to_event_seconds",
            "Causal-trace stage: transaction submit -> carried in an event",
        )
        self._h_event_round = registry.histogram(
            "babble_trace_stage_event_to_round_seconds",
            "Causal-trace stage: event insertion -> round assigned",
        )
        self._h_round_famous = registry.histogram(
            "babble_trace_stage_round_to_famous_seconds",
            "Causal-trace stage: round assigned -> round-received decided",
        )
        self._h_famous_commit = registry.histogram(
            "babble_trace_stage_famous_to_commit_seconds",
            "Causal-trace stage: round-received decided -> block commit",
        )

    # ------------------------------------------------------------------
    # context lifecycle
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        # unguarded-ok: GIL-atomic len() for a staleness-tolerant debug probe
        return len(self._ctxs)

    def get(self, trace_id: str) -> Optional[TraceContext]:
        with self._lock:
            return self._ctxs.get(trace_id)

    def _insert(self, ctx: TraceContext) -> None:  # requires-lock: _lock
        """Caller must hold _lock."""
        self._ctxs[ctx.trace_id] = ctx
        while len(self._ctxs) > self.capacity:
            self._ctxs.popitem(last=False)
            self._dropped.inc()

    def begin(self, tx: bytes) -> None:
        """Open a trace at the submission edge (origin node). Idempotent:
        re-submitting identical bytes keeps the first submit mark."""
        if not self.enabled:
            return
        tid = trace_id_for(tx)
        now = self.clock.monotonic()
        with self._lock:
            if tid in self._ctxs:
                self._ctxs.move_to_end(tid)
                return
            ctx = TraceContext(tid, self.node_id,
                               span_id_for(tid, self.node_id), parent="")
            ctx.marks["submit"] = now
            self._insert(ctx)
        self.tracer.record(
            "trace.submit", now, 0.0,
            {"trace": tid, "span": ctx.span_id, "parent": "",
             "node": self.node_id},
        )

    def absorb(self, wire_ctxs: Sequence[dict]) -> None:
        """Adopt contexts piggybacked on an inbound sync payload. Must run
        BEFORE the payload's events are inserted so the consensus hooks
        find them. Idempotent under duplicate delivery (dup_rate faults):
        a known trace id is only touched, never re-parented."""
        if not self.enabled or not wire_ctxs:
            return
        now = self.clock.monotonic()
        recorded: List[TraceContext] = []
        with self._lock:
            for w in wire_ctxs:
                tid = w.get("Id") if isinstance(w, dict) else None
                if not isinstance(tid, str) or not tid:
                    continue
                if tid in self._ctxs:
                    self._ctxs.move_to_end(tid)
                    continue
                parent = w.get("Span", "")
                if not isinstance(parent, str):
                    parent = ""
                try:
                    origin = int(w.get("Origin", -1))
                except (TypeError, ValueError):
                    origin = -1
                ctx = TraceContext(tid, origin,
                                   span_id_for(tid, self.node_id), parent)
                ctx.marks["receive"] = now
                self._insert(ctx)
                recorded.append(ctx)
        for ctx in recorded:
            self.tracer.record(
                "trace.receive", now, 0.0,
                {"trace": ctx.trace_id, "span": ctx.span_id,
                 "parent": ctx.parent, "node": self.node_id},
            )

    def contexts_for(self, events: Iterable) -> List[dict]:
        """Wire contexts for the traced transactions carried by an
        outgoing event diff — the out-of-band piggyback payload."""
        # unguarded-ok: racy emptiness probe; the locked block below is authoritative
        if not self.enabled or not self._ctxs:
            return []
        out: List[dict] = []
        seen = set()
        with self._lock:
            for ev in events:
                for tx in ev.transactions():
                    tid = trace_id_for(tx)
                    if tid in seen:
                        continue
                    ctx = self._ctxs.get(tid)
                    if ctx is None:
                        continue
                    seen.add(tid)
                    self._ctxs.move_to_end(tid)
                    out.append(ctx.to_wire())
        return out

    # ------------------------------------------------------------------
    # consensus stage marks
    # ------------------------------------------------------------------

    def mark_event(self, txs: Sequence[bytes]) -> None:
        """The transaction is now carried by an inserted event."""
        self._mark(txs, "event", "submit", self._h_submit_event,
                   "trace.event")

    def mark_round(self, txs: Sequence[bytes]) -> None:
        """The carrying event was assigned a round (DivideRounds)."""
        self._mark(txs, "round", "event", self._h_event_round,
                   "trace.round")

    def mark_famous(self, txs: Sequence[bytes]) -> None:
        """The carrying event's round-received was decided — every unique
        famous witness of a later round sees it (DecideRoundReceived)."""
        self._mark(txs, "famous", "round", self._h_round_famous,
                   "trace.famous")

    def mark_commit(self, txs: Sequence[bytes]) -> None:
        """The transaction committed in a block: observe the final stage
        and complete (remove) the context — completion is not a drop."""
        # unguarded-ok: racy emptiness probe; the locked pop below is authoritative
        if not self.enabled or not self._ctxs or not txs:
            return
        now = self.clock.monotonic()
        done: List[Tuple[TraceContext, Optional[float]]] = []
        with self._lock:
            for tx in txs:
                ctx = self._ctxs.pop(trace_id_for(tx), None)
                if ctx is not None:
                    done.append((ctx, ctx.marks.get("famous")))
        for ctx, prev in done:
            if prev is not None:
                self._h_famous_commit.observe(now - prev)
            start = prev if prev is not None else now
            self.tracer.record(
                "trace.commit", start, now - start,
                {"trace": ctx.trace_id, "span": ctx.span_id + ":commit",
                 "parent": ctx.span_id, "node": self.node_id},
            )

    def _mark(self, txs: Sequence[bytes], stage: str, prev_stage: str,
              histogram, span_name: str) -> None:
        # unguarded-ok: racy emptiness probe; the locked walk below is authoritative
        if not self.enabled or not self._ctxs or not txs:
            return
        now = self.clock.monotonic()
        marked: List[Tuple[TraceContext, Optional[float]]] = []
        with self._lock:
            for tx in txs:
                tid = trace_id_for(tx)
                ctx = self._ctxs.get(tid)
                if ctx is None or stage in ctx.marks:
                    continue
                ctx.marks[stage] = now
                self._ctxs.move_to_end(tid)
                marked.append((ctx, ctx.marks.get(prev_stage)))
        for ctx, prev in marked:
            if prev is not None:
                histogram.observe(now - prev)
            # the stage span covers the wait since the previous stage, so
            # the Perfetto timeline reads as contiguous per-node segments
            start = prev if prev is not None else now
            self.tracer.record(  # obs-ok: stage names are literals at the mark_* call sites
                span_name, start, now - start,
                {"trace": ctx.trace_id, "span": ctx.span_id + ":" + stage,
                 "parent": ctx.span_id, "node": self.node_id},
            )


def assemble_cluster_trace(node_docs: Sequence[Tuple[Optional[int], dict]],
                           ) -> dict:
    """Merge per-node Chrome-trace documents into one cluster timeline.

    `node_docs` is ``[(node_id_or_None, chrome_trace_doc), ...]``; a
    non-None node id overrides the document's pids (the sim path), None
    keeps the pids the exporting node stamped (the HTTP federation path,
    where each /debug/trace response already carries its node id).

    Parent references that do not resolve to any span in the merged
    document are **cleanly truncated**: the span is re-rooted
    (``parent=""``) and marked ``truncated`` — a crashed or unreachable
    node's spans are absent, never dangling. The output therefore
    contains no orphan parent span ids by construction.
    """
    events: List[dict] = []
    span_ids = set()
    for node_id, doc in node_docs:
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)  # never mutate the exporting tracer's dicts
            if node_id is not None:
                ev["pid"] = node_id
            args = ev.get("args")
            if isinstance(args, dict) and args.get("span"):
                span_ids.add(args["span"])
            events.append(ev)
    for ev in events:
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        parent = args.get("parent")
        if parent and parent not in span_ids:
            args = dict(args)
            args["parent"] = ""
            args["truncated"] = True
            ev["args"] = args
    return {"traceEvents": events, "displayTimeUnit": "ms"}
