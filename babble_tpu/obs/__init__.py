"""Unified observability layer (ISSUE 4).

One `Observability` object per node bundles the three telemetry
surfaces behind the injected Clock seam:

- a typed `MetricsRegistry` (counters / gauges / log-bucketed
  histograms with declared, bounded label sets) rendered as Prometheus
  text at `GET /metrics`;
- a bounded ring-buffer `SpanTracer` exporting Chrome trace-event JSON
  at `GET /debug/trace`;
- the `Clock` every instrumentation site must time through, so sim
  sweeps produce byte-identical latency histograms for a given seed.

Metric names are declared with static string literals only — the
`obs-*` analysis rules (babble_tpu/analysis/obs.py) reject computed
names and undeclared label sets at lint time.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common.clock import Clock, SYSTEM_CLOCK
from .clusterview import (
    ClusterObservatory,
    DIGEST_VERSION,
    HealthDigest,
    MAX_FLEET,
    failure_kind,
)
from .devledger import (
    DeviceLedger,
    ENTRY_INFO,
    build_timeline,
    ledger_call,
    retrace_baseline,
    retrace_delta,
)
from .flightrec import (
    DEFAULT_FLIGHT_CAPACITY,
    FlightRecord,
    FlightRecorder,
)
from .metrics import (
    Counter,
    DEFAULT_COUNT_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    DEFAULT_SIZE_BUCKETS,
    Gauge,
    Histogram,
    MAX_LABEL_SETS,
    MetricsRegistry,
    log_buckets,
)
from .provenance import (
    DEFAULT_PROV_ROUND_CAP,
    DivergenceBisector,
    ProvenanceRecorder,
    RoundProvenance,
    bisect_pass_results,
    capture_pass_results,
    run_bisector_smoke,
)
from .trace import DEFAULT_SPAN_CAPACITY, Span, SpanTracer
from .slo import SLObjective, SLOEngine
from .tracectx import (
    DEFAULT_TRACE_CAPACITY,
    TraceContext,
    TraceStore,
    assemble_cluster_trace,
    span_id_for,
    trace_id_for,
)

__all__ = [
    "Observability",
    "ClusterObservatory",
    "HealthDigest",
    "DIGEST_VERSION",
    "MAX_FLEET",
    "failure_kind",
    "DeviceLedger",
    "ENTRY_INFO",
    "build_timeline",
    "ledger_call",
    "retrace_baseline",
    "retrace_delta",
    "FlightRecorder",
    "FlightRecord",
    "SLOEngine",
    "SLObjective",
    "ProvenanceRecorder",
    "RoundProvenance",
    "DivergenceBisector",
    "capture_pass_results",
    "bisect_pass_results",
    "run_bisector_smoke",
    "DEFAULT_PROV_ROUND_CAP",
    "DEFAULT_FLIGHT_CAPACITY",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "SpanTracer",
    "Span",
    "TraceContext",
    "TraceStore",
    "assemble_cluster_trace",
    "trace_id_for",
    "span_id_for",
    "log_buckets",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
    "DEFAULT_COUNT_BUCKETS",
    "DEFAULT_SPAN_CAPACITY",
    "DEFAULT_TRACE_CAPACITY",
    "MAX_LABEL_SETS",
]


class Observability:
    """Per-node bundle of registry + tracer + trace store + the clock
    they all time by."""

    def __init__(self, clock: Optional[Clock] = None, node_id: int = 0,
                 span_capacity: int = DEFAULT_SPAN_CAPACITY,
                 trace_capacity: int = DEFAULT_TRACE_CAPACITY,
                 tracing: bool = True,
                 flightrec_capacity: int = DEFAULT_FLIGHT_CAPACITY):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.node_id = node_id
        self.registry = MetricsRegistry()
        self.tracer = SpanTracer(clock=self.clock, capacity=span_capacity)
        # black-box flight recorder (ISSUE 7): bounded ring of typed
        # structured records dumped wholesale on stall/divergence/flap/
        # SLO breach — same Clock seam, same determinism contract
        self.flightrec = FlightRecorder(
            clock=self.clock, node_id=node_id, capacity=flightrec_capacity,
        )
        # consensus decision provenance (ISSUE 14): per-round voting
        # tables + fame-decision whys, captured by every engine at its
        # host-side integration seam — the DivergenceBisector's input
        self.provenance = ProvenanceRecorder(
            clock=self.clock, node_id=node_id,
        )
        # cross-node causal tracing (ISSUE 5): live TraceContexts for
        # in-flight transactions, bounded, feeding per-stage histograms
        # and trace.* spans into the registry/tracer above
        self.traces = TraceStore(
            clock=self.clock, node_id=node_id, registry=self.registry,
            tracer=self.tracer, capacity=trace_capacity, enabled=tracing,
        )
        # device-time ledger (ISSUE 19): per-pass kernel cost cells,
        # compile/retrace accounting over jax.monitoring, and the seam
        # ring behind GET /debug/timeline — durations follow the clock
        # policy (real SystemClock only; the sim records exact zeros)
        self.devledger = DeviceLedger(self)
        # cluster health plane (ISSUE 20): federates piggybacked peer
        # HealthDigests into derived cluster series, a queryable fleet
        # table, and staleness-asymmetry partition inference; dormant
        # until the node calls bind_local with its digest providers
        self.clusterview = ClusterObservatory(self)

    # Delegates so call sites read `obs.counter("...")`. The name flows
    # through a parameter here, which the obs-dynamic-name rule cannot
    # prove static — waived: the rule checks the *call sites*, which do
    # pass literals.
    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self.registry.counter(name, help_text, labels)  # obs-ok: delegate, name checked at call sites

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self.registry.gauge(name, help_text, labels)  # obs-ok: delegate, name checked at call sites

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (), buckets=None) -> Histogram:
        return self.registry.histogram(name, help_text, labels, buckets=buckets)  # obs-ok: delegate, name checked at call sites

    def span(self, name: str, histogram=None, **attrs):
        """Context manager timing a block into the span ring (and an
        optional histogram) via the injected clock."""
        return self.tracer.span(name, histogram=histogram, **attrs)  # obs-ok: delegate, name checked at call sites
