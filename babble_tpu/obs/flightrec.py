"""Black-box flight recorder (ISSUE 7 tentpole).

A bounded, Clock-timestamped ring of typed structured records — the
"what was the node doing just before it went wrong" counterpart to the
metrics registry's "how much" and the span tracer's "how long". Call
sites across the consensus stack append small named records (backend
ladder transitions, dispatch-queue lifecycle, watchdog stall episodes,
fame re-openings, resets, fork evidence, sig-backlog pressure); the
ring keeps the most recent ``capacity`` of them and is dumped wholesale
when something trips: a watchdog stall, a DivergenceChecker failure, a
demotion flap, an SLO breach, or a crash.

Determinism contract (the sim's byte-equality gates depend on it):

- every record is timestamped through the injected Clock, never the OS
  clock, so same-seed sim runs produce byte-identical record streams;
- record fields must be deterministic values (rounds, counts, Clock
  durations) — no thread names, object ids or wall-clock times;
- ``stream_bytes()`` is canonical sorted-key JSON and its sha256
  (``fingerprint()``) joins ``SimCluster.result()``'s determinism
  fingerprint alongside the block digest and trace fingerprint;
- dump artifact filenames are deterministic (node id + dump ordinal +
  reason — no timestamps), so replay artifacts line up across runs.

Record names are static string literals at call sites, enforced by the
`obs-flightrec-static-name` lint rule (analysis/obs.py) — receivers
must be *named* ``flightrec`` (e.g. ``obs.flightrec``) for the rule to
see them, which doubles as a naming convention.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..common.clock import Clock, SYSTEM_CLOCK

# ring capacity: ~2k records is minutes of context at consensus rates
# while keeping a dump artifact comfortably under a megabyte
DEFAULT_FLIGHT_CAPACITY = 2048

# dumps held in memory when no dump_dir is configured (the sim runs
# file-free; the sweep exports these on failure)
MAX_DUMP_DOCS = 8

# Clock seconds between dumps: the FIRST trigger in a failure episode
# captures the interesting ring; a stall, its SLO breach and a demotion
# flap milliseconds later would dump near-identical copies otherwise
DEFAULT_DUMP_SUPPRESS_S = 30.0

# events within this Clock window counting toward a flap before the
# recorder self-dumps (e.g. 3 backend demotions in 10s)
FLAP_WINDOW_S = 10.0
FLAP_THRESHOLD = 3


class FlightRecord:
    """One typed record: monotonically increasing ``seq``, Clock time
    ``t``, static ``name`` and a small dict of deterministic fields."""

    __slots__ = ("seq", "t", "name", "fields")

    def __init__(self, seq: int, t: float, name: str,
                 fields: Dict[str, Any]):
        self.seq = seq
        self.t = t
        self.name = name
        self.fields = fields

    def to_dict(self) -> Dict[str, Any]:
        # floats rounded so accumulated Clock arithmetic renders stably
        fields = {
            k: (round(v, 9) if isinstance(v, float) else v)
            for k, v in self.fields.items()
        }
        return {
            "seq": self.seq,
            "t": round(self.t, 9),
            "name": self.name,
            "fields": fields,
        }


class FlightRecorder:
    """Bounded ring of FlightRecords with triggered whole-ring dumps."""

    def __init__(self, clock: Optional[Clock] = None, node_id: int = 0,
                 capacity: int = DEFAULT_FLIGHT_CAPACITY,
                 dump_dir: Optional[str] = None,
                 logger: Optional[logging.Logger] = None,
                 dump_suppress_s: float = DEFAULT_DUMP_SUPPRESS_S):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.node_id = node_id
        self.capacity = max(1, capacity)
        self.dump_dir = dump_dir
        self.logger = logger if logger is not None else logging.getLogger(
            "babble.flightrec"
        )
        self.dump_suppress_s = dump_suppress_s
        self._lock = threading.Lock()
        # guarded-by: _lock — fixed ring, same discipline as SpanTracer
        self._ring: List[Optional[FlightRecord]] = [None] * self.capacity
        self._next = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock — overwritten records
        self.dumps = 0  # guarded-by: _lock — dumps emitted (not suppressed)
        self.dumps_suppressed = 0  # guarded-by: _lock
        self.dump_docs: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._last_dump_at: Optional[float] = None  # guarded-by: _lock
        # guarded-by: _lock — recent event times per flap kind
        self._flap_times: Dict[str, Deque[float]] = {}

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def record(self, name: str, **fields: Any) -> None:
        """Append one record. ``name`` must be a static string literal
        at the call site (obs-flightrec-static-name); fields must be
        deterministic values — no wall-clock, no thread identity."""
        t = self.clock.monotonic()
        with self._lock:
            slot = self._next % self.capacity
            if self._ring[slot] is not None:
                self.dropped += 1
            self._ring[slot] = FlightRecord(self._next, t, name, fields)
            self._next += 1

    def note_flap(self, kind: str) -> Optional[str]:
        """Count one event toward a flap; auto-dump when FLAP_THRESHOLD
        land within FLAP_WINDOW_S of Clock time (e.g. a node bouncing
        between backend rungs). Returns the dump path when one fired."""
        now = self.clock.monotonic()
        with self._lock:
            times = self._flap_times.get(kind)
            if times is None:
                times = self._flap_times[kind] = deque(maxlen=FLAP_THRESHOLD)
            times.append(now)
            flapping = (
                len(times) >= FLAP_THRESHOLD
                and now - times[0] <= FLAP_WINDOW_S
            )
        if flapping:
            return self.dump(kind + "-flap", window_s=FLAP_WINDOW_S,
                             events=FLAP_THRESHOLD)
        return None

    # ------------------------------------------------------------------
    # reading
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return min(self._next, self.capacity)

    def records(self) -> List[FlightRecord]:
        """Snapshot, oldest first (same wrap logic as SpanTracer)."""
        with self._lock:
            head = self._next % self.capacity
            ordered = self._ring[head:] + self._ring[:head]
        return [r for r in ordered if r is not None]

    def stream_bytes(self) -> bytes:
        """Canonical byte serialization of the current record stream —
        the unit of the sim's byte-identical-replay guarantee."""
        docs = [r.to_dict() for r in self.records()]
        return json.dumps(docs, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    def fingerprint(self) -> str:
        """sha256 of ``stream_bytes()`` — joins the sim's determinism
        fingerprint in ``SimCluster.result()``."""
        return hashlib.sha256(self.stream_bytes()).hexdigest()

    def to_json(self) -> Dict[str, Any]:
        """Full document for ``GET /debug/flightrec``."""
        with self._lock:
            dropped = self.dropped
            dumps = self.dumps
            suppressed = self.dumps_suppressed
        return {
            "node": self.node_id,
            "capacity": self.capacity,
            "dropped": dropped,
            "dumps": dumps,
            "dumps_suppressed": suppressed,
            "fingerprint": self.fingerprint(),
            "records": [r.to_dict() for r in self.records()],
        }

    # ------------------------------------------------------------------
    # dumping
    # ------------------------------------------------------------------

    def dump(self, reason: str, dump_dir: Optional[str] = None,
             **context: Any) -> Optional[str]:
        """Dump the whole ring: a structured document appended to the
        bounded in-memory ``dump_docs`` list, written as a JSON artifact
        when a dump dir is configured, and summarized to the log. Dumps
        within ``dump_suppress_s`` of the previous one are suppressed
        (any reason — the first trigger of an episode owns the ring).
        Returns the artifact path, or None when in-memory only or
        suppressed."""
        t = self.clock.monotonic()
        with self._lock:
            if (
                self._last_dump_at is not None
                and t - self._last_dump_at < self.dump_suppress_s
            ):
                self.dumps_suppressed += 1
                return None
            self._last_dump_at = t
            self.dumps += 1
            ordinal = self.dumps
            dropped = self.dropped
        records = [r.to_dict() for r in self.records()]
        doc = {
            "reason": reason,
            "node": self.node_id,
            "t": round(t, 9),
            "ordinal": ordinal,
            "context": {
                k: (round(v, 9) if isinstance(v, float) else v)
                for k, v in context.items()
            },
            "dropped": dropped,
            "records": records,
        }
        with self._lock:
            self.dump_docs.append(doc)
            if len(self.dump_docs) > MAX_DUMP_DOCS:
                self.dump_docs.pop(0)
        path = None
        directory = dump_dir if dump_dir is not None else self.dump_dir
        if directory:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory,
                f"flightrec-node{self.node_id}-{ordinal:02d}-{reason}.json",
            )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
        self.logger.warning(
            "flight recorder dump (%s): %d records, node %d%s",
            reason, len(records), self.node_id,
            f" -> {path}" if path else " (in-memory)",
        )
        return path
