"""Cluster health plane (ISSUE 20): a federated consensus observatory.

Every observability layer before this one — metrics (ISSUE 4), causal
traces (ISSUE 5), the flight recorder + SLOs (ISSUE 7), decision
provenance (ISSUE 14), the device-time ledger (ISSUE 19) — is strictly
node-local, yet Babble's correctness and liveness properties are
*cluster* properties: commit-frontier agreement, bounded round-advance
skew, quorum reachability. The `ClusterObservatory` closes that gap:

- each node assembles a compact, versioned `HealthDigest` (commit
  frontier + block-hash prefix, round frontier, undecided-witness
  count and oldest-undecided age, tx/ingress backlog, signature
  backlog, engine-ladder rung, fork-evidence count, peer-staleness
  vector) and piggybacks it **out-of-band** on sync payloads exactly
  like the `Traces` key — wire hashes and signatures untouched, no new
  RPCs; a pull fallback (`GET /health/digest`) covers non-gossiping
  observers;
- digests gossip transitively (a node forwards its whole fleet table),
  so every node converges on an eventually-consistent fleet view;
- from the fleet table the observatory derives the series node-local
  metrics cannot express: `babble_cluster_commit_skew_blocks`,
  `babble_cluster_round_skew`, `babble_cluster_frontier_agreement`
  (a live safety canary — peers' block-hash prefixes checked against
  our own chain at the common frontier), a per-peer lag matrix with
  bounded labels, and `babble_cluster_fame_latency_rounds`;
- **partition inference** from mutual-staleness asymmetry: sync
  failures are classified by *kind* — a refusal (connection refused,
  "peer down", "not ready") proves the path answers and is NOT
  partition evidence; only *silence* (timeouts, dropped/partitioned
  links) accumulates. A peer silent past the staleness deadline while
  other peers stay fresh is the asymmetry signature of a partition
  (a fully-isolated or crashed node sees every path fail and never
  self-diagnoses a partition — by design, that is the watchdog's
  job). Rising/falling edges emit `cluster.partition_suspected` /
  `cluster.partition_healed` flight records with an automatic
  flight-recorder dump, one record per episode.

Determinism contract: everything times through the injected Clock, so
under the sim the fleet table, derived series and suspicion components
are byte-identical across same-seed runs —
`SimCluster.result()["cluster_health"]` fingerprints them.

Series and record names on observatory receivers must be static string
literals — the `obs-cluster-static-name` analysis rule enforces it.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "ClusterObservatory",
    "HealthDigest",
    "DIGEST_VERSION",
    "MAX_FLEET",
    "failure_kind",
]

# Digest schema version. Compat rule: entries missing the required keys
# (v/addr/t/block) are dropped; any v >= 1 entry is accepted field-wise
# with unknown keys carried opaquely (newer nodes may add fields old
# nodes forward untouched); per-origin merge is newest-t-wins.
DIGEST_VERSION = 1

# Fleet-table bound: beyond this many distinct origins, unknown origins
# are dropped (matches MAX_LABEL_SETS so the lag matrix never overflows
# into the collapsed `other` series before the table itself saturates).
MAX_FLEET = 64

# A digest older than stale_factor * staleness deadline is excluded from
# the derived series (a crashed peer's last digest must not pin the
# cluster skew forever) but stays in the fleet table, age-annotated.
STALE_DIGEST_FACTOR = 3.0

# Consecutive silent failures required before a peer counts as silent —
# a single dropped packet on a lossy (non-partitioned) link must not
# trip suspicion (false-positive guard).
MIN_SILENT_FAILS = 2

# Substrings that mark a sync failure as *silence* (no answer from the
# far side) rather than *refusal* (the path answered with an error).
# Sim transport reasons: "partitioned: a -/- b", "dropped: a -> b";
# real TCP: "timed out" / "timeout". Everything else — connection
# refused, "peer down", "node down", "not ready", app-level errors —
# proves reachability and therefore clears silence.
_SILENCE_MARKERS = ("partitioned", "dropped", "timed out", "timeout")

HealthDigest = Dict[str, Any]


def failure_kind(err: Any) -> str:
    """Classify a sync failure as "silence" or "refusal" (see module
    docstring). The classification keys off the error text because the
    transports funnel every failure through one exception type."""
    msg = str(err).lower()
    if any(marker in msg for marker in _SILENCE_MARKERS):
        return "silence"
    return "refusal"


class _Contact:
    """Per-peer reachability ledger feeding partition inference."""

    __slots__ = ("last_ok", "silent_since", "silent_fails")

    def __init__(self) -> None:
        self.last_ok: Optional[float] = None
        self.silent_since: Optional[float] = None
        self.silent_fails: int = 0


class ClusterObservatory:
    """Federates per-node `HealthDigest`s into derived cluster series,
    a queryable fleet table, and partition suspicion. One per node,
    constructed by `Observability`; dormant until `bind_local`."""

    def __init__(self, obs) -> None:
        self.obs = obs
        self.clock = obs.clock
        self.flightrec = obs.flightrec
        self.enabled = False  # unguarded-ok: bool flag set once at bind_local; racy fast-path reads are benign
        self.addr: Optional[str] = None  # unguarded-ok: set once at bind_local before gossip starts; str reads are atomic
        self.staleness_deadline = 5.0  # guarded-by: _lock
        self._digest_fn: Optional[Callable[[], Dict[str, Any]]] = None  # guarded-by: _lock
        self._block_hash_fn: Optional[Callable[[int], str]] = None  # guarded-by: _lock
        self._lock = threading.RLock()
        self._fleet: Dict[str, HealthDigest] = {}  # guarded-by: _lock
        # local receive time per origin: digest liveness is judged by
        # when WE last heard a fresh digest, not by the origin's own
        # timestamp — peers' monotonic epochs are not comparable across
        # real processes (they are in the sim, but the sim must not be
        # the only place staleness works)
        self._seen: Dict[str, float] = {}  # guarded-by: _lock
        self._contacts: Dict[str, _Contact] = {}  # guarded-by: _lock
        self._suspected = False  # guarded-by: _lock
        self._components: List[List[str]] = []  # guarded-by: _lock
        self._suspect_since: Optional[float] = None  # guarded-by: _lock

        reg = obs.registry
        reg.gauge(
            "babble_cluster_size",
            "Distinct nodes in the local fleet table (self included)",
        ).set_function(lambda: float(len(self.fleet())))
        reg.gauge(
            "babble_cluster_commit_skew_blocks",
            "Max minus min committed block index across live digests",
        ).set_function(lambda: self.series_value("babble_cluster_commit_skew_blocks"))
        reg.gauge(
            "babble_cluster_round_skew",
            "Max minus min consensus round across live digests",
        ).set_function(lambda: self.series_value("babble_cluster_round_skew"))
        reg.gauge(
            "babble_cluster_frontier_agreement",
            "Fraction of comparable digests whose block-hash prefix "
            "matches our chain at their frontier (safety canary)",
        ).set_function(lambda: self.series_value("babble_cluster_frontier_agreement"))
        reg.gauge(
            "babble_cluster_fame_latency_rounds",
            "Oldest undecided-witness age, in rounds, across the fleet",
        ).set_function(lambda: self.series_value("babble_cluster_fame_latency_rounds"))
        reg.gauge(
            "babble_cluster_partition_suspected",
            "1 while a partition is suspected from staleness asymmetry",
        ).set_function(lambda: self.series_value("babble_cluster_partition_suspected"))
        # per-peer lag matrix: written (not set_function) inside check()
        # because labelled series have no pull-time callback form
        self._lag_gauge = reg.gauge(
            "babble_cluster_peer_lag_blocks",
            "Our committed block index minus the peer's (positive: peer "
            "lags us; negative: peer is ahead)",
            labels=("peer",),
        )

    # -- wiring ------------------------------------------------------------

    def bind_local(
        self,
        addr: str,
        digest_fn: Callable[[], Dict[str, Any]],
        block_hash_fn: Optional[Callable[[int], str]] = None,
        enabled: bool = True,
        staleness_deadline: float = 5.0,
    ) -> None:
        """Attach the node-side providers: `digest_fn` returns the digest
        body (block/bh/round/undecided/...), `block_hash_fn(index)` our
        own block-hash prefix at an index (for frontier agreement)."""
        with self._lock:
            self.addr = addr
            self._digest_fn = digest_fn
            self._block_hash_fn = block_hash_fn
            self.enabled = bool(enabled)
            self.staleness_deadline = float(staleness_deadline)

    # -- digest assembly / federation --------------------------------------

    def local_digest(self) -> HealthDigest:
        """Our own versioned digest, freshly assembled. Empty dict until
        bind_local (bare Observability in unit tests)."""
        with self._lock:
            if self.addr is None or self._digest_fn is None:
                return {}
            d: HealthDigest = {
                "v": DIGEST_VERSION,
                "id": self.obs.node_id,
                "addr": self.addr,
                "t": round(float(self.clock.monotonic()), 9),
            }
            try:
                d.update(self._digest_fn() or {})
            except Exception:  # noqa: BLE001 — a broken provider must not
                pass  # take gossip down; the digest just stays sparse
            now = self.clock.monotonic()
            d["stale"] = {
                peer: round(float(now - c.last_ok), 9)
                for peer, c in sorted(self._contacts.items())
                if c.last_ok is not None
            }
            return d

    def wire_digests(self) -> List[HealthDigest]:
        """The out-of-band payload for a sync response/push: our own
        fresh digest plus every absorbed peer digest (transitive gossip).
        Empty when disabled, so the wire key is omitted and payloads stay
        byte-identical to an undigested build."""
        if not self.enabled:
            return []
        own = self.local_digest()
        if not own:
            return []
        with self._lock:
            self._store_own(own)
            return [self._fleet[a] for a in sorted(self._fleet)]

    def _store_own(self, own: HealthDigest) -> None:  # requires-lock: _lock
        self._fleet[self.addr] = own  # type: ignore[index]
        self._seen[self.addr] = float(self.clock.monotonic())  # type: ignore[index]

    def absorb(self, entries: Optional[Sequence[HealthDigest]]) -> None:
        """Merge piggybacked digests into the fleet table: validated,
        newest-t-wins per origin, own addr never absorbed, MAX_FLEET
        bound (known origins update; novel ones drop when full)."""
        if not self.enabled or not entries:
            return
        with self._lock:
            for e in entries:
                if not isinstance(e, dict):
                    continue
                addr = e.get("addr")
                if (
                    not isinstance(e.get("v"), int)
                    or e["v"] < 1
                    or not isinstance(addr, str)
                    or not isinstance(e.get("t"), (int, float))
                    or not isinstance(e.get("block"), int)
                ):
                    continue  # compat rule: required keys or drop
                if addr == self.addr:
                    continue
                now = float(self.clock.monotonic())
                prev = self._fleet.get(addr)
                if prev is not None and prev.get("t", 0) >= e["t"]:
                    # newest-t wins within one origin incarnation — but a
                    # restarted origin's monotonic clock regressed, so an
                    # entry we have not refreshed for a full staleness
                    # horizon loses to ANY fresh digest
                    horizon = STALE_DIGEST_FACTOR * self.staleness_deadline
                    if now - self._seen.get(addr, now) <= horizon:
                        continue
                if prev is None and len(self._fleet) >= MAX_FLEET:
                    continue  # bounded table
                self._fleet[addr] = e
                self._seen[addr] = now

    # -- contact ledger (partition-inference input) ------------------------

    def note_contact(
        self,
        peer: str,
        ok: bool,
        t_start: Optional[float] = None,
        err: Any = None,
    ) -> None:
        """Record one sync exchange outcome with `peer`. `t_start` is the
        exchange *start* time: silence is backdated to it, so a long
        transport timeout does not also delay partition detection."""
        if not self.enabled or not peer:
            return
        with self._lock:
            c = self._contacts.setdefault(peer, _Contact())
            if ok:
                c.last_ok = float(self.clock.monotonic())
                c.silent_since = None
                c.silent_fails = 0
            elif failure_kind(err) == "silence":
                if c.silent_since is None:
                    c.silent_since = float(
                        t_start if t_start is not None else self.clock.monotonic()
                    )
                c.silent_fails += 1
            else:
                # a refusal proves the path answers: not partition evidence
                c.silent_since = None
                c.silent_fails = 0

    # -- suspicion state machine -------------------------------------------

    def check(self) -> None:
        """Heartbeat hook: refresh the lag matrix and run the partition
        suspicion edge detector. Cheap; call once per node tick."""
        if not self.enabled:
            return
        with self._lock:
            now = float(self.clock.monotonic())
            deadline = self.staleness_deadline
            own = self.local_digest()
            if own:
                self._store_own(own)
                own_block = int(own.get("block", -1))
                for addr in sorted(self._fleet):
                    if addr == self.addr:
                        continue
                    peer_block = self._fleet[addr].get("block")
                    if isinstance(peer_block, int):
                        self._lag_gauge.labels(peer=addr).set(
                            float(own_block - peer_block)
                        )
            # a peer is partition-silent only when BOTH channels died:
            # direct contact (>= MIN_SILENT_FAILS consecutive silent
            # failures spanning the deadline) AND its federated digest
            # (no fresh digest via ANY path within the deadline). On a
            # merely lossy link the peer's digest keeps arriving
            # relayed through third parties, so loss never qualifies —
            # only a true cut starves both channels.
            silent = sorted(
                p
                for p, c in self._contacts.items()
                if c.silent_since is not None
                and now - c.silent_since >= deadline
                and c.silent_fails >= MIN_SILENT_FAILS
                and (
                    p not in self._seen
                    or now - self._seen[p] >= deadline
                )
            )
            # fresh counter-evidence must POSTDATE the silence: a
            # last_ok from just before a full cut would otherwise let
            # the isolated minority itself claim the asymmetry
            silence_start = min(
                (
                    self._contacts[p].silent_since
                    for p in silent
                    if self._contacts[p].silent_since is not None
                ),
                default=None,
            )
            fresh = sorted(
                p
                for p, c in self._contacts.items()
                if c.last_ok is not None
                and now - c.last_ok <= deadline
                and (silence_start is None or c.last_ok >= silence_start)
            )
            suspected = bool(silent) and bool(fresh)
            if suspected and not self._suspected:
                self._suspected = True
                self._suspect_since = now
                # near side = everyone known to the fleet table who is
                # not silent (self included): fresh contacts alone would
                # omit reachable peers we simply have not gossiped with
                # recently, under-reporting the majority component
                near = sorted(
                    (set([self.addr or ""]) | set(self._fleet) | set(fresh))
                    - set(silent)
                )
                self._components = sorted(
                    [silent, near], key=lambda c: c[0] if c else ""
                )
                self.flightrec.record(
                    "cluster.partition_suspected",
                    components=json.dumps(self._components),
                    silent=len(silent),
                    fresh=len(fresh),
                )
                self.flightrec.dump(
                    "partition-suspected",
                    components=json.dumps(self._components),
                )
            elif self._suspected and not silent:
                # falling edge: every silent peer answered again (or its
                # silence was reclassified by a refusal)
                since = self._suspect_since if self._suspect_since is not None else now
                self._suspected = False
                self._suspect_since = None
                self._components = []
                self.flightrec.record(
                    "cluster.partition_healed",
                    duration=round(now - since, 9),
                )

    # -- derived series / queries ------------------------------------------

    def fleet(self) -> Dict[str, HealthDigest]:
        """Copy of the fleet table (own fresh digest included)."""
        with self._lock:
            own = self.local_digest()
            if own:
                self._store_own(own)
            return {a: dict(self._fleet[a]) for a in sorted(self._fleet)}

    def _live_digests(self) -> List[HealthDigest]:  # requires-lock: _lock
        now = float(self.clock.monotonic())
        horizon = STALE_DIGEST_FACTOR * self.staleness_deadline
        return [
            d
            for a, d in sorted(self._fleet.items())
            if now - self._seen.get(a, now) <= horizon
        ]

    def derived(self) -> Dict[str, float]:
        """All derived cluster series, from live digests only."""
        with self._lock:
            own = self.local_digest()
            if own:
                self._store_own(own)
            live = self._live_digests()
            blocks = [int(d["block"]) for d in live if isinstance(d.get("block"), int)]
            rounds = [
                int(d["round"])
                for d in live
                if isinstance(d.get("round"), int) and d["round"] >= 0
            ]
            ages = [
                int(d["oldest_age"])
                for d in live
                if isinstance(d.get("oldest_age"), int)
            ]
            agreement = self._frontier_agreement(own, live)
            return {
                "babble_cluster_size": float(len(live)),
                "babble_cluster_commit_skew_blocks": float(
                    max(blocks) - min(blocks) if blocks else 0
                ),
                "babble_cluster_round_skew": float(
                    max(rounds) - min(rounds) if rounds else 0
                ),
                "babble_cluster_frontier_agreement": agreement,
                "babble_cluster_fame_latency_rounds": float(
                    max(ages) if ages else 0
                ),
                "babble_cluster_partition_suspected": float(self._suspected),
            }

    def _frontier_agreement(  # requires-lock: _lock
        self, own: HealthDigest, live: List[HealthDigest]
    ) -> float:
        """Safety canary: of the live digests whose frontier we can check
        (their committed index <= ours), what fraction carry a block-hash
        prefix matching our own chain at that index? Self always agrees;
        1.0 when nothing is comparable. Any value below 1.0 on a healthy
        cluster means two nodes committed different blocks at the same
        index — the one anomaly that must never be smoothed over."""
        own_block = int(own.get("block", -1)) if own else -1
        comparable, agree = 1, 1  # self
        if self._block_hash_fn is None:
            return 1.0
        for d in live:
            addr = d.get("addr")
            if addr == self.addr:
                continue
            peer_block = d.get("block")
            peer_prefix = d.get("bh")
            if (
                not isinstance(peer_block, int)
                or peer_block < 0
                or peer_block > own_block
                or not isinstance(peer_prefix, str)
                or not peer_prefix
            ):
                continue
            try:
                mine = self._block_hash_fn(peer_block) or ""
            except Exception:  # noqa: BLE001 — pruned store window
                continue
            if not mine:
                continue
            comparable += 1
            n = min(len(mine), len(peer_prefix))
            if mine[:n] == peer_prefix[:n]:
                agree += 1
        return round(agree / comparable, 9)

    def series_value(self, name: str) -> float:
        """One derived series by its exported name (static literals only —
        enforced by the obs-cluster-static-name rule at call sites)."""
        return float(self.derived().get(name, 0.0))

    def flag(self, name: str, **fields: Any) -> None:
        """Emit a cluster-scope flight record (static literal names only —
        enforced by the obs-cluster-static-name rule at call sites)."""
        self.flightrec.record(name, **fields)  # obs-ok: delegate, name checked at call sites

    def suspicion(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "suspected": self._suspected,
                "components": [list(c) for c in self._components],
                "since": (
                    round(float(self._suspect_since), 9)
                    if self._suspect_since is not None
                    else None
                ),
            }

    def snapshot(self) -> Dict[str, Any]:
        """The full health plane, as served by `GET /debug/cluster` and
        rendered by `babble-tpu status`."""
        with self._lock:
            fleet = self.fleet()
            # `now` read after fleet() so the just-refreshed own digest
            # cannot show a negative age
            now = float(self.clock.monotonic())
            for a, d in fleet.items():
                d["age"] = round(max(0.0, now - self._seen.get(a, now)), 9)
            contacts = {
                p: {
                    "last_ok_age": (
                        round(now - c.last_ok, 9) if c.last_ok is not None else None
                    ),
                    "silent_for": (
                        round(now - c.silent_since, 9)
                        if c.silent_since is not None
                        else None
                    ),
                    "silent_fails": c.silent_fails,
                }
                for p, c in sorted(self._contacts.items())
            }
            return {
                "addr": self.addr,
                "enabled": self.enabled,
                "t": round(now, 9),
                "staleness_deadline": self.staleness_deadline,
                "fleet": fleet,
                "derived": self.derived(),
                "suspicion": self.suspicion(),
                "contacts": contacts,
            }

    # -- determinism fingerprint -------------------------------------------

    def health_doc(self) -> Dict[str, Any]:
        """The deterministic slice of the health plane: derived series
        plus suspicion, floats pre-rounded — the sim's
        `cluster_health_fingerprint` hashes the canonical JSON of one of
        these per node."""
        derived = {k: round(v, 9) for k, v in sorted(self.derived().items())}
        return {"derived": derived, "suspicion": self.suspicion()}

    def stream_bytes(self) -> bytes:
        """Canonical JSON bytes of `health_doc` (sorted keys, compact
        separators — same convention as FlightRecorder.stream_bytes)."""
        return json.dumps(
            self.health_doc(), sort_keys=True, separators=(",", ":")
        ).encode()
