"""SLO engine: declared objectives over registry series, with
multi-window burn-rate evaluation on the injected Clock (ISSUE 7).

The metrics registry (metrics.py) records what happened; this module
turns a handful of those series into pass/fail *objectives* — the
ROADMAP's "p50/p99 submit->commit SLO gates" made executable. Each
objective reads one existing series (a histogram's buckets/sum/count or
a gauge/counter value), and ``evaluate()`` keeps a bounded Clock-pruned
sample history so burn rates are computed over deltas per window — the
SRE multi-window pattern: an objective only *breaches* when EVERY
configured window is burning past the threshold, so a transient spike
(short window hot, long window fine) pages nobody while a sustained
regression (all windows hot) does.

Evaluation is driven from the same seams as the liveness watchdog: the
threaded node's `_babble` tick and the sim's `_tick`, both on the
injected Clock — same-seed sim runs evaluate at identical virtual
times and produce byte-identical `babble_slo_*` gauges. Before the
first window has elapsed the baseline is the engine's start point, so a
one-shot evaluation (the `bench.py --slo` gate) degrades to cumulative
evaluation over the whole run — exactly what a bench wants.

A breach transition appends an `slo.breach` flight record and triggers
a flight-recorder dump (reason `slo-breach`), closing the observe →
triage loop.

Objective and series names are static string literals at call sites,
enforced by the `obs-slo-decl` lint rule (analysis/obs.py) — declare
objectives on a receiver *named* ``slo`` so the rule sees them.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from .metrics import Counter, Gauge, Histogram

# default burn-rate evaluation windows, Clock seconds: a fast window
# that reacts within a sim run / soak and a slow one that filters noise
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 300.0)

# burn >= this in EVERY window = breach (1.0 = consuming error budget
# exactly at the rate that exhausts it over the objective period)
DEFAULT_BURN_THRESHOLD = 1.0

# guard against division by zero in ratio math
_TINY = 1e-12


class SLObjective:
    """One declared objective over one registry series.

    kinds:
      - ``p_below``   histogram: the ``quantile`` of observations must
                      sit at or below ``threshold`` (good = obs <=
                      threshold; budget = 1 - quantile)
      - ``mean_below`` histogram: windowed mean must be <= threshold
      - ``mean_above`` histogram: windowed mean must be >= threshold
      - ``below``     gauge/counter: sampled value must be <= threshold
      - ``above``     gauge/counter: sampled value must be >= threshold
    """

    KINDS = ("p_below", "mean_below", "mean_above", "below", "above")

    __slots__ = ("name", "series", "kind", "threshold", "quantile",
                 "budget", "labels", "description")

    def __init__(self, name: str, series: str, kind: str, threshold: float,
                 quantile: Optional[float] = None,
                 budget: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None,
                 description: str = ""):
        if kind not in self.KINDS:
            raise ValueError(f"{name}: unknown objective kind {kind!r}")
        if kind == "p_below":
            if quantile is None:
                quantile = 0.99
            if budget is None:
                budget = max(1.0 - quantile, _TINY)
        self.name = name
        self.series = series
        self.kind = kind
        self.threshold = float(threshold)
        self.quantile = quantile
        self.budget = budget
        self.labels = dict(labels) if labels else {}
        self.description = description


class SLOEngine:
    """Evaluates declared objectives against the node's registry.

    ``evaluate()`` is cheap (a handful of dict reads) and must be
    called periodically from a Clock-driven tick; it samples every
    objective's underlying series, prunes history past the longest
    window, computes per-window burn rates, updates the
    ``babble_slo_*`` gauges and fires ``on_breach`` + a flight-recorder
    dump on the transition into breach."""

    def __init__(self, obs, windows: Sequence[float] = DEFAULT_WINDOWS,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 on_breach: Optional[Callable[[str, dict], None]] = None,
                 logger: Optional[logging.Logger] = None):
        self.obs = obs
        self.registry = obs.registry
        self.clock = obs.clock
        self.windows = tuple(sorted(windows))
        self.burn_threshold = burn_threshold
        self.on_breach = on_breach
        self.logger = logger if logger is not None else logging.getLogger(
            "babble.slo"
        )
        # unguarded-ok: objectives are declared during single-threaded
        # boot and the dict is read-only once the tick loop starts
        self._objectives: Dict[str, SLObjective] = {}
        # serializes evaluate() between the tick loop and /debug/slo
        self._lock = threading.Lock()
        # guarded-by: _lock — (t, {objective: reading}), pruned past the
        # longest window
        self._samples: Deque[Tuple[float, Dict[str, dict]]] = deque()
        self._t0 = self.clock.monotonic()
        self._breached: Dict[str, bool] = {}  # guarded-by: _lock
        self._g_burn = obs.gauge(
            "babble_slo_burn_rate",
            "Error-budget burn rate per objective and window (>= 1 in "
            "every window = breach)",
            labels=("objective", "window"),
        )
        self._g_breached = obs.gauge(
            "babble_slo_breached",
            "1 while the objective is burning past threshold in every "
            "window",
            labels=("objective",),
        )
        self._m_breaches = obs.counter(
            "babble_slo_breaches_total",
            "Breach transitions per objective since boot",
            labels=("objective",),
        )

    # ------------------------------------------------------------------
    # declaration
    # ------------------------------------------------------------------

    def objective(self, name: str, series: str, kind: str, threshold: float,
                  quantile: Optional[float] = None,
                  budget: Optional[float] = None,
                  labels: Optional[Dict[str, str]] = None,
                  description: str = "") -> SLObjective:
        """Declare one objective. ``name`` and ``series`` must be static
        string literals at the call site (obs-slo-decl lint rule)."""
        if name in self._objectives:
            raise ValueError(f"objective {name!r} already declared")
        obj = SLObjective(name, series, kind, threshold, quantile=quantile,
                          budget=budget, labels=labels,
                          description=description)
        self._objectives[name] = obj
        # unguarded-ok: declaration happens at boot, before the tick loop
        self._breached[name] = False
        self._g_breached.labels(objective=name).set(0.0)
        return obj

    def objectives(self) -> List[SLObjective]:
        return list(self._objectives.values())

    # ------------------------------------------------------------------
    # sampling
    # ------------------------------------------------------------------

    def _read(self, obj: SLObjective) -> dict:
        """Cumulative reading of the objective's series: histogram ->
        {count, sum, good}; gauge/counter -> {value}. Missing series
        read as zeros (an objective over a path the node never took
        simply has no data and cannot breach)."""
        metric = self.registry.get(obj.series)
        if metric is None:
            return {}
        if isinstance(metric, Histogram):
            key = ",".join(
                str(obj.labels.get(ln, "")) for ln in metric.label_names
            )
            snap = metric.snapshot()["series"].get(key)
            if snap is None:
                return {}
            good = snap["count"]
            if obj.kind == "p_below":
                # largest bucket upper bound at or below the threshold:
                # conservative (undercounts good, never bad)
                good = 0
                for le, cum in snap["buckets"]:
                    if float(le) <= obj.threshold * (1.0 + 1e-9):
                        good = cum
                    else:
                        break
            return {"count": snap["count"], "sum": snap["sum"],
                    "good": good}
        if isinstance(metric, (Gauge, Counter)):
            return {"value": metric.value(**obj.labels)}
        return {}

    @staticmethod
    def _delta(cur: dict, base: Optional[dict], field: str) -> float:
        if not cur:
            return 0.0
        b = base.get(field, 0.0) if base else 0.0
        return float(cur.get(field, 0.0)) - float(b)

    def _burn(self, obj: SLObjective, cur: dict, base: Optional[dict],
              gauge_samples: List[float]) -> Optional[float]:
        """Burn rate for one window; None = no data in the window."""
        if obj.kind in ("below", "above"):
            if not gauge_samples:
                return None
            mean = sum(gauge_samples) / len(gauge_samples)
            if obj.kind == "below":
                return mean / max(obj.threshold, _TINY)
            return obj.threshold / max(mean, _TINY)
        dc = self._delta(cur, base, "count")
        if dc <= 0:
            return None
        if obj.kind == "p_below":
            bad = dc - self._delta(cur, base, "good")
            return (bad / dc) / max(obj.budget or _TINY, _TINY)
        mean = self._delta(cur, base, "sum") / dc
        if obj.kind == "mean_below":
            return mean / max(obj.threshold, _TINY)
        return obj.threshold / max(mean, _TINY)

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------

    def evaluate(self) -> Dict[str, Any]:
        """One evaluation pass; returns the same document `status()`
        serves. Call from the node/sim tick or once for a bench gate."""
        with self._lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> Dict[str, Any]:  # requires-lock: _lock
        now = self.clock.monotonic()
        readings = {n: self._read(o) for n, o in self._objectives.items()}
        self._samples.append((now, readings))
        horizon = now - (self.windows[-1] if self.windows else 0.0)
        while len(self._samples) > 1 and self._samples[1][0] <= horizon:
            self._samples.popleft()

        results = []
        for name, obj in self._objectives.items():
            cur = readings[name]
            burns: Dict[str, Optional[float]] = {}
            any_data = False
            all_burning = True
            for w in self.windows:
                start = now - w
                # newest sample at or before the window start is the
                # baseline; before one exists, t0 (engine start) is —
                # so a young engine evaluates cumulatively
                base: Optional[dict] = None
                for t, r in self._samples:
                    if t <= start:
                        base = r.get(name)
                    else:
                        break
                gauge_samples = [
                    float(r[name]["value"])
                    for t, r in self._samples
                    if t > start and r.get(name) and "value" in r[name]
                ]
                burn = self._burn(obj, cur, base, gauge_samples)
                label = f"{int(w)}s"
                burns[label] = burn
                if burn is None:
                    all_burning = False
                else:
                    any_data = True
                    self._g_burn.labels(objective=name, window=label).set(
                        burn
                    )
                    if burn < self.burn_threshold:
                        all_burning = False
            breached = any_data and all_burning
            was = self._breached[name]
            self._breached[name] = breached
            self._g_breached.labels(objective=name).set(
                1.0 if breached else 0.0
            )
            doc = {
                "name": name,
                "series": obj.series,
                "kind": obj.kind,
                "threshold": obj.threshold,
                "quantile": obj.quantile,
                "description": obj.description,
                "burn": {
                    k: (round(v, 6) if v is not None else None)
                    for k, v in burns.items()
                },
                "breached": breached,
            }
            results.append(doc)
            if breached and not was:
                self._on_breach_transition(name, obj, doc)
        return {
            "t": round(now, 9),
            "burn_threshold": self.burn_threshold,
            "windows": [f"{int(w)}s" for w in self.windows],
            "objectives": results,
        }

    def _on_breach_transition(self, name: str, obj: SLObjective,
                              doc: dict) -> None:
        self._m_breaches.labels(objective=name).inc()
        self.logger.warning(
            "SLO breach: %s (%s %s vs threshold %g) burning in every "
            "window %s",
            name, obj.series, obj.kind, obj.threshold, doc["burn"],
        )
        flightrec = getattr(self.obs, "flightrec", None)
        if flightrec is not None:
            flightrec.record(
                "slo.breach", objective=name, series=obj.series,
                kind=obj.kind, threshold=obj.threshold,
            )
            flightrec.dump("slo-breach", objective=name)
        if self.on_breach is not None:
            try:
                self.on_breach(name, doc)
            except Exception:  # noqa: BLE001 — a broken callback must
                pass  # not take the evaluation tick down

    # ------------------------------------------------------------------
    # reporting
    # ------------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Document for ``GET /debug/slo`` — a fresh evaluation, so the
        endpoint always reflects the current registry state."""
        return self.evaluate()

    def breached(self) -> List[str]:
        """Names of currently-breached objectives (bench gates)."""
        # unguarded-ok: racy boolean snapshot; bench gates tolerate staleness
        return [n for n, b in self._breached.items() if b]
