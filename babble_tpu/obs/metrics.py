"""Typed metrics registry with Prometheus text exposition (ISSUE 4).

Three metric kinds — counters, gauges, log-bucketed histograms — each
declared once by a STATIC name (the `obs-*` lint family rejects computed
names) with an optional declared label set. Series cardinality is
bounded by construction: once a metric holds `MAX_LABEL_SETS` distinct
label combinations, further novel combinations collapse into a single
`other` series, so a buggy or adversarial label value can never grow the
registry without bound.

Determinism contract: the registry never reads a clock or RNG — every
observed value arrives from the caller, who measures through the
injected Clock seam (common/clock.py). Under the simulator's SimClock,
two runs of the same seed therefore produce byte-identical exposition
and snapshots (the sim's latency-histogram fingerprint rides on this).
Rendering sorts metrics by name and series by label values, so output
order never depends on declaration or observation interleaving.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# hard per-metric cap on distinct label-value combinations; the overflow
# series keeps totals right while freezing cardinality
MAX_LABEL_SETS = 64
OVERFLOW_LABEL = "other"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """`count` log-spaced histogram bounds: start, start*factor, ... ."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("log_buckets needs start>0, factor>1, count>=1")
    out: List[float] = []
    v = float(start)
    for _ in range(count):
        out.append(v)
        v *= factor
    return tuple(out)


# 1 ms .. ~65 s: spans gossip round-trips, consensus passes and commit
# latency on one axis (the +Inf bucket absorbs pathological stalls)
DEFAULT_LATENCY_BUCKETS = log_buckets(0.001, 2.0, 17)
# 64 B .. 16 MiB: wire frames (DEFAULT_MAX_FRAME is 64 MiB -> +Inf tail)
DEFAULT_SIZE_BUCKETS = log_buckets(64, 4.0, 10)
# 1 .. 1024 items: event counts per sync payload (sync_limit-bounded)
DEFAULT_COUNT_BUCKETS = log_buckets(1, 2.0, 11)


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render without the dot."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Child:
    """A metric bound to one label-value combination."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Metric", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, -amount)

    def set(self, value: float) -> None:
        self._metric._set(self._key, value)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        self._metric._observe(self._key, value, exemplar=exemplar)


class Metric:
    """Base: name, declared label set, bounded series map."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        self.name = name
        self.help = help_text
        self.label_names: Tuple[str, ...] = tuple(label_names)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}  # guarded-by: _lock

    # -- label resolution --------------------------------------------------

    def labels(self, **kv: str) -> _Child:
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: got labels {sorted(kv)}, "
                f"declared {list(self.label_names)}"
            )
        # the child carries the RAW key; the cardinality bound is applied
        # inside each mutation op while self._lock is held, so admission
        # and insertion are one atomic step (deciding here and inserting
        # later let two first-callers overshoot MAX_LABEL_SETS)
        return _Child(self, tuple(str(kv[ln]) for ln in self.label_names))

    def _bind_locked(self, key: Tuple[str, ...]) -> Tuple[str, ...]:  # requires-lock: _lock
        """The declared-bounded cardinality guarantee: novel combinations
        past MAX_LABEL_SETS collapse into one `other` series. Must be
        called with self._lock held, immediately before the insertion it
        admits."""
        if key in self._series or len(self._series) < MAX_LABEL_SETS:
            return key
        return (OVERFLOW_LABEL,) * len(key)

    def _no_labels_key(self) -> Tuple[str, ...]:
        if self.label_names:
            raise ValueError(f"{self.name}: declared labels {self.label_names};"
                             " use .labels(...)")
        return ()

    # -- rendering ---------------------------------------------------------

    def _label_str(self, key: Tuple[str, ...]) -> str:
        if not key:
            return ""
        pairs = ",".join(
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.label_names, key)
        )
        return "{" + pairs + "}"

    def _sorted_series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._series.items())


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._inc(self._no_labels_key(), amount)

    def _inc(self, key: Tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            key = self._bind_locked(key)
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **kv: str) -> float:
        key = tuple(str(kv[ln]) for ln in self.label_names) if kv else ()
        with self._lock:
            return float(self._series.get(key, 0.0))  # type: ignore[arg-type]

    def render(self) -> List[str]:
        return [
            f"{self.name}{self._label_str(k)} {_fmt(v)}"  # type: ignore[arg-type]
            for k, v in self._sorted_series()
        ]

    def snapshot(self) -> dict:
        return {
            "type": self.kind,
            "series": {
                ",".join(k): v for k, v in self._sorted_series()
            },
        }


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name: str, help_text: str, label_names: Sequence[str] = ()):
        super().__init__(name, help_text, label_names)
        # pull-time callback for the unlabeled series (read at render)
        # unguarded-ok: rebound once at declaration time; racing readers
        # see None or the callback, both safe
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._set(self._no_labels_key(), value)

    def inc(self, amount: float = 1.0) -> None:
        self._inc(self._no_labels_key(), amount)

    def dec(self, amount: float = 1.0) -> None:
        self._inc(self._no_labels_key(), -amount)

    def set_function(self, fn: Callable[[], float]) -> "Gauge":
        """Evaluate `fn` at exposition time (live view of node state)."""
        self._no_labels_key()
        self._fn = fn
        return self

    def _set(self, key: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._series[self._bind_locked(key)] = float(value)

    def _inc(self, key: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            key = self._bind_locked(key)
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **kv: str) -> float:
        if self._fn is not None:
            return self._read_fn()
        key = tuple(str(kv[ln]) for ln in self.label_names) if kv else ()
        with self._lock:
            return float(self._series.get(key, 0.0))  # type: ignore[arg-type]

    def _read_fn(self) -> float:
        try:
            return float(self._fn())  # type: ignore[misc]
        except Exception:  # noqa: BLE001 — a broken callback must not
            return 0.0  # take the whole exposition down

    def render(self) -> List[str]:
        if self._fn is not None:
            return [f"{self.name} {_fmt(self._read_fn())}"]
        return [
            f"{self.name}{self._label_str(k)} {_fmt(v)}"  # type: ignore[arg-type]
            for k, v in self._sorted_series()
        ]

    def snapshot(self) -> dict:
        if self._fn is not None:
            return {"type": self.kind, "series": {"": self._read_fn()}}
        return {
            "type": self.kind,
            "series": {
                ",".join(k): v for k, v in self._sorted_series()
            },
        }


class Histogram(Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        label_names: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ):
        super().__init__(name, help_text, label_names)
        bs = tuple(buckets) if buckets is not None else DEFAULT_LATENCY_BUCKETS
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"{self.name}: buckets must strictly increase")
        self.buckets = bs
        # last exemplar per series (e.g. the trace_id of the latest
        # commit-latency observation): rendered as a `# EXEMPLAR` comment
        # in the exposition so a p99 breach links to a concrete trace
        self._exemplars: Dict[Tuple[str, ...], str] = {}  # guarded-by: _lock

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        self._observe(self._no_labels_key(), value, exemplar=exemplar)

    def _observe(self, key: Tuple[str, ...], value: float,
                 exemplar: Optional[str] = None) -> None:
        v = float(value)
        with self._lock:
            key = self._bind_locked(key)
            st = self._series.get(key)
            if st is None:
                # per-bucket counts (non-cumulative) + [sum, count]
                st = [[0] * (len(self.buckets) + 1), [0.0, 0]]
                self._series[key] = st
            counts, agg = st  # type: ignore[misc]
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            counts[i] += 1
            agg[0] += v
            agg[1] += 1
            if exemplar is not None:
                self._exemplars[key] = str(exemplar)

    def exemplar(self, **kv: str) -> Optional[str]:
        """Last exemplar attached to one series, or None."""
        key = tuple(str(kv[ln]) for ln in self.label_names) if kv else ()
        with self._lock:
            return self._exemplars.get(key)

    def stats(self, **kv: str) -> Tuple[int, float]:
        """(count, sum) of one series; (0, 0.0) when never observed."""
        key = tuple(str(kv[ln]) for ln in self.label_names) if kv else ()
        with self._lock:
            st = self._series.get(key)
            if st is None:
                return 0, 0.0
            return int(st[1][1]), float(st[1][0])  # type: ignore[index]

    def render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            exemplars = dict(self._exemplars)
        for key, st in self._sorted_series():
            counts, agg = st  # type: ignore[misc]
            cum = 0
            for le, c in zip(self.buckets, counts):
                cum += c
                lk = self._bucket_label(key, _fmt(le))
                out.append(f"{self.name}_bucket{lk} {cum}")
            cum += counts[-1]
            out.append(f"{self.name}_bucket{self._bucket_label(key, '+Inf')} {cum}")
            ls = self._label_str(key)
            out.append(f"{self.name}_sum{ls} {_fmt(agg[0])}")
            out.append(f"{self.name}_count{ls} {cum}")
            ex = exemplars.get(key)
            if ex is not None:
                # text format 0.0.4 has no native exemplar syntax; a
                # comment line keeps the exposition parseable everywhere
                # while still surfacing the trace link next to its series
                out.append(
                    f'# EXEMPLAR {self.name}{ls} trace_id="{_escape_label(ex)}"'
                )
        return out

    def _bucket_label(self, key: Tuple[str, ...], le: str) -> str:
        pairs = [
            f'{n}="{_escape_label(v)}"' for n, v in zip(self.label_names, key)
        ]
        pairs.append(f'le="{le}"')
        return "{" + ",".join(pairs) + "}"

    def snapshot(self) -> dict:
        series = {}
        with self._lock:
            exemplars = dict(self._exemplars)
        for key, st in self._sorted_series():
            counts, agg = st  # type: ignore[misc]
            cum, buckets = 0, []
            for le, c in zip(self.buckets, counts):
                cum += c
                buckets.append([_fmt(le), cum])
            entry = {
                "count": agg[1], "sum": agg[0], "buckets": buckets,
            }
            ex = exemplars.get(key)
            if ex is not None:
                # deterministic under the sim (trace ids hash tx bytes),
                # so including it keeps the snapshot fingerprint-safe
                entry["exemplar"] = ex
            series[",".join(key)] = entry
        return {"type": self.kind, "series": series}


class MetricsRegistry:
    """Get-or-create home for every metric of one node.

    Re-requesting a name returns the existing metric; a kind or label-set
    mismatch raises (two call sites silently disagreeing about a metric's
    shape is a bug, not a merge)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}  # guarded-by: _lock

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(
        self, name: str, help_text: str = "", labels: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, labels, buckets=buckets
        )

    def _get_or_create(self, cls, name, help_text, labels, **kw) -> Metric:
        # double-checked creation: a lock-free fast path for the hot
        # re-request case (a GIL-atomic dict read; never partially
        # constructed, since insertion below happens after construction,
        # under the lock), then re-check + create under the registry lock
        # so N concurrent first-callers all receive the SAME instance.
        # unguarded-ok: fast-path read; the locked slow path re-validates
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help_text, labels, **kw)
                    self._metrics[name] = m
                    return m
        if type(m) is not cls or m.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} re-declared as {cls.kind} "
                f"labels={tuple(labels)} (was {m.kind} "
                f"labels={m.label_names})"
            )
        return m

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, dict]:
        """Structured dict view (sim fingerprints, bench emission)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return {m.name: m.snapshot() for m in metrics}

    def snapshot_flat(self) -> Dict[str, float]:
        """One-level dict for structured logging: `name{labels}` -> value;
        histograms contribute `_count` and `_sum` entries."""
        out: Dict[str, float] = {}
        for name, snap in self.snapshot().items():
            if snap["type"] == "histogram":
                for key, st in snap["series"].items():
                    suffix = "{" + key + "}" if key else ""
                    out[f"{name}_count{suffix}"] = st["count"]
                    out[f"{name}_sum{suffix}"] = round(st["sum"], 9)
            else:
                for key, v in snap["series"].items():
                    suffix = "{" + key + "}" if key else ""
                    out[name + suffix] = v
        return out
