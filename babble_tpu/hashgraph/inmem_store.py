"""In-memory store backed by LRU caches (reference: src/hashgraph/inmem_store.go)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..common import LRU, RollingIndex, StoreErr, StoreErrType, is_store_err
from ..peers import Peers
from .block import Block
from .caches import ParticipantEventsCache
from .event import Event
from .frame import Frame
from .root import Root, new_base_root
from .round_info import RoundInfo
from .store import Store


# per-chain tail kept safe from eviction: incoming diff events reference
# parents this deep during ordinary gossip races (see _pin_event)
TAIL_PIN = 64


class InmemStore(Store):
    def __init__(self, participants: Peers, cache_size: int, pin_live: bool = True):
        # pin_live=False for write-through use under a persistent store
        # (SQLiteStore): evicted bodies are recoverable from disk there,
        # so the hard cache bound matters more than the pin
        self._cache_size = cache_size
        self._participants = participants
        self._pin = self._pin_event if pin_live else None
        self.event_cache = LRU(cache_size, pin=self._pin)
        self.round_cache = LRU(cache_size)
        self.block_cache = LRU(cache_size)
        self.frame_cache = LRU(cache_size)
        self.consensus_cache = RollingIndex("ConsensusCache", cache_size)
        self.tot_consensus_events = 0
        self.participant_events_cache = ParticipantEventsCache(cache_size, participants)
        self.roots_by_participant: Dict[str, Root] = {
            pk: new_base_root(peer.id) for pk, peer in participants.by_pub_key.items()
        }
        self._roots_by_self_parent: Optional[Dict[str, Root]] = None
        self._last_round = -1
        self.last_consensus_events: Dict[str, str] = {}  # [participant] => last consensus event hex
        self._last_block = -1

    def cache_size(self) -> int:
        return self._cache_size

    def _pin_event(self, key: str, ev: Event) -> bool:
        """LIVE event bodies are exempt from LRU eviction (round 5): a
        body the store's own known-events high-water still claims, but
        whose bytes are gone, livelocks the node — peers' diffs reference
        it as a parent, inserts fail forever, and over_sync_limit never
        trips because the high-water looks current (observed: a survivor
        wedged 960s on three evicted bodies). Live =
        (a) undetermined (no round-received yet: consensus still reads
            it, and a stall makes the undetermined window outgrow any
            fixed cache), or
        (b) within the newest TAIL_PIN of its creator's chain (diff
            inserts resolve parents this deep during gossip races).
        When everything in the scan budget is live the cache grows past
        its bound instead — memory degradation over DAG corruption."""
        if ev.round_received is None:
            return True
        peer = self._participants.by_pub_key.get(ev.creator())
        if peer is None:
            return False
        # single-chain high-water, not known() — the predicate runs per
        # eviction probe and known() materializes a dict over all N
        ri = self.participant_events_cache.rim.mapping.get(peer.id)
        high = ri.get_last_window()[1] if ri is not None else -1
        return ev.index() > high - TAIL_PIN

    def participants(self) -> Peers:
        return self._participants

    def roots_by_self_parent(self) -> Dict[str, Root]:
        if self._roots_by_self_parent is None:
            self._roots_by_self_parent = {
                root.self_parent.hash: root for root in self.roots_by_participant.values()
            }
        return self._roots_by_self_parent

    def get_event(self, key: str) -> Event:
        res, ok = self.event_cache.get(key)
        if not ok:
            raise StoreErr("EventCache", StoreErrType.KEY_NOT_FOUND, key)
        return res

    def set_event(self, event: Event) -> None:
        key = event.hex()
        _, ok = self.event_cache.get(key)
        if not ok:
            self._add_participant_event(event.creator(), key, event.index())
        self.event_cache.add(key, event)

    def _add_participant_event(self, participant: str, hash_: str, index: int) -> None:
        self.participant_events_cache.set(participant, hash_, index)

    def participant_events(self, participant: str, skip: int) -> List[str]:
        return self.participant_events_cache.get(participant, skip)

    def participant_event(self, participant: str, index: int) -> str:
        try:
            return self.participant_events_cache.get_item(participant, index)
        except StoreErr:
            root = self.roots_by_participant.get(participant)
            if root is None:
                raise StoreErr("InmemStore.Roots", StoreErrType.NO_ROOT, participant)
            if root.self_parent.index == index:
                return root.self_parent.hash
            raise

    def last_event_from(self, participant: str) -> Tuple[str, bool]:
        """Returns (hash, is_root)."""
        try:
            return self.participant_events_cache.get_last(participant), False
        except StoreErr as e:
            if is_store_err(e, StoreErrType.EMPTY):
                root = self.roots_by_participant.get(participant)
                if root is not None:
                    return root.self_parent.hash, True
                raise StoreErr("InmemStore.Roots", StoreErrType.NO_ROOT, participant)
            raise

    def last_consensus_event_from(self, participant: str) -> Tuple[str, bool]:
        if participant in self.last_consensus_events:
            return self.last_consensus_events[participant], False
        root = self.roots_by_participant.get(participant)
        if root is not None:
            return root.self_parent.hash, True
        raise StoreErr("InmemStore.Roots", StoreErrType.NO_ROOT, participant)

    def known_events(self) -> Dict[int, int]:
        known = self.participant_events_cache.known()
        for pk, peer in self._participants.by_pub_key.items():
            if known.get(peer.id, -1) == -1:
                root = self.roots_by_participant.get(pk)
                if root is not None:
                    known[peer.id] = root.self_parent.index
        return known

    def consensus_events(self) -> List[str]:
        window, _ = self.consensus_cache.get_last_window()
        return list(window)

    def consensus_events_count(self) -> int:
        return self.tot_consensus_events

    def add_consensus_event(self, event: Event) -> None:
        self.consensus_cache.set(event.hex(), self.tot_consensus_events)
        self.tot_consensus_events += 1
        self.last_consensus_events[event.creator()] = event.hex()

    def seed_last_consensus_event(self, participant: str, event_hex: str) -> None:
        """Fast-sync: install the donor's last-consensus-event baseline for a
        participant without counting it as a locally processed event. Frame
        roots for participants quiet since the anchor are built from this
        (get_frame), so it must match the rest of the network exactly."""
        self.last_consensus_events[participant] = event_hex

    def get_round(self, r: int) -> RoundInfo:
        res, ok = self.round_cache.get(r)
        if not ok:
            raise StoreErr("RoundCache", StoreErrType.KEY_NOT_FOUND, str(r))
        return res

    def set_round(self, r: int, round_info: RoundInfo) -> None:
        self.round_cache.add(r, round_info)
        if r > self._last_round:
            self._last_round = r

    def last_round(self) -> int:
        return self._last_round

    def round_witnesses(self, r: int) -> List[str]:
        try:
            return self.get_round(r).witnesses()
        except StoreErr:
            return []

    def round_events(self, r: int) -> int:
        try:
            return len(self.get_round(r).events)
        except StoreErr:
            return 0

    def get_root(self, participant: str) -> Root:
        root = self.roots_by_participant.get(participant)
        if root is None:
            raise StoreErr("RootCache", StoreErrType.KEY_NOT_FOUND, participant)
        return root

    def get_block(self, index: int) -> Block:
        res, ok = self.block_cache.get(index)
        if not ok:
            raise StoreErr("BlockCache", StoreErrType.KEY_NOT_FOUND, str(index))
        return res

    def set_block(self, block: Block) -> None:
        self.block_cache.add(block.index(), block)
        if block.index() > self._last_block:
            self._last_block = block.index()

    def last_block_index(self) -> int:
        return self._last_block

    def get_frame(self, index: int) -> Frame:
        res, ok = self.frame_cache.get(index)
        if not ok:
            raise StoreErr("FrameCache", StoreErrType.KEY_NOT_FOUND, str(index))
        return res

    def set_frame(self, frame: Frame) -> None:
        self.frame_cache.add(frame.round, frame)

    def reset(self, roots: Dict[str, Root]) -> None:
        self.roots_by_participant = roots
        self._roots_by_self_parent = None
        self.event_cache = LRU(self._cache_size, pin=self._pin)
        self.round_cache = LRU(self._cache_size)
        self.consensus_cache = RollingIndex("ConsensusCache", self._cache_size)
        self.participant_events_cache.reset()
        self._last_round = -1
        self._last_block = -1
        # Beyond the reference (which keeps these, inmem_store.go:272-282):
        # frames and last-consensus-event entries built on the pre-reset
        # timeline would leak into future frame roots and diverge them;
        # after a reset the fast-sync section re-seeds both. Blocks are
        # chain history and survive.
        self.frame_cache = LRU(self._cache_size)
        self.last_consensus_events = {}

    def close(self) -> None:
        pass

    def need_bootstrap(self) -> bool:
        return False

    def store_path(self) -> str:
        return ""
