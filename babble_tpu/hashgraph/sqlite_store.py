"""Persistent store: InmemStore write-through + SQLite.

The TPU-native equivalent of the reference's BadgerStore
(reference: src/hashgraph/badger_store.go): every event / round / block /
frame / root is written through to disk, reads fall back cache-then-db, and
`db_topological_events` replays insertion order for Bootstrap
(reference: src/hashgraph/badger_store.go:403-444).

SQLite (stdlib) replaces BadgerDB; the reference's key scheme
(`topo_%09d`, `{participant}__event_%09d`, ... reference:
src/hashgraph/badger_store.go:121-147) becomes indexed relational tables,
which buys us ordered replay and participant-index lookups for free.
"""

from __future__ import annotations

import json
import os
import sqlite3
from typing import Dict, List, Tuple

from ..common import StoreErr, StoreErrType, is_store_err
from ..peers import Peer, Peers
from .block import Block
from .event import Event
from .frame import Frame
from .inmem_store import InmemStore
from .root import Root
from .round_info import RoundInfo
from .store import Store

_SCHEMA = """
CREATE TABLE IF NOT EXISTS events (
    hex TEXT PRIMARY KEY,
    topo_index INTEGER NOT NULL,
    creator TEXT NOT NULL,
    idx INTEGER NOT NULL,
    data TEXT NOT NULL
);
CREATE UNIQUE INDEX IF NOT EXISTS events_topo ON events(topo_index);
CREATE UNIQUE INDEX IF NOT EXISTS events_creator_idx ON events(creator, idx);
CREATE TABLE IF NOT EXISTS rounds (
    idx INTEGER PRIMARY KEY,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS blocks (
    idx INTEGER PRIMARY KEY,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS frames (
    idx INTEGER PRIMARY KEY,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS roots (
    participant TEXT PRIMARY KEY,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS participants (
    pub_key_hex TEXT PRIMARY KEY,
    net_addr TEXT NOT NULL
);
"""


class SQLiteStore(Store):
    def __init__(self, participants: Peers, cache_size: int, path: str, existing_db: bool = False):
        self._path = path
        self.inmem = InmemStore(participants, cache_size, pin_live=False)
        self._need_bootstrap = existing_db

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # access is serialized by the node's core_lock, so sharing the
        # connection across the node's worker threads is safe
        self.db = sqlite3.connect(path, check_same_thread=False)
        self.db.executescript(_SCHEMA)

        if existing_db:
            # participants come from the db, roots re-read from disk
            db_participants = self._db_participants()
            if len(db_participants):
                self.inmem = InmemStore(db_participants, cache_size, pin_live=False)
                for pk in db_participants.to_pub_key_slice():
                    try:
                        self.inmem.roots_by_participant[pk] = self._db_get_root(pk)
                    except StoreErr:
                        pass
                self.inmem._roots_by_self_parent = None
        else:
            with self.db:
                for p in participants.to_peer_slice():
                    self.db.execute(
                        "INSERT OR REPLACE INTO participants VALUES (?, ?)",
                        (p.pub_key_hex, p.net_addr),
                    )
                for pk, root in self.inmem.roots_by_participant.items():
                    self._db_set_root(pk, root)

        self._topo_counter = self._db_max_topo() + 1

    # -- factory -----------------------------------------------------------

    @classmethod
    def load_or_create(cls, participants: Peers, cache_size: int, path: str) -> "SQLiteStore":
        if os.path.exists(path):
            return cls(participants, cache_size, path, existing_db=True)
        return cls(participants, cache_size, path, existing_db=False)

    # -- db helpers --------------------------------------------------------

    def _db_participants(self) -> Peers:
        rows = self.db.execute("SELECT pub_key_hex, net_addr FROM participants").fetchall()
        return Peers.from_slice([Peer(net_addr=a, pub_key_hex=pk) for pk, a in rows])

    def _db_max_topo(self) -> int:
        row = self.db.execute("SELECT MAX(topo_index) FROM events").fetchone()
        return row[0] if row and row[0] is not None else -1

    def _db_set_root(self, participant: str, root: Root) -> None:
        self.db.execute(
            "INSERT OR REPLACE INTO roots VALUES (?, ?)",
            (participant, json.dumps(root.to_canonical())),
        )

    def _db_get_root(self, participant: str) -> Root:
        row = self.db.execute(
            "SELECT data FROM roots WHERE participant = ?", (participant,)
        ).fetchone()
        if row is None:
            raise StoreErr("SQLite.Roots", StoreErrType.KEY_NOT_FOUND, participant)
        return Root.from_canonical(json.loads(row[0]))

    def db_topological_events(self) -> List[Event]:
        """All events in insertion order, for Bootstrap replay. Consensus
        metadata is deliberately stripped (from_json, not from_store_json):
        the replay recomputes coordinates/rounds through the full pipeline."""
        rows = self.db.execute(
            "SELECT data FROM events ORDER BY topo_index"
        ).fetchall()
        return [Event.from_json(json.loads(r[0])) for r in rows]

    # -- Store interface: write-through then read-through ------------------

    def cache_size(self) -> int:
        return self.inmem.cache_size()

    def participants(self) -> Peers:
        return self.inmem.participants()

    def roots_by_self_parent(self) -> Dict[str, Root]:
        return self.inmem.roots_by_self_parent()

    def get_event(self, key: str) -> Event:
        try:
            return self.inmem.get_event(key)
        except StoreErr:
            row = self.db.execute("SELECT data FROM events WHERE hex = ?", (key,)).fetchone()
            if row is None:
                raise StoreErr("SQLite.Events", StoreErrType.KEY_NOT_FOUND, key)
            return Event.from_store_json(json.loads(row[0]))

    def set_event(self, event: Event) -> None:
        with self.db:
            row = self.db.execute(
                "SELECT topo_index FROM events WHERE hex = ?", (event.hex(),)
            ).fetchone()
            peer = self.inmem.participants().by_pub_key[event.creator()]
            last_known = self.inmem.participant_events_cache.known().get(peer.id, -1)
            if event.index() > last_known:
                # advances the creator's sequence: register in the
                # participant rolling index
                self.inmem.set_event(event)
            else:
                # write-back of an already-registered event (possibly
                # LRU-evicted meanwhile): refresh the cache only —
                # re-registering would hit a rolled participant window
                self.inmem.event_cache.add(event.hex(), event)
            topo = row[0] if row else self._topo_counter
            if row is None:
                self._topo_counter += 1
            self.db.execute(
                "INSERT OR REPLACE INTO events VALUES (?, ?, ?, ?, ?)",
                (
                    event.hex(),
                    topo,
                    event.creator(),
                    event.index(),
                    json.dumps(event.to_store_json()),
                ),
            )

    def participant_events(self, participant: str, skip: int) -> List[str]:
        try:
            return self.inmem.participant_events(participant, skip)
        except StoreErr:
            rows = self.db.execute(
                "SELECT hex FROM events WHERE creator = ? AND idx > ? ORDER BY idx",
                (participant, skip),
            ).fetchall()
            return [r[0] for r in rows]

    def participant_event(self, participant: str, index: int) -> str:
        try:
            return self.inmem.participant_event(participant, index)
        except StoreErr:
            row = self.db.execute(
                "SELECT hex FROM events WHERE creator = ? AND idx = ?",
                (participant, index),
            ).fetchone()
            if row is None:
                raise StoreErr("SQLite.Events", StoreErrType.KEY_NOT_FOUND, str(index))
            return row[0]

    def last_event_from(self, participant: str) -> Tuple[str, bool]:
        return self.inmem.last_event_from(participant)

    def last_consensus_event_from(self, participant: str) -> Tuple[str, bool]:
        return self.inmem.last_consensus_event_from(participant)

    def known_events(self) -> Dict[int, int]:
        return self.inmem.known_events()

    def consensus_events(self) -> List[str]:
        return self.inmem.consensus_events()

    def consensus_events_count(self) -> int:
        return self.inmem.consensus_events_count()

    def add_consensus_event(self, event: Event) -> None:
        self.inmem.add_consensus_event(event)

    def seed_last_consensus_event(self, participant: str, event_hex: str) -> None:
        self.inmem.seed_last_consensus_event(participant, event_hex)

    def get_round(self, r: int) -> RoundInfo:
        try:
            return self.inmem.get_round(r)
        except StoreErr:
            row = self.db.execute("SELECT data FROM rounds WHERE idx = ?", (r,)).fetchone()
            if row is None:
                raise StoreErr("SQLite.Rounds", StoreErrType.KEY_NOT_FOUND, str(r))
            return RoundInfo.from_json(json.loads(row[0]))

    def set_round(self, r: int, round_info: RoundInfo) -> None:
        self.inmem.set_round(r, round_info)
        with self.db:
            self.db.execute(
                "INSERT OR REPLACE INTO rounds VALUES (?, ?)",
                (r, json.dumps(round_info.to_json())),
            )

    def last_round(self) -> int:
        return self.inmem.last_round()

    def round_witnesses(self, r: int) -> List[str]:
        try:
            return self.get_round(r).witnesses()
        except StoreErr:
            return []

    def round_events(self, r: int) -> int:
        try:
            return len(self.get_round(r).events)
        except StoreErr:
            return 0

    def get_root(self, participant: str) -> Root:
        try:
            return self.inmem.get_root(participant)
        except StoreErr:
            return self._db_get_root(participant)

    def get_block(self, index: int) -> Block:
        try:
            return self.inmem.get_block(index)
        except StoreErr:
            row = self.db.execute("SELECT data FROM blocks WHERE idx = ?", (index,)).fetchone()
            if row is None:
                raise StoreErr("SQLite.Blocks", StoreErrType.KEY_NOT_FOUND, str(index))
            return Block.from_json(json.loads(row[0]))

    def set_block(self, block: Block) -> None:
        self.inmem.set_block(block)
        with self.db:
            self.db.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?, ?)",
                (block.index(), json.dumps(block.to_json())),
            )

    def last_block_index(self) -> int:
        return self.inmem.last_block_index()

    def get_frame(self, index: int) -> Frame:
        try:
            return self.inmem.get_frame(index)
        except StoreErr:
            row = self.db.execute("SELECT data FROM frames WHERE idx = ?", (index,)).fetchone()
            if row is None:
                raise StoreErr("SQLite.Frames", StoreErrType.KEY_NOT_FOUND, str(index))
            return Frame.from_json(json.loads(row[0]))

    def set_frame(self, frame: Frame) -> None:
        self.inmem.set_frame(frame)
        with self.db:
            self.db.execute(
                "INSERT OR REPLACE INTO frames VALUES (?, ?)",
                (frame.round, json.dumps(frame.to_json())),
            )

    def reset(self, roots: Dict[str, Root]) -> None:
        self.inmem.reset(roots)
        with self.db:
            for pk, root in roots.items():
                self._db_set_root(pk, root)

    def close(self) -> None:
        self.db.close()

    def need_bootstrap(self) -> bool:
        return self._need_bootstrap

    def store_path(self) -> str:
        return self._path
