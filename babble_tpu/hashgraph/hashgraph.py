"""Hashgraph consensus core — scalar (CPU) engine.

Implements gossip-about-gossip virtual voting (reference:
src/hashgraph/hashgraph.go): a DAG of events plus five consensus passes
(DivideRounds, DecideFame, DecideRoundReceived, ProcessDecidedRounds,
ProcessSigPool) projecting a total order of transactions onto a blockchain.

This scalar engine is the semantic oracle: the TPU engine
(babble_tpu.engine.tpu) must produce identical rounds / fame / consensus
order on every DAG, enforced by differential tests.

Design deltas from the reference (deliberate, TPU-first):
- dense coordinates: last_ancestors / first_descendants are lists indexed by
  peer *position* in the sorted validator set (the reference uses ordered
  (participantId, coords) pairs, reference: src/hashgraph/event.go:62-99);
  position indexing is what the device grids use, so both engines share it.
- deterministic iteration everywhere (Python dicts are insertion-ordered;
  the reference relies on order-independence of random Go map iteration).
- memoization in plain dicts cleared on Reset (the reference uses bounded
  LRUs, reference: src/hashgraph/hashgraph.go:36-40); recursions are
  unrolled into explicit stacks so deep self-parent chains cannot overflow.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Tuple

from ..common import StoreErr, StoreErrType, is_store_err
from ..peers import Peers
from .block import Block, BlockSignature, new_block_from_frame
from .event import Event, WireEvent, root_self_parent
from .frame import Frame
from .root import Root, RootEvent
from .round_info import PendingRound, RoundInfo
from .section import FrozenRef, Section
from .store import Store

MAX_INT32 = 2**31 - 1
MIN_INT32 = -(2**31)


class BlockDivergenceError(Exception):
    """SAFETY tripwire: a block body at an already-occupied index differs
    from the stored body. A BFT engine must never replace or divergently
    re-derive a committed body — raising here stops the node from
    compounding a fork instead of silently overwriting chain history."""


def middle_bit(ehex: str) -> bool:
    """Coin-round bit: middle byte of the event hash (reference:
    src/hashgraph/hashgraph.go:1526-1535)."""
    raw = bytes.fromhex(ehex[2:])
    if len(raw) > 0 and raw[len(raw) // 2] == 0:
        return False
    return True


class Hashgraph:
    def __init__(
        self,
        participants: Peers,
        store: Store,
        commit_callback: Optional[Callable[[Block], None]] = None,
        logger=None,
        obs=None,
    ):
        import logging

        from ..obs import Observability

        n = len(participants)
        self.participants = participants
        self.store = store
        self.commit_callback = commit_callback
        self.super_majority = 2 * n // 3 + 1
        self.trust_count = math.ceil(n / 3)
        self.logger = logger or logging.getLogger("babble.hashgraph")
        # always present so the device engines can instrument without
        # nil-guards; a Node passes its own bundle (sharing the injected
        # clock), direct construction gets a private system-clock one
        self.obs = obs if obs is not None else Observability()
        self._pass_hist = self.obs.histogram(
            "babble_consensus_pass_duration_seconds",
            "Wall time of each consensus pipeline pass",
            labels=("phase",),
        )

        self.undetermined_events: List[str] = []
        self.pending_rounds: List[PendingRound] = []
        self.last_consensus_round: Optional[int] = None
        self.first_consensus_round: Optional[int] = None
        self.anchor_block: Optional[int] = None
        # surfaced as the `round_events` stat; the reference declares this
        # counter but never assigns it (src/hashgraph/hashgraph.go:27 is its
        # only non-test mention), so staying 0 is bit-faithful parity
        self.last_committed_round_events = 0
        self.sig_pool: List[BlockSignature] = []
        # arrival inbox above; per-block-index backlog for signatures whose
        # block is not here yet (see process_sig_pool's pool discipline)
        self._sig_backlog: Dict[int, List[BlockSignature]] = {}
        # backlog indices whose signatures failed verification against a
        # still-empty state_hash: re-tried only once our commit fills it
        self._sig_wait_commit: set = set()
        self.consensus_transactions = 0
        # diagnostics: how often fame voting reached a coin round, and how
        # often the coin (event-hash middle bit) actually decided a vote —
        # lets tests prove the adversarial branch was exercised
        self.coin_rounds = 0
        self.coin_flips = 0
        # fork evidence observed locally: divergent re-derivations caught
        # by check_block_immutable. Exported in the cluster HealthDigest
        # (ISSUE 20) so any peer can see a neighbour that tripped the
        # safety invariant even after it stopped committing.
        self.fork_evidence = 0
        # deepest fame decision (j - round_index at the deciding vote):
        # 2 = every witness decided on the first ballot; >= 3 proves
        # contested fame (split votes forced extra voting rounds)
        self.max_fame_depth = 0
        self.pending_loaded_events = 0
        self.topological_index = 0
        # the frame a reset() was applied from, pinned beyond the store's
        # LRU so the anchor it backs stays servable (see reset/get_frame)
        self._reset_frame: Optional[Frame] = None

        # peer-position lookups shared with the device grids
        self._pos_by_pubkey: Dict[str, int] = {
            p.pub_key_hex: i for i, p in enumerate(participants.to_peer_slice())
        }
        self._pos_by_id: Dict[int, int] = {
            p.id: i for i, p in enumerate(participants.to_peer_slice())
        }

        # memo caches (unbounded dicts; cleared on Reset)
        self._round_cache: Dict[str, int] = {}
        self._timestamp_cache: Dict[str, int] = {}

        # identities of events below a fast-sync section cut, referenced as
        # other-parents by section events (see section.py); reset_floor is
        # the anchor round of the last applied section — rounds at or below
        # it are undecidable here and skipped in the round-received scan
        self.frozen_refs: Dict[str, FrozenRef] = {}
        # (index, frame_hash, sig-set) -> valid-signature count; see
        # _block_proof_count
        self._proof_count_cache: Dict[tuple, int] = {}
        self.reset_floor: Optional[int] = None
        # index of the block this hashgraph was last reset() from (-1 if
        # never reset): the anchor-serving walk cannot build frames below it
        self._reset_anchor_index: int = -1
        # optional hook: called as (event, fd_writes) after every insert —
        # the incremental device engine's delta feed (babble_tpu/tpu/live.py)
        self.insert_listener = None

    # ------------------------------------------------------------------
    # positions
    # ------------------------------------------------------------------

    def peer_position(self, pub_key_hex: str) -> int:
        return self._pos_by_pubkey[pub_key_hex]

    # ------------------------------------------------------------------
    # DAG predicates (reference: src/hashgraph/hashgraph.go:80-395)
    # ------------------------------------------------------------------

    def ancestor(self, x: str, y: str) -> bool:
        """True if y is an ancestor of x (O(1) via last-ancestor coordinates)."""
        if x == y:
            return True
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        pos = self._pos_by_pubkey[ey.creator()]
        last_known_index = ex.last_ancestors[pos][0]
        return last_known_index >= ey.index()

    def self_ancestor(self, x: str, y: str) -> bool:
        if x == y:
            return True
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        return ex.creator() == ey.creator() and ex.index() >= ey.index()

    def see(self, x: str, y: str) -> bool:
        # forks are prevented at insertion, so seeing == ancestry
        return self.ancestor(x, y)

    def strongly_see(self, x: str, y: str) -> bool:
        """True if x sees y through events of a supermajority of validators:
        count positions where x's last ancestor is at or past y's first
        descendant (reference: src/hashgraph/hashgraph.go:172-191)."""
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        c = sum(
            1
            for la, fd in zip(ex.last_ancestors, ey.first_descendants)
            if la[0] >= fd[0]
        )
        return c >= self.super_majority

    # -- round ----------------------------------------------------------

    def round(self, x: str) -> int:
        cached = self._round_cache.get(x)
        if cached is not None:
            return cached
        # iterative evaluation of the self/other-parent recursion
        stack = [x]
        while stack:
            h = stack[-1]
            if h in self._round_cache:
                stack.pop()
                continue
            deps = self._round_deps(h)
            missing = [d for d in deps if d not in self._round_cache]
            if missing:
                stack.extend(missing)
                continue
            self._round_cache[h] = self._round_once(h)
            stack.pop()
        return self._round_cache[x]

    def _round_deps(self, x: str) -> List[str]:
        """Parent hashes whose rounds must be known before x's."""
        if x in self.store.roots_by_self_parent():
            return []
        ex = self.store.get_event(x)
        root = self.store.get_root(ex.creator())
        if ex.self_parent() == root.self_parent.hash:
            other = root.others.get(ex.hex())
            if ex.other_parent() == "" or (other is not None and other.hash == ex.other_parent()):
                return []
        deps = [ex.self_parent()]
        if ex.other_parent() != "":
            other = root.others.get(ex.hex())
            if not (other is not None and other.hash == ex.other_parent()):
                deps.append(ex.other_parent())
        return deps

    def _round_once(self, x: str) -> int:
        """Single-step round computation assuming parent rounds are cached
        (reference: src/hashgraph/hashgraph.go:205-278)."""
        roots_by_sp = self.store.roots_by_self_parent()
        if x in roots_by_sp:
            return roots_by_sp[x].self_parent.round

        ex = self.store.get_event(x)
        root = self.store.get_root(ex.creator())

        # event directly attached to the root
        if ex.self_parent() == root.self_parent.hash:
            other = root.others.get(ex.hex())
            if ex.other_parent() == "" or (other is not None and other.hash == ex.other_parent()):
                return root.next_round

        # whitepaper formula: parent round + increment
        parent_round = self._round_cache[ex.self_parent()]
        if ex.other_parent() != "":
            other = root.others.get(ex.hex())
            if other is not None and other.hash == ex.other_parent():
                op_round = root.next_round
            else:
                op_round = self._round_cache[ex.other_parent()]
            if op_round > parent_round:
                parent_round = op_round

        c = 0
        for w in self.store.round_witnesses(parent_round):
            if self.strongly_see(x, w):
                c += 1
        if c >= self.super_majority:
            parent_round += 1
        return parent_round

    def witness(self, x: str) -> bool:
        """True if x is the first event of its creator in its round."""
        ex = self.store.get_event(x)
        return self.round(x) > self.round(ex.self_parent())

    def round_received(self, x: str) -> int:
        ex = self.store.get_event(x)
        return ex.round_received if ex.round_received is not None else -1

    # -- lamport ---------------------------------------------------------

    def lamport_timestamp(self, x: str) -> int:
        cached = self._timestamp_cache.get(x)
        if cached is not None:
            return cached
        stack = [x]
        while stack:
            h = stack[-1]
            if h in self._timestamp_cache:
                stack.pop()
                continue
            deps = self._lamport_deps(h)
            missing = [d for d in deps if d not in self._timestamp_cache]
            if missing:
                stack.extend(missing)
                continue
            self._timestamp_cache[h] = self._lamport_once(h)
            stack.pop()
        return self._timestamp_cache[x]

    def _lamport_deps(self, x: str) -> List[str]:
        if x in self.store.roots_by_self_parent():
            return []
        ex = self.store.get_event(x)
        root = self.store.get_root(ex.creator())
        deps = []
        if ex.self_parent() != root.self_parent.hash:
            deps.append(ex.self_parent())
        if ex.other_parent() != "":
            try:
                self.store.get_event(ex.other_parent())
                deps.append(ex.other_parent())
            except StoreErr:
                pass
        return deps

    def _lamport_once(self, x: str) -> int:
        """reference: src/hashgraph/hashgraph.go:325-379."""
        roots_by_sp = self.store.roots_by_self_parent()
        if x in roots_by_sp:
            return roots_by_sp[x].self_parent.lamport_timestamp

        ex = self.store.get_event(x)
        root = self.store.get_root(ex.creator())

        if ex.self_parent() == root.self_parent.hash:
            plt = root.self_parent.lamport_timestamp
        else:
            plt = self._timestamp_cache[ex.self_parent()]

        if ex.other_parent() != "":
            op_lt = MIN_INT32
            if ex.other_parent() in self._timestamp_cache:
                op_lt = self._timestamp_cache[ex.other_parent()]
            else:
                other = root.others.get(x)
                if other is not None and other.hash == ex.other_parent():
                    op_lt = other.lamport_timestamp
            if op_lt > plt:
                plt = op_lt

        return plt + 1

    def round_diff(self, x: str, y: str) -> int:
        return self.round(x) - self.round(y)

    # ------------------------------------------------------------------
    # insertion (reference: src/hashgraph/hashgraph.go:398-544,714-761)
    # ------------------------------------------------------------------

    def _check_self_parent(self, event: Event) -> None:
        creator_last_known, _ = self.store.last_event_from(event.creator())
        if event.self_parent() != creator_last_known:
            raise ValueError("Self-parent not last known event by creator")

    def _check_other_parent(self, event: Event) -> None:
        other_parent = event.other_parent()
        if other_parent == "":
            return
        try:
            self.store.get_event(other_parent)
            return
        except StoreErr:
            if other_parent in self.frozen_refs:
                return
            root = self.store.get_root(event.creator())
            other = root.others.get(event.hex())
            if other is not None and other.hash == other_parent:
                return
            raise ValueError("Other-parent not known")

    def _init_event_coordinates(self, event: Event) -> None:
        n = len(self.participants)
        event.first_descendants = [(MAX_INT32, "")] * n

        sp: Optional[Event] = None
        op: Optional[Event] = None
        try:
            sp = self.store.get_event(event.self_parent())
        except StoreErr:
            pass
        try:
            op = self.store.get_event(event.other_parent())
        except StoreErr:
            pass

        if sp is None and op is None:
            event.last_ancestors = [(-1, "")] * n
        elif sp is None:
            event.last_ancestors = list(op.last_ancestors)
        elif op is None:
            event.last_ancestors = list(sp.last_ancestors)
        else:
            event.last_ancestors = [
                a if a[0] >= b[0] else b
                for a, b in zip(sp.last_ancestors, op.last_ancestors)
            ]

        pos = self._pos_by_pubkey[event.creator()]
        coords = (event.index(), event.hex())
        event.first_descendants[pos] = coords
        event.last_ancestors[pos] = coords

    def _update_ancestor_first_descendant(self, event: Event) -> List[tuple]:
        """Walk each last-ancestor's self-parent chain marking this event as
        first descendant (reference: src/hashgraph/hashgraph.go:510-544).
        Returns the (ancestor_hash, creator_pos, index) cells written — the
        delta stream an incremental device engine replays."""
        pos = self._pos_by_pubkey[event.creator()]
        coords = (event.index(), event.hex())
        writes: List[tuple] = []
        for _, ah in event.last_ancestors:
            while ah != "":
                try:
                    a = self.store.get_event(ah)
                except StoreErr:
                    break
                if a.first_descendants[pos][0] == MAX_INT32:
                    a.first_descendants[pos] = coords
                    self.store.set_event(a)
                    writes.append((ah, pos, coords[0]))
                    ah = a.self_parent()
                else:
                    break
        return writes

    def insert_event(self, event: Event, set_wire_info: bool) -> None:
        if not event.verify():
            raise ValueError("Invalid Event signature")

        self._check_self_parent(event)
        self._check_other_parent(event)

        event.topological_index = self.topological_index
        self.topological_index += 1

        if set_wire_info:
            self._set_wire_info(event)

        self._init_event_coordinates(event)
        self.store.set_event(event)
        fd_writes = self._update_ancestor_first_descendant(event)
        if self.insert_listener is not None:
            self.insert_listener(event, fd_writes)

        self.undetermined_events.append(event.hex())
        if event.is_loaded():
            self.pending_loaded_events += 1
        self.sig_pool.extend(event.block_signatures())
        # causal tracing (ISSUE 5): the traced txs this event carries are
        # now in the graph — the trace store looks them up by tx hash, so
        # no trace data touches the signed event bytes
        self.obs.traces.mark_event(event.transactions())

    def _set_wire_info(self, event: Event) -> None:
        self_parent_index = -1
        other_parent_creator_id = -1
        other_parent_index = -1

        last_from, is_root = self.store.last_event_from(event.creator())
        if is_root and last_from == event.self_parent():
            root = self.store.get_root(event.creator())
            self_parent_index = root.self_parent.index
        else:
            self_parent = self.store.get_event(event.self_parent())
            self_parent_index = self_parent.index()

        if event.other_parent() != "":
            root = self.store.get_root(event.creator())
            other = root.others.get(event.hex())
            if other is not None and other.hash == event.other_parent():
                other_parent_creator_id = other.creator_id
                other_parent_index = other.index
            else:
                other_parent = self.store.get_event(event.other_parent())
                other_parent_creator_id = self.participants.by_pub_key[
                    other_parent.creator()
                ].id
                other_parent_index = other_parent.index()

        event.set_wire_info(
            self_parent_index,
            other_parent_creator_id,
            other_parent_index,
            self.participants.by_pub_key[event.creator()].id,
        )

    # ------------------------------------------------------------------
    # roots (reference: src/hashgraph/hashgraph.go:546-640)
    # ------------------------------------------------------------------

    def _create_self_parent_root_event(self, ev: Event) -> RootEvent:
        sp = ev.self_parent()
        return RootEvent(
            hash=sp,
            creator_id=self.participants.by_pub_key[ev.creator()].id,
            index=ev.index() - 1,
            lamport_timestamp=self.lamport_timestamp(sp),
            round=self.round(sp),
        )

    def _create_other_parent_root_event(self, ev: Event) -> RootEvent:
        op = ev.other_parent()
        root = self.store.get_root(ev.creator())
        other = root.others.get(ev.hex())
        if other is not None and other.hash == op:
            return other
        try:
            other_parent = self.store.get_event(op)
        except StoreErr:
            ref = self.frozen_refs.get(op)
            if ref is None:
                raise
            return RootEvent(
                hash=op,
                creator_id=ref.creator_id,
                index=ref.index,
                lamport_timestamp=ref.lamport,
                round=ref.round,
            )
        return RootEvent(
            hash=op,
            creator_id=self.participants.by_pub_key[other_parent.creator()].id,
            index=other_parent.index(),
            lamport_timestamp=self.lamport_timestamp(op),
            round=self.round(op),
        )

    def _create_root(self, ev: Event) -> Root:
        root = Root(
            next_round=self.round(ev.hex()),
            self_parent=self._create_self_parent_root_event(ev),
            others={},
        )
        if ev.other_parent() != "":
            root.others[ev.hex()] = self._create_other_parent_root_event(ev)
        return root

    # ------------------------------------------------------------------
    # the five passes
    # ------------------------------------------------------------------

    def divide_rounds(self) -> None:
        """Assign round + lamport timestamp, flag witnesses, queue pending
        rounds (reference: src/hashgraph/hashgraph.go:767-849)."""
        for hash_ in self.undetermined_events:
            ev = self.store.get_event(hash_)
            update_event = False

            if ev.round is None:
                round_number = self.round(hash_)
                ev.set_round(round_number)
                self.obs.traces.mark_round(ev.transactions())
                update_event = True

                try:
                    round_info = self.store.get_round(round_number)
                except StoreErr as e:
                    if not is_store_err(e, StoreErrType.KEY_NOT_FOUND):
                        raise
                    round_info = RoundInfo()

                is_witness = self.witness(hash_)

                # lower bound prevents reprocessing the base layer after Reset
                if not round_info.queued and (
                    self.last_consensus_round is None
                    or round_number >= self.last_consensus_round
                ):
                    self.pending_rounds.append(PendingRound(round_number, False))
                    round_info.queued = True
                elif (
                    is_witness
                    and round_info.queued
                    and not round_info.is_decided(hash_)
                    # rounds at or below a fast-sync cut are the donor's to
                    # decide — their votes are not derivable from the
                    # scrubbed DAG, so re-queueing could never resolve
                    and (
                        self.reset_floor is None
                        or round_number > self.reset_floor
                    )
                    and not any(
                        p.index == round_number for p in self.pending_rounds
                    )
                ):
                    # A witness arriving AFTER its round was decided and
                    # dequeued (e.g. a crashed peer's pre-crash tail event
                    # surfacing post-restart) would otherwise keep fame
                    # UNDEFINED forever: decide_fame only visits pending
                    # rounds, so witnesses_decided() flips false for good
                    # and every reception scan crossing this round stalls —
                    # while peers that held the event before deciding
                    # receive those events normally (the round-5 survivor-
                    # side reception divergence). Re-queue so fame resolves;
                    # process_decided_rounds drops settled rounds again once
                    # decided, so no block is ever re-minted.
                    self.pending_rounds.append(PendingRound(round_number, False))
                    self.obs.flightrec.record(
                        "fame.reopen", round=round_number,
                    )

                round_info.add_event(hash_, is_witness)
                self.store.set_round(round_number, round_info)
                if is_witness:
                    self.obs.provenance.note_witness(
                        hash_, round_number, self.peer_position(ev.creator()),
                    )

            if ev.lamport_timestamp is None:
                ev.set_lamport_timestamp(self.lamport_timestamp(hash_))
                update_event = True

            if update_event:
                self.store.set_event(ev)
                if (
                    ev.round is not None
                    and ev.lamport_timestamp is not None
                    and ev.last_ancestors is not None
                ):
                    # decision provenance: the DivideRounds table cell —
                    # same value the device engines capture from their
                    # staged lastAncestors rows (obs/provenance.py)
                    self.obs.provenance.note_event(
                        hash_, ev.round, ev.lamport_timestamp,
                        ev.last_ancestors,
                    )

    def decide_fame(self) -> None:
        """Virtual voting on witness fame (reference:
        src/hashgraph/hashgraph.go:852-947)."""
        votes: Dict[Tuple[str, str], bool] = {}  # (y, x) => vote

        decided_rounds: Dict[int, int] = {}

        for pos, pr in enumerate(self.pending_rounds):
            round_index = pr.index
            round_info = self.store.get_round(round_index)
            for x in round_info.witnesses():
                if round_info.is_decided(x):
                    continue
                decided = False
                # decision provenance: coin rounds traversed (and coin
                # flips taken) while fame of x was open — part of the
                # "why" on the landed verdict (obs/provenance.py)
                x_coins = 0
                x_flips = 0
                for j in range(round_index + 1, self.store.last_round() + 1):
                    if decided:
                        break
                    for y in self.store.round_witnesses(j):
                        diff = j - round_index
                        if diff == 1:
                            votes[(y, x)] = self.see(y, x)
                        else:
                            # count votes among strongly-seen prev-round witnesses
                            ss_witnesses = [
                                w
                                for w in self.store.round_witnesses(j - 1)
                                if self.strongly_see(y, w)
                            ]
                            yays = sum(1 for w in ss_witnesses if votes.get((w, x), False))
                            nays = len(ss_witnesses) - yays
                            v = yays >= nays
                            t = yays if v else nays

                            if diff % len(self.participants) > 0:
                                # normal round: supermajority decides
                                if t >= self.super_majority:
                                    round_info.set_fame(x, v)
                                    votes[(y, x)] = v
                                    decided = True
                                    self.max_fame_depth = max(
                                        self.max_fame_depth, diff
                                    )
                                    # the landed verdict with its full
                                    # "why": deciding voter, tallies,
                                    # strongly-seen count, deciding step
                                    self.obs.provenance.note_fame(
                                        x, round_index, v, engine="cpu",
                                        voter=y, yays=yays, nays=nays,
                                        ss=len(ss_witnesses), step=diff,
                                        coins=x_coins, flips=x_flips,
                                    )
                                    break
                                votes[(y, x)] = v
                            else:
                                # coin round
                                self.coin_rounds += 1
                                x_coins += 1
                                if t >= self.super_majority:
                                    votes[(y, x)] = v
                                else:
                                    votes[(y, x)] = middle_bit(y)
                                    self.coin_flips += 1
                                    x_flips += 1

            self.store.set_round(round_index, round_info)
            if round_info.witnesses_decided():
                decided_rounds[round_index] = pos

        # recompute (not just promote): a late witness re-opening a round
        # must also UNSET a stale decided flag, or process_decided_rounds
        # could settle the round around an undefined fame
        for pr in self.pending_rounds:
            pr.decided = pr.index in decided_rounds

    def decide_round_received(self) -> None:
        """An event is received in the first round where all unique famous
        witnesses see it, provided all earlier rounds are fully decided
        (reference: src/hashgraph/hashgraph.go:951-1036)."""
        new_undetermined: List[str] = []

        for x in self.undetermined_events:
            received = False
            r = self.round(x)

            for i in range(r + 1, self.store.last_round() + 1):
                try:
                    tr = self.store.get_round(i)
                except StoreErr:
                    # rounds at or below a fast-sync cut are undecidable
                    # here; the donor already evaluated them as not
                    # receiving this event, so keep scanning upward
                    if self.reset_floor is not None and i <= self.reset_floor:
                        continue
                    # can happen after Reset/fast-sync
                    if (
                        self.last_consensus_round is not None
                        and r < self.last_consensus_round
                    ):
                        received = True
                        break
                    raise

                if not tr.witnesses_decided():
                    break

                fws = tr.famous_witnesses()
                s = [w for w in fws if self.see(w, x)]

                if len(s) == len(fws) and len(s) > 0:
                    received = True
                    ex = self.store.get_event(x)
                    ex.set_round_received(i)
                    self.obs.provenance.note_received(x, i)
                    self.obs.traces.mark_famous(ex.transactions())
                    self.store.set_event(ex)
                    tr.set_consensus_event(x)
                    self.store.set_round(i, tr)
                    break

            if not received:
                new_undetermined.append(x)

        self.undetermined_events = new_undetermined

    def process_decided_rounds(self) -> None:
        """Map decided rounds onto Frames and Blocks; commit through the
        callback (reference: src/hashgraph/hashgraph.go:1041-1122).

        Processing order is SORTED round order, not queue order, and any
        round at or below last_consensus_round is dropped as settled —
        both deliberate strengthenings of the reference (which processes
        its FIFO queue and skips only `index == LastConsensusRound`,
        hashgraph.go:1049-1063). The reference can rely on queue order
        because its joiners re-derive everything from live sync; this
        rebuild's section replay (apply_section) re-queues scrubbed rounds
        in section TOPOLOGICAL order, where a round-13 event can precede a
        round-12 event. Processing 13 first advances last_consensus_round
        past 12, after which an equality skip no longer recognizes the
        settled anchor round — it was re-minted as a duplicate block at
        the next free index, shifting the joiner's whole chain one block
        against the cluster (the round-5 in-suite byte-divergence). A
        round <= last_consensus_round is materialized by construction
        (blocks mint in this loop in ascending round order; reset/section
        replay settle the anchor), so the floor skip can never drop an
        unmaterialized round."""
        pending = sorted(self.pending_rounds, key=lambda p: p.index)
        pos = 0
        try:
            while pos < len(pending):
                pr = pending[pos]
                # rounds at or below a fast-sync cut were settled by the
                # donor; their fame is not re-derivable from the scrubbed
                # DAG, so they may never read as decided here — drop them
                # unconditionally (the original floor-skip behavior)
                donor_settled = (
                    self.reset_floor is not None
                    and pr.index <= self.reset_floor
                )
                if (
                    self.last_consensus_round is not None
                    and pr.index <= self.last_consensus_round
                    and (pr.decided or donor_settled)
                ):
                    # settled round back in the queue (re-queued for a late
                    # witness, or section replay): fame is whole again (or
                    # donor authority), drop it without re-minting a block
                    pos += 1
                    continue
                # never process a decided round before all previous rounds
                # are whole — including a settled round re-opened by a late
                # witness: later frames must not freeze while an earlier
                # round's famous set is still in question
                if not pr.decided:
                    break

                frame = self.get_frame(pr.index)

                if frame.events:
                    for e in frame.events:
                        self.store.add_consensus_event(e)
                        self.consensus_transactions += len(e.transactions())
                        if e.is_loaded():
                            self.pending_loaded_events -= 1

                    last_block_index = self.store.last_block_index()
                    block = new_block_from_frame(last_block_index + 1, frame)
                    self.check_block_immutable(block)
                    self.store.set_block(block)
                    if self.commit_callback is not None:
                        self.commit_callback(block)

                pos += 1
                self._set_last_consensus_round(pr.index)
                # the round's tables are committed history from here on
                self.obs.provenance.settle_round(pr.index)
        finally:
            self.pending_rounds = pending[pos:]

    def get_frame(self, round_received: int) -> Frame:
        """reference: src/hashgraph/hashgraph.go:1125-1231."""
        try:
            return self.store.get_frame(round_received)
        except StoreErr as e:
            if not is_store_err(e, StoreErrType.KEY_NOT_FOUND):
                raise
        rf = getattr(self, "_reset_frame", None)
        if rf is not None and rf.round == round_received:
            # the pinned post-reset frame (see reset()): evicted from the
            # store's LRU but still the only buildable copy of its round
            return rf

        round_info = self.store.get_round(round_received)
        events = [self.store.get_event(eh) for eh in round_info.consensus_events()]
        from .event import by_lamport_key

        events.sort(key=by_lamport_key)

        roots: Dict[str, Root] = {}
        for ev in events:
            p = ev.creator()
            if p not in roots:
                roots[p] = self._create_root(ev)

        # participants with no events in the frame: root from last consensus event
        for p in self.participants.to_pub_key_slice():
            if p not in roots:
                last_consensus, is_root = self.store.last_consensus_event_from(p)
                if is_root:
                    root = self.store.get_root(p)
                else:
                    root = self._create_root(self.store.get_event(last_consensus))
                roots[p] = root

        # other-parents outside the frame must be reachable via Root.Others
        treated = set()
        for ev in events:
            treated.add(ev.hex())
            other_parent = ev.other_parent()
            if other_parent != "" and other_parent not in treated:
                if ev.self_parent() != roots[ev.creator()].self_parent.hash:
                    roots[ev.creator()].others[ev.hex()] = (
                        self._create_other_parent_root_event(ev)
                    )

        ordered_roots = [roots[p.pub_key_hex] for p in self.participants.to_peer_slice()]

        res = Frame(round=round_received, roots=ordered_roots, events=events)
        self.store.set_frame(res)
        return res

    # ECDSA verifications per process_sig_pool pass. The pass runs under
    # core_lock on every sync; an unbounded pass (e.g. the burst of
    # backlogged signatures that all become verifiable the moment a
    # fast-forward rebuilds the store) stalls the lock past peers' RPC
    # timeouts and reads as a dead node (round-5 faulthandler capture:
    # every peer thread queued behind one process_sig_pool walk).
    SIG_POOL_VERIFY_BUDGET = 512

    # Bound on how far ABOVE our block height a backlogged signature may
    # claim to be before we refuse to hold it (ISSUE 1 satellite): without
    # a horizon, a lagging node accumulates one bucket per future block
    # its peers commit — unbounded memory held under core_lock forever if
    # the node never catches up incrementally (it fast-forwards instead,
    # and reset() clears pre-anchor buckets but future junk keyed by a
    # byzantine peer's fictitious indices would survive every pass). Sized
    # like a generous sync-limit horizon: signatures for blocks this far
    # ahead cannot attach before a fast-forward rebuilds state anyway, and
    # honest peers re-carry their signatures in events we re-receive then.
    SIG_BACKLOG_HORIZON = 1024
    # Hard cap on backlog buckets: even within the horizon, eviction keeps
    # a byzantine flood bounded. Farthest-future buckets go first: the
    # lowest indices are the next to attach (they advance the anchor),
    # while far-future signatures are re-carried by honest peers' events
    # after the fast-forward that reaching them requires — dropping those
    # loses nothing durable.
    SIG_BACKLOG_MAX_BUCKETS = 2048

    def pending_signatures(self) -> int:
        """Signatures waiting to attach: the arrival inbox plus the
        per-block backlog (observability + tests)."""
        return len(self.sig_pool) + sum(
            len(v) for v in self._sig_backlog.values()
        )

    def process_sig_pool(self) -> None:
        """Attach valid signatures to blocks; advance the anchor block once a
        block has >1/3 signatures (reference: src/hashgraph/hashgraph.go:1236-1300).

        The pool discipline is deliberately tighter than the reference,
        which keeps every unprocessed signature in one flat list and
        re-walks it all — re-verifying the invalid ones — on every pass
        (hashgraph.go:1240-1297 marks only attached ones processed). Go
        clusters never feel that; this rebuild's lagging nodes do: a node
        2,000 blocks behind holds ~8,000 future-block signatures, and an
        O(pool) walk with store-miss exceptions under core_lock on EVERY
        sync is a round-5 cluster wedge (observed: joiner pinned at block
        23 while peers ran to 2,462). So arrivals land in an inbox
        (`sig_pool`), are routed once into a per-block-index backlog, and
        each pass touches ONLY indices at or below the store's block
        height — a far-future signature costs nothing until its block
        exists. Rules:
        - unknown validator: dropped (the validator set is static);
        - block index above our height: backlogged untouched;
        - block at or below our height but absent locally (pre-anchor gap
          after a fast-forward, or evicted): dropped — it can never attach;
        - invalid against a body whose state_hash is still empty:
          retained, and the bucket is then skipped at zero ECDSA cost
          until our commit fills the hash (the only event that can change
          the outcome; peers sign after their commit does). The skip is
          armed by a FAILED verify, never by the empty hash alone —
          stateless apps legitimately finalize at state_hash=b"" and
          their signatures must attach on the first pass;
        - invalid against a final (state-hashed) body: dropped — an
          immutable body can never re-validate the signature."""
        inbox, self.sig_pool = self.sig_pool, []
        for bs in inbox:
            if bs.validator_hex() not in self.participants.by_pub_key:
                self.logger.warning(
                    "Unknown validator for block signature: %s",
                    bs.validator_hex(),
                )
                continue
            self._sig_backlog.setdefault(bs.index, []).append(bs)
            # a new arrival re-opens a wait-committed bucket: the skip
            # below exists to avoid RE-verifying known failures, and must
            # not deny a first verification to a fresh signature — for a
            # stateless app (final state_hash=b"") one corrupt signature
            # would otherwise wedge the bucket and block valid ones from
            # ever attaching (code review r5)
            self._sig_wait_commit.discard(bs.index)

        last_block = self.store.last_block_index()
        # backlog bound (see SIG_BACKLOG_HORIZON/MAX_BUCKETS): drop buckets
        # past the horizon, then evict farthest-future buckets beyond the
        # hard cap. Runs after routing so a single pass bounds whatever the
        # inbox brought in.
        horizon = last_block + self.SIG_BACKLOG_HORIZON
        beyond = [i for i in self._sig_backlog if i > horizon]
        for idx in beyond:
            self._sig_backlog.pop(idx)
            self._sig_wait_commit.discard(idx)
        if beyond:
            self.obs.flightrec.record(
                "sig.pressure", kind="horizon", dropped=len(beyond),
                last_block=last_block,
            )
            self.logger.warning(
                "sig backlog: dropped %d bucket(s) beyond horizon "
                "(last_block=%d horizon=+%d max_index=%d)",
                len(beyond), last_block, self.SIG_BACKLOG_HORIZON,
                max(beyond),
            )
        if len(self._sig_backlog) > self.SIG_BACKLOG_MAX_BUCKETS:
            excess = sorted(self._sig_backlog, reverse=True)[
                : len(self._sig_backlog) - self.SIG_BACKLOG_MAX_BUCKETS
            ]
            for idx in excess:
                self._sig_backlog.pop(idx)
                self._sig_wait_commit.discard(idx)
            self.obs.flightrec.record(
                "sig.pressure", kind="cap", dropped=len(excess),
                last_block=last_block,
            )
            self.logger.warning(
                "sig backlog: evicted %d farthest-future bucket(s) over "
                "the %d-bucket cap", len(excess), self.SIG_BACKLOG_MAX_BUCKETS,
            )
        verified = 0
        for idx in sorted(i for i in self._sig_backlog if i <= last_block):
            if verified >= self.SIG_POOL_VERIFY_BUDGET:
                break
            try:
                block = self.store.get_block(idx)
            except StoreErr:
                self._sig_backlog.pop(idx)
                self._sig_wait_commit.discard(idx)
                continue
            if idx in self._sig_wait_commit and not block.state_hash():
                # this bucket already failed verification against the
                # still-empty body; the only event that can change the
                # outcome is our commit filling state_hash — skip at zero
                # ECDSA cost until then (code review r5: re-verifying
                # burned the whole budget on deterministic failures).
                # NOTE an empty state_hash is NOT itself proof of a
                # pending commit — stateless apps legitimately finalize
                # at b"" — which is why entry to this set requires an
                # actual failed verify, not the falsy hash alone.
                continue
            bucket = self._sig_backlog.pop(idx)
            self._sig_wait_commit.discard(idx)
            retained: List[BlockSignature] = []
            failed_on_empty = False
            truncated = False
            updated = False
            for pos, bs in enumerate(bucket):
                if verified >= self.SIG_POOL_VERIFY_BUDGET:
                    retained.extend(bucket[pos:])
                    truncated = True
                    break
                verified += 1
                if not block.verify(bs):
                    if not block.state_hash():
                        # may be OUR commit lagging (peers sign after
                        # theirs fills state_hash): retry after commit
                        retained.append(bs)
                        failed_on_empty = True
                    else:
                        self.logger.warning(
                            "Invalid block signature for block %d "
                            "(validator=%s rr=%d txs=%d)",
                            idx,
                            bs.validator_hex()[:12],
                            block.round_received(),
                            len(block.transactions()),
                        )
                    continue
                block.set_signature(bs)
                updated = True
            if updated:
                self.store.set_block(block)
                if len(block.signatures) > self.trust_count and (
                    self.anchor_block is None or block.index() > self.anchor_block
                ):
                    self.anchor_block = block.index()
            if retained:
                self._sig_backlog[idx] = retained
                # arm the skip only when EVERY retained signature actually
                # failed against the empty body — budget-truncated ones
                # were never verified, and for a stateless app (hash stays
                # b"" forever) the skip would deny them a first pass for
                # good (code review r5)
                if failed_on_empty and not truncated:
                    self._sig_wait_commit.add(idx)

    def run_consensus(self) -> None:
        """The full pipeline with per-pass timing into the obs layer
        (reference: src/node/core.go:335-377). Durations ride the
        injected clock, not perf_counter, so the per-pass histograms are
        byte-deterministic under the simulator's virtual time (where
        every pass reads as zero-cost, which is exactly the sim's model)."""
        clock = self.obs.clock
        for name, phase, pass_ in (
            ("DivideRounds", "divide_rounds", self.divide_rounds),
            ("DecideFame", "decide_fame", self.decide_fame),
            ("DecideRoundReceived", "decide_round_received",
             self.decide_round_received),
            ("ProcessDecidedRounds", "process_decided_rounds",
             self.process_decided_rounds),
            ("ProcessSigPool", "process_sig_pool", self.process_sig_pool),
        ):
            start = clock.monotonic()
            pass_()
            dur = clock.monotonic() - start
            self._pass_hist.labels(phase=phase).observe(dur)
            self.obs.tracer.record("consensus." + phase, start, dur)  # obs-ok: phases are the literal tuple above
            self.logger.debug("%s() duration=%dns", name, int(dur * 1e9))

    # ------------------------------------------------------------------
    # anchor / reset / bootstrap (reference: src/hashgraph/hashgraph.go:1302-1410)
    # ------------------------------------------------------------------

    def get_anchor_block_with_frame(
        self, max_index: Optional[int] = None
    ) -> Tuple[Block, Frame]:
        """The freshest servable anchor: a block with >1/3 accumulated
        signatures and a buildable frame, at or below `max_index`.

        `max_index` caps the anchor at the app's last-committed block: the
        commit channel is async (reference analog src/node/node.go:323-345),
        so the hashgraph's anchor_block can run up to a full channel ahead
        of the app — serving it would make the donor's get_snapshot fail
        ("snapshot N not found") and starve every joiner until the commit
        loop catches up. Capping here makes that starvation impossible by
        construction (VERDICT r4 #2). Signatures on locally stored blocks
        were verified before being attached (process_sig_pool), so the
        threshold check is a length test, not an ECDSA pass."""
        if self.anchor_block is None:
            raise ValueError("No Anchor Block")
        idx = self.anchor_block
        if max_index is not None and max_index < idx:
            idx = max_index
        # bounded walk (code review r5): blocks below our own reset anchor
        # have no rebuildable frames (reset cleared their rounds), and a
        # donor whose chain is healthy finds a signed anchor within a few
        # steps — so don't let a pathological store turn every joiner
        # request into an O(cache) scan under core_lock
        floor = max(self._reset_anchor_index, idx - 128)
        while idx >= floor:
            try:
                block = self.store.get_block(idx)
            except StoreErr:
                break
            if len(block.signatures) > self.trust_count:
                try:
                    frame = self.get_frame(block.round_received())
                except StoreErr:
                    idx -= 1
                    continue
                return block, frame
            idx -= 1
        raise ValueError(
            "No servable anchor"
            + (f" at or below block {max_index}" if max_index is not None else "")
        )

    def reset(self, block: Block, frame: Frame) -> None:
        self.obs.flightrec.record(
            "hashgraph.reset", block=block.index(),
            round=block.round_received(),
        )
        # any incremental device state is invalid after a reset
        eng = getattr(self, "_live_device_engine", None)
        if eng is not None:
            eng.detach()
            self._live_device_engine = None
        self.last_consensus_round = None
        self.first_consensus_round = None
        self.anchor_block = None

        self.undetermined_events = []
        self.pending_rounds = []
        self.pending_loaded_events = 0
        self.topological_index = 0

        self._round_cache.clear()
        self._timestamp_cache.clear()
        self.frozen_refs.clear()
        self.reset_floor = None
        # wait-commit flags describe pre-reset block bodies; the backlog
        # itself is kept (signatures may attach to replayed blocks) but
        # every bucket deserves a fresh verification pass against them
        self._sig_wait_commit.clear()

        participants = self.participants.to_peer_slice()
        root_map = {participants[pos].pub_key_hex: root for pos, root in enumerate(frame.roots)}
        self.store.reset(root_map)
        self.store.set_block(block)
        # keep the received frame servable: it IS the frame at the anchor's
        # round_received, already validated against the block's signed
        # FrameHash. Without it, a fresh-synced node that becomes an anchor
        # holder cannot rebuild the frame (the round's consensus bookkeeping
        # predates the reset) and every FastForwardRequest it serves fails
        # with a missing-round error — observed livelocking a cluster whose
        # only Babbling node was a fresh joiner. Pinned on the hashgraph as
        # well: the store's frame cache is an evicting LRU, and a stalled
        # anchor must stay servable past cache_size newer rounds.
        self.store.set_frame(frame)
        self._reset_frame = frame
        self._reset_anchor_index = block.index()
        self._set_last_consensus_round(block.round_received())

        for ev in frame.events:
            self.insert_event(ev, False)

        # Seed the last-consensus-event baseline recoverable from the frame
        # itself: frame events are the events RECEIVED at the anchor round,
        # and round-received is monotone along each self-parent chain, so a
        # participant's highest-indexed frame event IS its last consensus
        # event as of the anchor. Without this, the next frame this node
        # builds constructs roots for participants quiet since the anchor
        # from the anchor ROOT (their first-received event) instead of
        # their last consensus event — a divergent FrameHash, hence a
        # byte-divergent block (the round-5 root cause of the mixed-backend
        # fast-sync divergence; the section path's consensus_baseline
        # refines this for participants quiet since BEFORE the anchor,
        # whose correct roots the frame's root_map already carries).
        last_per_creator: Dict[str, Event] = {}
        for ev in frame.events:
            cur = last_per_creator.get(ev.creator())
            if cur is None or ev.index() > cur.index():
                last_per_creator[ev.creator()] = ev
        for p, ev in last_per_creator.items():
            self.store.seed_last_consensus_event(p, ev.hex())

    # ------------------------------------------------------------------
    # fast-sync live section (beyond the reference — see section.py)
    # ------------------------------------------------------------------

    def get_section(self, anchor_round: int, anchor_block_index: int = -1) -> Section:
        """Donor side: everything decided or pending above the anchor cut.
        Caller must hold the node's core lock so the snapshot is consistent.
        `anchor_block_index` keys the accumulated-signature proof for the
        blocks above the anchor (verify_section on the joiner)."""
        last_consensus = (
            self.last_consensus_round
            if self.last_consensus_round is not None
            else anchor_round
        )

        # Per-column collection: every event above the joiner's post-reset
        # base head (its frame head, or the frame root's self-parent for
        # columns absent from the frame). This is exactly the diff a fresh
        # reset store would request, so self-parent chains stay intact.
        frame = self.get_frame(anchor_round)
        peer_slice = self.participants.to_peer_slice()
        base_idx: Dict[str, int] = {
            peer.pub_key_hex: frame.roots[i].self_parent.index
            for i, peer in enumerate(peer_slice)
        }
        for ev in frame.events:
            p = ev.creator()
            if ev.index() > base_idx[p]:
                base_idx[p] = ev.index()

        events: List[Event] = []
        seen = set()
        for p, base in base_idx.items():
            for h in self.store.participant_events(p, base):
                ev = self.store.get_event(h)
                if ev.round is None:
                    ev.set_round(self.round(h))
                if ev.lamport_timestamp is None:
                    ev.set_lamport_timestamp(self.lamport_timestamp(h))
                events.append(ev)
                seen.add(h)
        events.sort(key=lambda e: e.topological_index)

        # from anchor_round INCLUSIVE: the anchor round's RoundInfo carries
        # the witness set every post-reset round computation grounds on —
        # without it, a joiner whose section has no higher decided rounds
        # recreates round(anchor) empty on first use, every new event
        # computes round == anchor (strongly_see needs 2/3 of the TRUE
        # witness set to advance), and consensus freezes at the anchor
        # forever (round-5 capture: 3,999 of 4,000 backlogged events in
        # round 22, witness_state {22: (1, 0)})
        rounds: Dict[int, RoundInfo] = {}
        for r in range(anchor_round, self.store.last_round() + 1):
            try:
                rounds[r] = self.store.get_round(r)
            except StoreErr:
                continue

        # refs for other-parents below the cut (frame events of the anchor
        # round are shipped separately and are not "frozen")
        frame_hashes = {e.hex() for e in frame.events}
        frozen: List[FrozenRef] = []
        frozen_seen = set()
        for ev in events:
            op = ev.other_parent()
            if (
                op != ""
                and op not in seen
                and op not in frame_hashes
                and op not in frozen_seen
            ):
                try:
                    ope = self.store.get_event(op)
                except StoreErr:
                    # a donor that itself fast-synced may hold only a ref —
                    # forward it, or a joiner chaining off this donor cannot
                    # resolve the other-parent and is stuck retrying
                    ref = self.frozen_refs.get(op)
                    if ref is not None:
                        frozen_seen.add(op)
                        frozen.append(ref)
                    continue
                frozen_seen.add(op)
                frozen.append(
                    FrozenRef(
                        hash=op,
                        creator_id=self.participants.by_pub_key[ope.creator()].id,
                        index=ope.index(),
                        round=self.round(op),
                        lamport=self.lamport_timestamp(op),
                    )
                )

        frames = [
            self.get_frame(r) for r in range(anchor_round + 1, last_consensus + 1)
        ]
        # stored blocks (with accumulated validator signatures) for every
        # block the joiner will replay from these frames — its proof the
        # continuation is the network's chain, not this donor's invention
        proof_blocks: Dict[int, Block] = {}
        if anchor_block_index >= 0:
            for i in range(anchor_block_index + 1, self.store.last_block_index() + 1):
                try:
                    proof_blocks[i] = self.store.get_block(i)
                except StoreErr:
                    continue

        # Truncate to the provable prefix. The joiner refuses any replayed
        # block below its 2-round trust window without >1/3 valid
        # signatures (verify_section) — and blocks committed right before
        # a validator die-off may NEVER gather them (the signers are
        # gone). Shipping those frames would make every fast-forward from
        # this donor fail permanently. Instead, ship frames only up to one
        # round past the first unprovable block — inside the joiner's
        # trust window — and let the joiner recompute the rest from the
        # shipped events through its own consensus (same DAG, same
        # decisions; the section docstring's "truncation only delays the
        # joiner" promise, made real).
        if anchor_block_index >= 0:
            next_index = anchor_block_index + 1
            cut_round = None
            for f in frames:
                if not f.events:
                    continue
                valid = self._block_proof_count(
                    f, proof_blocks.get(next_index), next_index
                )
                if valid <= self.trust_count:
                    cut_round = f.round + 1
                    break
                next_index += 1
            if cut_round is not None:
                frames = [f for f in frames if f.round <= cut_round]
                # the joiner's apply_section scrubs all decided metadata
                # above its shipped-frame ceiling regardless (advisor r3:
                # donor-stamped rounds above the cut must not seed block
                # composition); don't ship what will be ignored
                rounds = {r: ri for r, ri in rounds.items() if r <= cut_round}
        base_meta = [
            FrozenRef(
                hash=ev.hex(),
                creator_id=self.participants.by_pub_key[ev.creator()].id,
                index=ev.index(),
                round=self.round(ev.hex()),
                lamport=self.lamport_timestamp(ev.hex()),
            )
            for ev in frame.events
        ]

        # last consensus event per participant AS OF the anchor round: walk
        # each chain down from the donor's current last-consensus-event until
        # round-received <= anchor. Frame roots for participants quiet since
        # the anchor are built from exactly this event (get_frame), so the
        # joiner must share it or its frame hashes diverge from the network.
        consensus_baseline: Dict[str, str] = {}
        for p in self.participants.to_pub_key_slice():
            h, is_root = self.store.last_consensus_event_from(p)
            while not is_root:
                try:
                    ev = self.store.get_event(h)
                except StoreErr:
                    h = ""
                    break
                if ev.round_received is not None and ev.round_received <= anchor_round:
                    break
                h = ev.self_parent()
            if not is_root and h:
                consensus_baseline[p] = h
        return Section(
            anchor_round=anchor_round,
            last_consensus_round=last_consensus,
            events=events,
            rounds=rounds,
            frames=frames,
            frozen_refs=frozen,
            base_meta=base_meta,
            proof_blocks=proof_blocks,
            consensus_baseline=consensus_baseline,
        )

    def verify_section(self, anchor_block: Block, section: Section) -> None:
        """Joiner side, BEFORE any state is mutated: check that the chain
        the section replays is the network's, not a single donor's
        fabrication.

        Every event must carry a valid creator signature. Every replayed
        block must be endorsed by >1/3 of the validator set (the
        check_block threshold): the donor ships its stored blocks as proof,
        whose signatures cover the full body (index, round-received, state
        hash, frame hash, txs) — so a proof block with enough valid
        signatures whose identity fields match the frame we will replay
        pins that frame to the network's chain.

        Residual trust window, stated honestly: the freshest two rounds are
        exempt from the proof requirement, because a block's signatures
        ride self-events of strictly later rounds and cannot have
        propagated yet. A donor therefore gets an optimistic window of at
        most two replayed rounds whose ordering is its word alone — the
        same post-anchor trust the reference extends when re-deciding from
        donor-gossiped data — and forging even that window requires a
        malicious *validator* (events are signature-checked, so frame
        contents must be real validator events). Everything deeper must be
        proven or the sync is rejected; a donor that truncates its section
        to stay inside the window only delays the joiner, which picks up
        the rest through ordinary gossip."""
        for ev in section.events:
            if not ev.verify():
                raise ValueError("Invalid Event signature in fast-sync section")

        # frames must be the contiguous round range above the anchor (the
        # donor builds exactly that, get_section) — gaps would desynchronize
        # the frame->block index chain that pairs proofs with frames, and a
        # round "skipped" by the donor would keep donor-stamped metadata
        # below the scrub ceiling without any frame to pin it
        expected = section.anchor_round + 1
        for f in section.frames:
            if f.round != expected:
                raise ValueError(
                    "fast-sync section: frames not contiguous from the anchor"
                    f" (got round {f.round}, want {expected})"
                )
            expected += 1

        sig_lag_floor = (
            max(f.round for f in section.frames) - 2 if section.frames else -1
        )
        # replicate process_decided_rounds' index assignment: ascending
        # frames, empty frames produce no block
        next_index = anchor_block.index() + 1
        for frame in section.frames:
            if not frame.events:
                continue
            valid = self._block_proof_count(
                frame, section.proof_blocks.get(next_index), next_index
            )
            if valid <= self.trust_count and frame.round <= sig_lag_floor:
                raise ValueError(
                    f"fast-sync section: replayed block {next_index} "
                    f"(round {frame.round}) has {valid} valid signatures, "
                    f"need {self.trust_count + 1}"
                )
            next_index += 1

        self._verify_consensus_baseline(section)

    def _verify_consensus_baseline(self, section: Section) -> None:
        """The baseline hashes seed future frame-root construction
        (apply_section), so each must identify a shipped, signature-checked
        event of the claimed participant that was received at or below the
        anchor — a fabricated hash would fork every later frame the joiner
        builds."""
        known: Dict[str, Event] = {ev.hex(): ev for ev in section.events}
        for f in section.frames:
            for ev in f.events:
                known[ev.hex()] = ev
        base_hashes = {fr.hash for fr in section.base_meta}
        for p, h in section.consensus_baseline.items():
            ev = known.get(h)
            if ev is None:
                if h in base_hashes:
                    continue  # anchor-frame event, already pinned + checked
                raise ValueError(
                    "fast-sync section: consensus baseline references an "
                    "unknown event"
                )
            if ev.creator() != p:
                raise ValueError(
                    "fast-sync section: consensus baseline creator mismatch"
                )
            if ev.round_received is not None and ev.round_received > section.anchor_round:
                raise ValueError(
                    "fast-sync section: consensus baseline above the anchor"
                )

    def _section_trusted_ceiling(self, anchor_index: int, section: Section) -> int:
        """Highest round of donor-DECIDED state the joiner accepts from a
        section. Walk the shipped frames in round order (contiguity is
        enforced by verify_section), chaining block indices exactly like
        process_decided_rounds, and extend the proven prefix on every
        non-empty frame whose proof block carries >1/3 valid validator
        signatures. The ceiling is that proven prefix plus the two-round
        signature-lag window (a block's signatures ride strictly LATER
        self-events, so the freshest two rounds cannot have proofs yet) —
        anchored to the proven prefix, NOT to the donor-controlled frame
        list: fabricated frames (empty-round padding included) cannot lift
        it, because padding never extends `last_proven`."""
        frames = sorted(section.frames, key=lambda f: f.round)
        if not frames:
            return section.anchor_round
        last_proven = section.anchor_round  # the anchor block is check_block-verified
        next_index = anchor_index + 1
        for f in frames:
            if not f.events:
                continue  # empty rounds mint no block; covered transitively
                # by the index chain when a later frame proves
            valid = self._block_proof_count(
                f, section.proof_blocks.get(next_index), next_index
            )
            if valid <= self.trust_count:
                break
            last_proven = f.round
            next_index += 1
        return min(frames[-1].round, last_proven + 2)

    def apply_section(self, section: Section, anchor_index: int = -1) -> None:
        """Joiner side: replay the donor's decided state above the anchor.
        Must run right after reset(block, frame); run_consensus() afterwards
        rebuilds the donor's blocks byte-identically via the shipped frames
        and then continues live from the donor's frontier.
        `anchor_index` is the verified anchor block's index (proof-chain
        base for the scrub ceiling).

        SCRUB CEILING (round 4, advisor finding): donor authority over
        DECIDED consensus state extends exactly as far as the proof-checked
        frame prefix plus the signature-lag window
        (_section_trusted_ceiling) — the anchor round itself if no frame
        proves. Above that ceiling, frames, RoundInfo snapshots, and event
        round/lamport/round-received stamps are unproven donor metadata:
        process_decided_rounds rebuilds blocks from stored frames and
        RoundInfo consensus membership, so accepting a "decided" round
        above the provable prefix would commit a donor-fabricated block.
        Everything above the ceiling is therefore dropped here and
        RE-DECIDED by this node's own consensus passes over the
        (signature-checked) shipped events — divide_rounds recomputes
        rounds/lamports grounded in the pinned anchor metadata and
        re-queues the rounds, decide_fame re-votes, decide_round_received
        re-stamps. The residual trust surface is the two-round sig-lag
        window (verify_section) plus sub-consensus metadata of the proven
        prefix (witness sets, frozen-ref coordinates), which cannot mint
        blocks on its own."""
        cut = self._section_trusted_ceiling(anchor_index, section)
        # events/rounds/frames are this joiner's own deserialized copies
        # (core.prepare_fast_forward round-trips the section through the
        # wire codec before any of this runs), so stripping in place is safe
        events: List[Event] = section.events
        for ev in events:
            if ev.round_received is not None and ev.round_received > cut:
                ev.set_round_received(None)
            if ev.round is not None and ev.round > cut:
                ev.set_round(None)
                ev.set_lamport_timestamp(None)
        rounds = {r: ri for r, ri in section.rounds.items() if r <= cut}
        frames = [f for f in section.frames if f.round <= cut]

        # the frame base is settled by definition (anchored in the block);
        # it must never be re-received into a later round
        for h in self.undetermined_events:
            ev = self.store.get_event(h)
            ev.set_round_received(section.anchor_round)
            self.store.set_event(ev)
        self.undetermined_events = []
        self.reset_floor = section.anchor_round

        self.frozen_refs.update({fr.hash: fr for fr in section.frozen_refs})
        # frozen refs ground the round/lamport recursion for re-decided
        # events whose other-parents sit below the cut (the event bodies
        # never ship, so the recursion cannot reach past them)
        for fr in section.frozen_refs:
            self._round_cache.setdefault(fr.hash, fr.round)
            self._timestamp_cache.setdefault(fr.hash, fr.lamport)
        # adopt the donor's last-consensus-event baseline: the anchor round
        # itself is never replayed (it is settled by the frame), so without
        # this the joiner's frame roots for participants quiet since the
        # anchor would be built from a different event than the network's
        for p, h in section.consensus_baseline.items():
            self.store.seed_last_consensus_event(p, h)
        # pin the anchor frame events' consensus metadata so nothing here
        # recomputes it from the amnesiac base
        for fr in section.base_meta:
            self._round_cache[fr.hash] = fr.round
            self._timestamp_cache[fr.hash] = fr.lamport
            try:
                ev = self.store.get_event(fr.hash)
            except StoreErr:
                continue
            ev.set_round(fr.round)
            ev.set_lamport_timestamp(fr.lamport)
            self.store.set_event(ev)
        for f in frames:
            self.store.set_frame(f)
        for r in sorted(rounds):
            ri = rounds[r]
            ri.queued = True  # pending status is tracked below
            self.store.set_round(r, ri)

        # event signatures were checked by verify_section (fast_forward
        # always validates before applying); re-verifying here would double
        # the dominant ECDSA cost of catch-up
        for ev in events:
            self._check_self_parent(ev)
            self._check_other_parent(ev)
            ev.topological_index = self.topological_index
            self.topological_index += 1
            # authoritative donor metadata below the scrub ceiling — not
            # recomputed; scrubbed events (None) are re-decided instead
            if ev.round is not None:
                self._round_cache[ev.hex()] = ev.round
            if ev.lamport_timestamp is not None:
                self._timestamp_cache[ev.hex()] = ev.lamport_timestamp
            self.store.set_event(ev)
            if ev.round_received is None:
                self.undetermined_events.append(ev.hex())
                if ev.is_loaded():
                    self.pending_loaded_events += 1
            elif ev.round_received > section.anchor_round and ev.is_loaded():
                # decremented again when its round is replayed into a block
                self.pending_loaded_events += 1
            self.sig_pool.extend(ev.block_signatures())

        self.pending_rounds = [
            PendingRound(r, rounds[r].witnesses_decided())
            for r in sorted(rounds)
        ]

    def bootstrap(self) -> None:
        """Replay a persistent store's topologically-ordered events through
        the full pipeline (reference: src/hashgraph/hashgraph.go:1375-1410)."""
        topo = getattr(self.store, "db_topological_events", None)
        if topo is None:
            return
        for e in topo():
            self.insert_event(e, True)
        self.run_consensus()

    # ------------------------------------------------------------------
    # wire (reference: src/hashgraph/hashgraph.go:1414-1479)
    # ------------------------------------------------------------------

    def read_wire_info(self, wevent: WireEvent) -> Event:
        self_parent = root_self_parent(wevent.body.creator_id)
        other_parent = ""

        creator = self.participants.by_id[wevent.body.creator_id]
        creator_bytes = bytes.fromhex(creator.pub_key_hex[2:])

        if wevent.body.self_parent_index >= 0:
            self_parent = self.store.participant_event(
                creator.pub_key_hex, wevent.body.self_parent_index
            )
        if wevent.body.other_parent_index >= 0:
            try:
                other_creator = self.participants.by_id[wevent.body.other_parent_creator_id]
                other_parent = self.store.participant_event(
                    other_creator.pub_key_hex, wevent.body.other_parent_index
                )
            except (StoreErr, KeyError):
                # check if other parent can be found in the creator's root
                root = self.store.get_root(creator.pub_key_hex)
                found = False
                for re_ in root.others.values():
                    if (
                        re_.creator_id == wevent.body.other_parent_creator_id
                        and re_.index == wevent.body.other_parent_index
                    ):
                        other_parent = re_.hash
                        found = True
                        break
                if not found:
                    raise ValueError("OtherParent not found")

        event = Event(
            transactions=wevent.body.transactions,
            block_signatures=wevent.block_signatures(creator_bytes),
            parents=[self_parent, other_parent],
            creator=creator_bytes,
            index=wevent.body.index,
        )
        event.signature = wevent.signature
        event.set_wire_info(
            wevent.body.self_parent_index,
            wevent.body.other_parent_creator_id,
            wevent.body.other_parent_index,
            wevent.body.creator_id,
        )
        return event

    def valid_signature_count(self, block: Block, limit: int = None) -> int:
        """Signatures that are both cryptographically valid AND from a
        member of the validator set — a signature from any other key proves
        nothing (process_sig_pool applies the same membership filter).
        `limit` stops the (ECDSA-verify-per-signature) count early once
        reached — threshold checks only need trust_count + 1, not all N."""
        count = 0
        for s in block.get_signatures():
            if s.validator_hex() in self.participants.by_pub_key and block.verify(s):
                count += 1
                if limit is not None and count >= limit:
                    return count
        return count

    def _block_proof_count(self, frame: Frame, proof: Optional[Block],
                           expected_index: int) -> int:
        """Valid-signature count of `proof` iff it matches the block this
        frame replays (identity triple: index, round_received, frame hash)
        — the ONE pairing rule shared by the donor's provable-prefix
        truncation (get_section) and the joiner's check (verify_section);
        the two must never diverge or donors ship sections their joiners
        deterministically reject. Capped at trust_count + 1 (the threshold
        both callers compare against)."""
        if (
            proof is None
            or proof.index() != expected_index
            or proof.round_received() != frame.round
            or proof.frame_hash() != frame.hash()
        ):
            return 0
        # memoized: verify_section and _section_trusted_ceiling walk the
        # same (frame, proof) pairs back to back within one fast_forward,
        # and ECDSA verification dominates catch-up cost. The key binds
        # the FULL signed body digest (signature validity depends on every
        # body field, not just the pairing identity — a forged proof
        # reusing a genuine block's signature set over an altered body
        # must not share a cache slot with the genuine one, ADVICE r4)
        # plus the signature set being counted. The digest is memoized on
        # the proof object because verify_section + _section_trusted_ceiling
        # hash the same proofs back to back — re-marshalling every
        # transaction twice per walk would put an O(tx bytes) serialization
        # back on the catch-up hot path. Donor-side proofs are LIVE store
        # blocks whose state_hash is replaced by commit(), so the memo is
        # keyed on the state_hash object's identity and self-invalidates
        # across that mutation (code review r5).
        memo = getattr(proof, "_body_digest", None)
        if memo is not None and memo[0] is proof.body.state_hash:
            digest = memo[1]
        else:
            digest = proof.body.hash()
            proof._body_digest = (proof.body.state_hash, digest)
        key = (
            digest,
            tuple(sorted(proof.signatures.items())),
        )
        cached = self._proof_count_cache.get(key)
        if cached is not None:
            return cached
        count = self.valid_signature_count(proof, limit=self.trust_count + 1)
        while len(self._proof_count_cache) >= 256:
            # FIFO eviction: dropping one cold entry keeps the back-to-back
            # verify_section / _section_trusted_ceiling walk hot (ADVICE r4)
            self._proof_count_cache.pop(next(iter(self._proof_count_cache)))
        self._proof_count_cache[key] = count
        return count

    def check_block(self, block: Block) -> None:
        """Valid iff strictly more than 1/3 of participants signed."""
        valid = self.valid_signature_count(block)
        if valid <= self.trust_count:
            raise ValueError(
                f"Not enough valid signatures: got {valid}, need {self.trust_count + 1}"
            )

    def check_block_immutable(self, block: Block) -> None:
        """SAFETY INVARIANT (VERDICT r4): a committed body at index i is
        never replaced or divergently re-derived. Legitimate rewrites of a
        stored block only ADD to it — the app fills state_hash after
        commit, signatures accumulate — so the consensus-derived body
        fields must match whatever is already stored at that index (e.g.
        a bootstrap replay re-minting the identical block passes).
        Raising makes a diverged node stop loudly instead of compounding
        a fork; the error carries both bodies for the post-mortem."""
        try:
            old = self.store.get_block(block.index())
        except StoreErr:
            return
        divergent = (
            old.round_received() != block.round_received()
            or old.frame_hash() != block.frame_hash()
            or old.transactions() != block.transactions()
        )
        if not divergent and old.state_hash() and block.state_hash():
            divergent = old.state_hash() != block.state_hash()
        if divergent:
            self.fork_evidence += 1
            msg = (
                f"block {block.index()} body divergence: stored "
                f"(round_received={old.round_received()}, "
                f"frame_hash={old.frame_hash().hex()[:16]}, "
                f"txs={len(old.transactions())}) vs re-derived "
                f"(round_received={block.round_received()}, "
                f"frame_hash={block.frame_hash().hex()[:16]}, "
                f"txs={len(block.transactions())})"
            )
            self.logger.error("SAFETY: %s", msg)
            raise BlockDivergenceError(msg)

    # ------------------------------------------------------------------

    def _set_last_consensus_round(self, i: int) -> None:
        self.last_consensus_round = i
        if self.first_consensus_round is None:
            self.first_consensus_round = i
        # "number of events in round before LastConsensusRound" — declared
        # but never maintained in the reference (hashgraph.go:27 is its
        # only non-getter mention, so its round_events stat is always 0);
        # here the stat is actually kept
        try:
            self.last_committed_round_events = len(
                self.store.get_round(i - 1).round_events()
            )
        except StoreErr:
            self.last_committed_round_events = 0
