"""babble-tpu command line: `run`, `keygen`, `sim`, `explain`, `status`,
`lint`, `version`
(reference: cmd/babble/main.go:11-15, cmd/babble/commands/run.go:28-155).

Flags mirror the reference's run command; values may also come from an
optional config file `<datadir>/babble.json` or `<datadir>/babble.toml`
(flag > config file > default, the viper merge order of run.go:93-155).
One addition: `--consensus-backend {cpu,tpu}` selects the host or device
consensus engine (SURVEY §7).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from . import version as version_mod
from .babble import Babble, BabbleConfig, default_data_dir, keygen
from .node import Config as NodeConfig
from .proxy import InmemDummyClient, SocketAppProxy

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "panic": logging.CRITICAL,
}


def _load_config_file(datadir: str) -> dict:
    """`babble.{json,toml}` under the datadir (reference: run.go:129-155)."""
    jpath = os.path.join(datadir, "babble.json")
    if os.path.exists(jpath):
        with open(jpath) as f:
            return json.load(f)
    tpath = os.path.join(datadir, "babble.toml")
    if os.path.exists(tpath):
        import tomllib

        with open(tpath, "rb") as f:
            return tomllib.load(f)
    return {}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="babble-tpu", description="TPU-native hashgraph consensus node")
    sub = p.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="Run a babble node")
    run.add_argument("--datadir", default=default_data_dir(),
                     help="Top-level directory for configuration and data")
    run.add_argument("--log", default="info", choices=sorted(LOG_LEVELS),
                     help="Log level")
    run.add_argument("-l", "--listen", default=":1337",
                     help="Listen IP:Port for the babble node")
    run.add_argument("-t", "--timeout", type=float, default=1.0,
                     help="TCP timeout in seconds")
    run.add_argument("--max-pool", type=int, default=2,
                     help="Connection pool size max")
    run.add_argument("--standalone", action="store_true",
                     help="Do not create a proxy (use the built-in dummy app)")
    run.add_argument("-p", "--proxy-listen", default="127.0.0.1:1338",
                     help="Listen IP:Port for the babble proxy")
    run.add_argument("-c", "--client-connect", default="127.0.0.1:1339",
                     help="IP:Port to connect to the client app")
    run.add_argument("-s", "--service-listen", default="",
                     help="Listen IP:Port for the HTTP service")
    run.add_argument("--service-remote-debug", action="store_true",
                     help="Allow /debug/* (profiler, stack dumps) from "
                          "non-loopback clients")
    run.add_argument("--store", action="store_true",
                     help="Use persistent on-disk store instead of in-mem")
    run.add_argument("--cache-size", type=int, default=500,
                     help="Number of items in LRU caches")
    run.add_argument("--heartbeat", type=float, default=1.0,
                     help="Time between gossips in seconds")
    run.add_argument("--sync-limit", type=int, default=100,
                     help="Max number of events for sync")
    run.add_argument("--consensus-backend", default="cpu", choices=("cpu", "tpu"),
                     help="Run the five-pass pipeline on host (cpu) or device (tpu)")
    run.add_argument("--mesh-devices", type=int, default=0,
                     help="With --consensus-backend=tpu: shard the device "
                          "passes over this many chips (0 = single device)")
    run.add_argument("--dispatch-queue-depth", type=int, default=4,
                     help="Max device dispatches in flight in the async "
                          "dispatch queue (1 = single-slot overlap, 0 = "
                          "disable the queued-mesh rung)")
    run.add_argument("--dispatch-batch-deadline", type=float, default=0.0,
                     help="Hold gossip-staged rows up to this many seconds "
                          "(or until a size threshold) before dispatching, "
                          "batching device work across syncs (0 = no hold)")
    run.add_argument("--dispatch-batch-rows", type=int, default=64,
                     help="Delta-row threshold that releases a held batch "
                          "and switches the dispatch onto the round-batched "
                          "(pointer-doubling) path; also sizes the live "
                          "engine's device batch")
    run.add_argument("--mesh-validator-shards", type=int, default=1,
                     help="With --mesh-devices N: fold the mesh into a 2-D "
                          "(validators, rounds) layout with this many "
                          "validator shards (must divide N; 1 = rounds-only)")
    run.add_argument("--packed-voting", choices=("0", "1", "auto"),
                     default="auto",
                     help="Voting-table layout: 1 packs the validator axis "
                          "into uint32 lanes with popcount tallies "
                          "(byte-equal, ~8x smaller voting state), 0 keeps "
                          "the wide bool layout, auto packs at large N; "
                          "env BABBLE_PACKED_VOTING overrides at call time")
    run.add_argument("--ingress-batch-bytes", type=int, default=65536,
                     help="Byte threshold that releases an ingress batch "
                          "to the tx worker; a single tx at/over it "
                          "bypasses coalescing and ships alone")
    run.add_argument("--ingress-batch-deadline", type=float, default=0.0,
                     help="Hold a partial ingress batch up to this many "
                          "seconds waiting for more submissions "
                          "(0 = release on every pump)")
    run.add_argument("--ingress-queue-cap", type=int, default=8192,
                     help="Max transactions held in the ingress pipeline "
                          "before submissions get the shed verdict "
                          "(0 = unbounded)")
    run.add_argument("--ingress-client-rate", type=float, default=0.0,
                     help="Per-client token-bucket rate in tx/s (client = "
                          "peer addr or app-supplied client_id); enables "
                          "deficit-round-robin fairness (0 = unlimited)")
    run.add_argument("--metrics", action="store_true",
                     help="Log periodic metrics-registry snapshots at info "
                          "(the registry always serves GET /metrics on the "
                          "HTTP service regardless)")
    run.add_argument("--flightrec-dir", default="",
                     help="Write flight-recorder dump artifacts (stall/"
                          "flap/SLO-breach triage) into this directory; "
                          "empty keeps dumps in memory, served at "
                          "GET /debug/flightrec either way")
    run.add_argument("--no-slo", action="store_true",
                     help="Disable the SLO engine (GET /debug/slo and the "
                          "babble_slo_* burn-rate gauges)")

    kg = sub.add_parser("keygen", help="Create new key pair")
    kg.add_argument("--datadir", default=default_data_dir(),
                    help="Directory to write priv_key.pem into")

    sim = sub.add_parser(
        "sim",
        help="Deterministic cluster simulation / seed sweep (docs/sim.md)",
    )
    sim.add_argument("--seed", type=int, default=0,
                     help="Master seed (first seed when sweeping)")
    sim.add_argument("--sweep", type=int, default=0, metavar="N",
                     help="Run N consecutive seeds starting at --seed")
    sim.add_argument("--nodes", type=int, default=4,
                     help="Cluster size")
    sim.add_argument("--plan", default="clean",
                     help="Fault plan: preset name (clean, lossy, "
                          "partition_heal, crash_restart, chaos) or a "
                          "FaultPlan JSON file path")
    sim.add_argument("--store", default="inmem", choices=("inmem", "sqlite"),
                     help="Per-node store backend (sqlite survives crashes)")
    sim.add_argument("--consensus-backend", default="cpu",
                     choices=("cpu", "tpu"),
                     help="Consensus engine for the simulated nodes")
    sim.add_argument("--target-block", type=int, default=15,
                     help="Stop once every live node commits this block")
    sim.add_argument("--until", type=float, default=60.0,
                     help="Virtual-time deadline in seconds")
    sim.add_argument("--artifact-dir", default="docs/artifacts",
                     help="Where divergence replay artifacts are written")
    sim.add_argument("--log", default="error", choices=sorted(LOG_LEVELS),
                     help="Log level for the simulated nodes")

    ex = sub.add_parser(
        "explain",
        help="Decision provenance: explain one round (live node or "
             "offline bisect; docs/observability.md)",
    )
    ex.add_argument("--addr", default="127.0.0.1:8000",
                    help="HTTP service address of a running node "
                         "(GET /debug/explain)")
    ex.add_argument("--block", type=int, default=None,
                    help="Explain the round that received this block")
    ex.add_argument("--round", type=int, default=None,
                    help="Explain this consensus round directly")
    ex.add_argument("--bisect", nargs=2, metavar=("A.json", "B.json"),
                    default=None,
                    help="Offline: diff two exported provenance streams "
                         "(sim export_provenance files) and print the "
                         "earliest divergent cell")
    ex.add_argument("--artifact-dir", default="",
                    help="With --bisect: also export the localization "
                         "triage artifact into this directory")
    ex.add_argument("--smoke", type=int, default=0, metavar="N",
                    help="Self-test: run the N-seed bisector smoke "
                         "(seeded synthetic divergence must localize "
                         "exactly; clean pairs must localize nothing)")

    st = sub.add_parser(
        "status",
        help="Cluster health dashboard: fleet frontier table, skew/"
             "agreement series and partition suspicion from a live "
             "node's GET /debug/cluster (docs/observability.md)",
    )
    st.add_argument("--addr", default="127.0.0.1:8000",
                    help="HTTP service address of a running node")
    st.add_argument("--watch", type=float, default=0.0, metavar="SECS",
                    help="Re-render every SECS seconds until interrupted "
                         "(0 = render once and exit)")
    st.add_argument("--json", action="store_true",
                    help="Print the raw /debug/cluster document instead "
                         "of the rendered dashboard")

    # `lint` is dispatched before the main parse (main()): the analysis
    # runner owns its own argparse, and argparse.REMAINDER inside a
    # subparser mis-handles leading optionals. Registered here so it
    # shows up in --help.
    sub.add_parser(
        "lint",
        help="Consensus-grade static analysis (docs/analysis.md)",
        add_help=False,
    )

    sub.add_parser("version", help="Show version info")
    return p


_SENTINEL = object()


def _explicit_attrs(argv) -> set:
    """Which run-command dests the user actually passed on the command
    line. Detected by re-parsing with every default swapped for a
    sentinel — argparse itself then accounts for glued short options
    (-t5), '=' forms, and prefix abbreviations (--heart 2)."""
    p = build_parser()
    sub = next(
        a for a in p._actions if isinstance(a, argparse._SubParsersAction)
    )
    for act in sub.choices["run"]._actions:
        if act.dest != "help":
            act.default = _SENTINEL
    ns = p.parse_args(argv)
    return {
        k for k, v in vars(ns).items()
        if v is not _SENTINEL and k != "command"
    }


def _merge_config_file(args: argparse.Namespace, argv=None) -> None:
    """Config-file values fill in anything the user did not pass
    explicitly (flags win, like the reference's viper binding,
    run.go:93-127). Explicitness is detected by argparse itself, not by
    comparing against defaults — a flag explicitly set TO its default
    must still beat the file."""
    cfg = _load_config_file(args.datadir)
    if not cfg:
        return
    argv = list(sys.argv[1:] if argv is None else argv)
    explicit = _explicit_attrs(argv)

    mapping = {
        "log": "log", "listen": "listen", "timeout": "timeout",
        "max-pool": "max_pool", "standalone": "standalone",
        "proxy-listen": "proxy_listen", "client-connect": "client_connect",
        "service-listen": "service_listen",
        "service-remote-debug": "service_remote_debug", "store": "store",
        "cache-size": "cache_size", "heartbeat": "heartbeat",
        "sync-limit": "sync_limit", "consensus-backend": "consensus_backend",
        "mesh-devices": "mesh_devices", "metrics": "metrics",
        "dispatch-queue-depth": "dispatch_queue_depth",
        "dispatch-batch-deadline": "dispatch_batch_deadline",
        "dispatch-batch-rows": "dispatch_batch_rows",
        "mesh-validator-shards": "mesh_validator_shards",
        "packed-voting": "packed_voting",
        "ingress-batch-bytes": "ingress_batch_bytes",
        "ingress-batch-deadline": "ingress_batch_deadline",
        "ingress-queue-cap": "ingress_queue_cap",
        "ingress-client-rate": "ingress_client_rate",
    }
    for file_key, attr in mapping.items():
        if file_key in cfg and attr not in explicit:
            setattr(args, attr, cfg[file_key])


def run_command(args: argparse.Namespace) -> int:
    logging.basicConfig(
        level=LOG_LEVELS[args.log],
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    logger = logging.getLogger("babble")

    # knob validation: batch sizing is a property of the dispatch queue,
    # so a non-default --dispatch-batch-rows with queuing disabled is a
    # configuration contradiction, not something to silently ignore
    if args.dispatch_batch_rows < 1:
        logger.error("--dispatch-batch-rows must be >= 1")
        return 1
    if args.dispatch_batch_rows != 64 and args.dispatch_queue_depth == 0:
        logger.error(
            "--dispatch-batch-rows requires --dispatch-queue-depth > 0 "
            "(the queued-mesh rung is what batches rows)"
        )
        return 1
    if args.mesh_validator_shards < 1:
        logger.error("--mesh-validator-shards must be >= 1")
        return 1
    if (
        args.mesh_validator_shards > 1
        and (
            args.mesh_devices < 2
            or args.mesh_devices % args.mesh_validator_shards != 0
        )
    ):
        logger.error(
            "--mesh-validator-shards=%d must divide --mesh-devices=%d",
            args.mesh_validator_shards, args.mesh_devices,
        )
        return 1
    if str(args.packed_voting) not in ("0", "1", "auto"):
        # config-file values bypass argparse choices — validate here too
        logger.error("--packed-voting must be 0, 1 or auto")
        return 1

    if args.ingress_batch_bytes < 1:
        logger.error("--ingress-batch-bytes must be >= 1")
        return 1
    if args.ingress_batch_deadline < 0:
        logger.error("--ingress-batch-deadline must be >= 0")
        return 1
    if args.ingress_queue_cap < 0:
        logger.error("--ingress-queue-cap must be >= 0 (0 = unbounded)")
        return 1
    if args.ingress_client_rate < 0:
        logger.error("--ingress-client-rate must be >= 0 (0 = unlimited)")
        return 1
    # contradiction, not something to silently ignore (the rate limiter's
    # overrate shed bound is derived from the queue cap — unbounded
    # admission with a per-client rate would park flooder backlogs forever)
    if args.ingress_client_rate > 0 and args.ingress_queue_cap == 0:
        logger.error(
            "--ingress-client-rate requires --ingress-queue-cap > 0 "
            "(rate limiting needs a bounded admission queue to shed into)"
        )
        return 1

    if args.standalone:
        proxy = InmemDummyClient(logger)
    else:
        proxy = SocketAppProxy(
            client_addr=args.client_connect,
            bind_addr=args.proxy_listen,
            timeout=args.heartbeat,
            logger=logger,
        )

    config = BabbleConfig(
        data_dir=args.datadir,
        bind_addr=args.listen,
        service_addr=args.service_listen,
        service_remote_debug=args.service_remote_debug,
        max_pool=args.max_pool,
        store=args.store,
        log_level=args.log,
        proxy=proxy,
        node=NodeConfig(
            heartbeat_timeout=args.heartbeat,
            tcp_timeout=args.timeout,
            cache_size=args.cache_size,
            sync_limit=args.sync_limit,
            consensus_backend=args.consensus_backend,
            mesh_devices=args.mesh_devices,
            dispatch_queue_depth=args.dispatch_queue_depth,
            dispatch_batch_deadline=args.dispatch_batch_deadline,
            dispatch_batch_rows=args.dispatch_batch_rows,
            mesh_validator_shards=args.mesh_validator_shards,
            packed_voting=str(args.packed_voting),
            ingress_batch_bytes=args.ingress_batch_bytes,
            ingress_batch_deadline=args.ingress_batch_deadline,
            ingress_queue_cap=args.ingress_queue_cap,
            ingress_client_rate=args.ingress_client_rate,
            metrics_log=args.metrics,
            flightrec_dir=args.flightrec_dir or None,
            slo_enabled=not args.no_slo,
            logger=logger,
        ),
    )

    engine = Babble(config)
    try:
        engine.init()
    except Exception as e:  # noqa: BLE001 — startup errors go to the operator
        logger.error("Cannot initialize engine: %s", e)
        return 1
    try:
        engine.run()
    except KeyboardInterrupt:
        engine.shutdown()
    return 0


def sim_command(args: argparse.Namespace) -> int:
    """Deterministic simulation driver. Single-seed mode prints the run
    result plus its block digest (the replay fingerprint: two invocations
    with the same seed and plan must print the same digest). Sweep mode
    runs N consecutive seeds and exits nonzero if any seed diverged —
    each failure leaves a replay artifact under --artifact-dir."""
    logging.basicConfig(
        level=LOG_LEVELS[args.log],
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    from .sim import FaultPlan, run_one, run_sweep

    if os.path.exists(args.plan):
        with open(args.plan) as f:
            plan = FaultPlan.from_json(f.read())
    else:
        plan = args.plan  # preset name; run_one/run_sweep resolve it

    common = dict(
        plan=plan,
        n=args.nodes,
        store=args.store,
        backend=args.consensus_backend,
        until=args.until,
        target_block=args.target_block,
        artifact_dir=args.artifact_dir,
    )
    if args.sweep > 0:
        def progress(row):
            status = "ok" if row["ok"] else f"DIVERGED ({row['artifact']})"
            print(
                f"seed {row['seed']:>6}: {status}  "
                f"blocks={row['blocks_checked']} t={row['virtual_time']}"
                f" restarts={row['restarts']} flips={row['catchup_flips']}"
            )
            if not row["ok"] and row.get("localized"):
                loc = row["localized"]
                print(
                    "  localized: round %s %s/%s cell %s (%s)" % (
                        loc["round"], loc["pass"], loc["table"],
                        (loc.get("cell") or "")[:18],
                        row.get("bisect_artifact"),
                    )
                )
            if not row["ok"] and row.get("flightrec"):
                print(f"  flight-recorder triage: {row['flightrec']}")

        summary = run_sweep(
            range(args.seed, args.seed + args.sweep),
            progress=progress, **common,
        )
        print(
            f"\n{summary['seeds']} seeds, {summary['failed']} failed, "
            f"{summary['total_blocks_checked']} blocks byte-checked"
        )
        if summary["failed"]:
            print(f"failing seeds: {summary['failed_seeds']}")
            print(f"replay artifacts: {summary['artifacts']}")
            if summary.get("flightrec_artifacts"):
                print(
                    "flight-recorder triage: "
                    f"{summary['flightrec_artifacts']}"
                )
            if summary.get("bisect_artifacts"):
                print(
                    "bisection triage: "
                    f"{summary['bisect_artifacts']}"
                )
            return 1
        return 0

    res = run_one(args.seed, **common)
    out = {k: v for k, v in res.items() if k != "rows"}
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0 if res["ok"] else 1


def explain_command(args: argparse.Namespace) -> int:
    """`babble-tpu explain` — three modes, one triage surface:

    - `--smoke N` (CI entry): seeded synthetic bisector self-test; the
      injected fame flip must localize to its exact cell and a clean
      pair must localize nothing. Nonzero exit on any failure.
    - `--bisect A.json B.json`: offline first-divergence bisection of
      two exported provenance streams (sim `export_provenance` files).
    - `--addr/--block/--round`: fetch the decision dossier from a live
      node's GET /debug/explain.
    """
    from .obs import DivergenceBisector, run_bisector_smoke

    if args.smoke > 0:
        failures = run_bisector_smoke(seeds=args.smoke)
        for f in failures:
            print(f"FAIL: {f}")
        print(
            f"bisector smoke: {args.smoke} seeds, "
            f"{len(failures)} failures"
        )
        return 1 if failures else 0

    if args.bisect is not None:
        a_path, b_path = args.bisect
        with open(a_path) as f:
            a_doc = json.load(f)
        with open(b_path) as f:
            b_doc = json.load(f)
        a_name = os.path.splitext(os.path.basename(a_path))[0]
        b_name = os.path.splitext(os.path.basename(b_path))[0]
        bis = DivergenceBisector(args.artifact_dir or "docs/artifacts")
        loc = bis.bisect(a_name, a_doc, b_name, b_doc)
        if loc is None:
            print("streams agree: no divergent cell")
            return 0
        print(json.dumps(loc, indent=2, sort_keys=True))
        if args.artifact_dir:
            path = bis.export(
                loc, f"bisect-{a_name}-vs-{b_name}.json",
                context={"a": a_path, "b": b_path},
            )
            print(f"triage artifact: {path}")
        return 1

    if args.block is None and args.round is None:
        print("explain needs --block, --round, --bisect or --smoke",
              file=sys.stderr)
        return 2
    url = f"http://{args.addr}/debug/explain?"
    url += (f"round={args.round}" if args.round is not None
            else f"block={args.block}")
    import urllib.request

    with urllib.request.urlopen(url, timeout=5.0) as resp:
        doc = json.loads(resp.read().decode())
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def render_status(doc: dict) -> str:
    """Render one GET /debug/cluster document as a one-screen dashboard.
    Pure (doc -> str), so the status smoke and tests exercise the exact
    strings an operator sees."""
    lines = []
    addr = doc.get("addr") or "?"
    derived = doc.get("derived") or {}
    fleet = doc.get("fleet") or {}
    susp = doc.get("suspicion") or {}
    lines.append(
        f"babble-tpu cluster status  (via {addr}, "
        f"{len(fleet)} node{'s' if len(fleet) != 1 else ''})"
    )
    lines.append("")
    hdr = (
        f"{'node':<22} {'block':>6} {'round':>6} {'rung':<12} "
        f"{'undec':>5} {'txs':>5} {'sigs':>5} {'ingr':>5} "
        f"{'forks':>5} {'age':>7}"
    )
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for a in sorted(fleet):
        d = fleet[a]
        mark = "*" if a == addr else " "
        age = d.get("age")
        lines.append(
            f"{mark}{a:<21} {d.get('block', '?'):>6} "
            f"{d.get('round', '?'):>6} {str(d.get('rung', '?')):<12} "
            f"{d.get('undecided', '?'):>5} {d.get('txs', '?'):>5} "
            f"{d.get('sigs', '?'):>5} {d.get('ingress', '?'):>5} "
            f"{d.get('forks', '?'):>5} "
            f"{('%.1fs' % age) if isinstance(age, (int, float)) else '?':>7}"
        )
    lines.append("")
    skew = derived.get("babble_cluster_commit_skew_blocks", 0.0)
    rskew = derived.get("babble_cluster_round_skew", 0.0)
    agree = derived.get("babble_cluster_frontier_agreement", 1.0)
    fame = derived.get("babble_cluster_fame_latency_rounds", 0.0)
    lines.append(
        f"commit skew: {skew:g} blocks   round skew: {rskew:g}   "
        f"frontier agreement: {agree:g}   fame latency: {fame:g} rounds"
    )
    if agree < 1.0:
        lines.append(
            "!! FRONTIER DISAGREEMENT: a peer committed a different "
            "block at a common index — investigate immediately"
        )
    if susp.get("suspected"):
        lines.append(
            f"!! PARTITION SUSPECTED: components "
            f"{susp.get('components')}"
        )
    else:
        lines.append("partition: none suspected")
    return "\n".join(lines)


def status_command(args: argparse.Namespace) -> int:
    """`babble-tpu status` — fetch GET /debug/cluster from a live node
    and render the cluster dashboard; `--watch SECS` re-renders in a
    loop (docs/observability.md)."""
    import time
    import urllib.request

    url = f"http://{args.addr}/debug/cluster"

    def once() -> int:
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                doc = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — operator-facing fetch
            print(f"status: cannot fetch {url}: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(render_status(doc))
        return 0

    if args.watch <= 0:
        return once()
    try:
        while True:
            # clear-screen escape, like `watch`: the dashboard is a
            # fixed-height single screen
            sys.stdout.write("\x1b[2J\x1b[H")
            rc = once()
            sys.stdout.flush()
            time.sleep(args.watch)  # det-ok: operator watch loop on a real terminal, never under the sim clock
            if rc != 0:
                # keep watching through transient fetch errors
                continue
    except KeyboardInterrupt:
        return 0


def keygen_command(args: argparse.Namespace) -> int:
    try:
        key = keygen(args.datadir)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    from .crypto import pub_key_bytes

    print(f"Public Key: 0x{pub_key_bytes(key).hex().upper()}")
    print(f"Key written to {os.path.join(args.datadir, 'priv_key.pem')}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        from .analysis import main as lint_main

        return lint_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.command == "run":
        _merge_config_file(args, argv)
        return run_command(args)
    if args.command == "sim":
        return sim_command(args)
    if args.command == "explain":
        return explain_command(args)
    if args.command == "status":
        return status_command(args)
    if args.command == "keygen":
        return keygen_command(args)
    if args.command == "version":
        print(version_mod.version)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
