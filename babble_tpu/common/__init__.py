from .clock import Clock, SystemClock, SYSTEM_CLOCK
from .errors import StoreErr, StoreErrType, is_store_err
from .lru import LRU
from .rolling_index import RollingIndex
from .rolling_index_map import RollingIndexMap
from .hash32 import hash32

__all__ = [
    "Clock",
    "SystemClock",
    "SYSTEM_CLOCK",
    "StoreErr",
    "StoreErrType",
    "is_store_err",
    "LRU",
    "RollingIndex",
    "RollingIndexMap",
    "hash32",
]
