"""Time source seam for the node runtime.

Every wall-clock read and sleep in the node layer goes through a `Clock`
so the deterministic simulator (babble_tpu/sim/) can substitute virtual
time: a `SimClock` advanced by an event-loop scheduler instead of the OS.
Production code uses `SystemClock` (the module-level `SYSTEM_CLOCK`
singleton), which delegates straight to `time.monotonic` / `time.sleep`.
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod


class Clock(ABC):
    """Monotonic time + sleep, substitutable for virtual time."""

    @abstractmethod
    def monotonic(self) -> float: ...

    @abstractmethod
    def sleep(self, seconds: float) -> None: ...


class SystemClock(Clock):
    """The OS clock — production default."""

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


# shared default: SystemClock is stateless, one instance serves everyone
SYSTEM_CLOCK = SystemClock()
