"""Bounded LRU cache (reference: src/common/lru.go:11-156).

Python's OrderedDict gives us the recency list for free; the optional
eviction callback mirrors the reference API.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Optional


class LRU:
    def __init__(
        self,
        size: int,
        on_evict: Optional[Callable[[Any, Any], None]] = None,
        pin: Optional[Callable[[Any, Any], bool]] = None,
    ):
        if size <= 0:
            raise ValueError("LRU size must be positive")
        self.size = size
        self.on_evict = on_evict
        # `pin(key, value) -> True` exempts an entry from eviction (round
        # 5): evicting an event body that gossip still needs — an
        # undetermined event, or a parent peers' diffs will reference —
        # silently corrupts the DAG store and livelocks the node (its
        # known-events high-water still claims the body, so peers never
        # resend it). A store that would have to drop pinned state grows
        # past `size` instead: memory degradation over corruption.
        self.pin = pin
        self._items: OrderedDict = OrderedDict()

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key) -> bool:
        return key in self._items

    def get(self, key):
        """Returns (value, True) and refreshes recency, or (None, False)."""
        try:
            self._items.move_to_end(key)
        except KeyError:
            return None, False
        return self._items[key], True

    def peek(self, key):
        """Returns (value, True) without refreshing recency."""
        if key in self._items:
            return self._items[key], True
        return None, False

    def add(self, key, value) -> bool:
        """Adds a value; returns True if an eviction occurred."""
        if key in self._items:
            self._items.move_to_end(key)
            self._items[key] = value
            return False
        self._items[key] = value
        if len(self._items) > self.size:
            if self.pin is None:
                old_key, old_val = self._items.popitem(last=False)
                if self.on_evict is not None:
                    self.on_evict(old_key, old_val)
                return True
            # bounded victim scan from the oldest end: evict unpinned
            # entries until back under the bound; pinned entries
            # encountered are recycled to the back (they ARE hot —
            # amortizes the scan and keeps the pinned prefix from being
            # rescanned every add). The budget bounds per-add cost; any
            # overage it leaves (all probes pinned) is reclaimed by later
            # adds, whose loop keeps draining while len > size.
            evicted = False
            for _ in range(8):
                if len(self._items) <= self.size:
                    break
                old_key = next(iter(self._items))
                old_val = self._items[old_key]
                if self.pin(old_key, old_val):
                    self._items.move_to_end(old_key)
                    continue
                del self._items[old_key]
                if self.on_evict is not None:
                    self.on_evict(old_key, old_val)
                evicted = True
            return evicted
        return False

    def remove(self, key) -> bool:
        if key in self._items:
            del self._items[key]
            return True
        return False

    def keys(self):
        """Keys oldest-to-newest."""
        return list(self._items.keys())

    def purge(self) -> None:
        if self.on_evict is not None:
            for k, v in self._items.items():
                self.on_evict(k, v)
        self._items.clear()
