from .codec import canonical_dumps, canonical_loads, b64e, b64d

__all__ = ["canonical_dumps", "canonical_loads", "b64e", "b64d"]
