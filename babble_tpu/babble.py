"""Composition root: wires peers, store, transport, key, node and service
into one runnable engine (reference: src/babble/babble.go:16-231).

Also the mobile-style embedding surface (reference: src/mobile/node.go:22-96):
`Babble` exposes run/submit_tx/shutdown plus an optional commit handler
callback, so an application can embed a node without touching the lower
layers.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from .crypto import PemKey, generate_key, pub_key_bytes
from .hashgraph import Block, InmemStore, SQLiteStore
from .net import TCPTransport
from .node import Config as NodeConfig
from .node import Node
from .peers import JSONPeers
from .proxy import AppProxy
from .service import Service


def default_data_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".babble")


@dataclass
class BabbleConfig:
    """Engine-level configuration (reference: src/babble/babble_config.go:15-51).

    `node` nests the runtime knobs (heartbeat, timeouts, cache, sync limit,
    consensus backend); the fields here cover composition: where the data
    lives, what to bind, which store, which proxy."""

    data_dir: str = field(default_factory=default_data_dir)
    bind_addr: str = ":1337"
    service_addr: str = ""  # "" = no HTTP service
    # allow /debug/* (stack dumps, sampling profiler) from non-loopback
    # clients; off by default — the profiler can hold a GIL-contending
    # sampling loop for up to 60s per request
    service_remote_debug: bool = False
    max_pool: int = 2
    store: bool = False  # False = in-memory, True = sqlite under data_dir
    log_level: str = "info"
    load_peers: bool = True
    proxy: Optional[AppProxy] = None
    # ec.EllipticCurvePrivateKey; loaded from <data_dir>/priv_key.pem if None
    key: Optional[object] = None
    node: NodeConfig = field(default_factory=NodeConfig)

    def db_path(self) -> str:
        """reference: BabbleConfig.BadgerDir (babble_config.go:49-51)."""
        return os.path.join(self.data_dir, "babble.db")


class Babble:
    """One consensus node, fully wired (reference: src/babble/babble.go)."""

    def __init__(self, config: BabbleConfig):
        self.config = config
        self.peers = None
        self.store = None
        self.trans = None
        self.node: Optional[Node] = None
        self.service: Optional[Service] = None
        self.logger = config.node.logger or logging.getLogger("babble")
        self._commit_handler: Optional[Callable[[Block], bytes]] = None

    # -- init sequence (reference: babble.go:171-201) -------------------

    def init(self) -> None:
        self._init_peers()
        self._init_store()
        self._init_transport()
        self._init_key()
        self._init_node()
        self._init_service()

    def _init_peers(self) -> None:
        if not self.config.load_peers:
            if self.peers is None:
                raise ValueError("did not load peers but none defined")
            return
        store = JSONPeers(self.config.data_dir)
        try:
            peers = store.peers()
        except FileNotFoundError:
            peers = None
        if peers is None or len(peers.to_peer_slice()) == 0:
            raise ValueError(f"peers.json not found in {self.config.data_dir}")
        self.peers = peers

    def _init_store(self) -> None:
        if self.config.store:
            self.store = SQLiteStore.load_or_create(
                self.peers, self.config.node.cache_size, self.config.db_path()
            )
        else:
            self.store = InmemStore(self.peers, self.config.node.cache_size)

    def _init_transport(self) -> None:
        self.trans = TCPTransport(
            self.config.bind_addr,
            max_pool=self.config.max_pool,
            timeout=self.config.node.tcp_timeout,
        )

    def _init_key(self) -> None:
        if self.config.key is not None:
            return
        self.config.key = PemKey(self.config.data_dir).read_key()

    def _init_node(self) -> None:
        if self.config.proxy is None:
            raise ValueError("no proxy configured")
        pub_hex = "0x" + pub_key_bytes(self.config.key).hex().upper()
        peer = self.peers.by_pub_key.get(pub_hex)
        if peer is None:
            raise ValueError(f"node key {pub_hex[:14]}… is not in the peer set")
        self.node = Node(
            self.config.node,
            peer.id,
            self.config.key,
            self.peers,
            self.store,
            self.trans,
            self.config.proxy,
        )
        self.node.init()

    def _init_service(self) -> None:
        if self.config.service_addr:
            self.service = Service(
                self.config.service_addr, self.node, self.logger,
                remote_debug=self.config.service_remote_debug,
            )

    # -- run (reference: babble.go:203-209) ------------------------------

    def run(self) -> None:
        """Blocking: serve HTTP (if configured) and run the node loop."""
        if self.service is not None:
            self.service.serve()
        self.node.run(True)

    def run_async(self) -> None:
        if self.service is not None:
            self.service.serve()
        self.node.run_async(True)

    # -- embedding surface (reference: src/mobile/node.go:22-96) ---------

    def submit_tx(self, tx: bytes) -> None:
        """Submit a raw transaction into consensus (mobile contract)."""
        # the proxy owns the submit channel; push through it so ordering
        # matches app-submitted transactions
        self.config.proxy.submit_ch().put(bytes(tx))

    def on_commit(self, handler: Callable[[Block], bytes]) -> None:
        """Register a commit callback (mobile CommitHandler contract). Only
        valid for proxies exposing set_commit_handler (InmemAppProxy)."""
        set_handler = getattr(self.config.proxy, "set_commit_handler", None)
        if set_handler is None:
            raise ValueError("configured proxy does not support commit handlers")
        set_handler(handler)

    def shutdown(self) -> None:
        if self.node is not None:
            self.node.shutdown()
        if self.service is not None:
            self.service.shutdown()


def keygen(data_dir: str):
    """Generate and persist a new node key; refuses to overwrite
    (reference: babble.go:211-231)."""
    pem = PemKey(data_dir)
    try:
        pem.read_key()
    except (FileNotFoundError, ValueError):
        pass
    else:
        raise ValueError(f"another key already lives under {data_dir}")
    key = generate_key()
    os.makedirs(data_dir, exist_ok=True)
    pem.write_key(key)
    return key
