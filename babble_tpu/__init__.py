"""babble-tpu: a TPU-native BFT consensus framework.

A from-scratch rebuild of the capabilities of Babble (hashgraph consensus
middleware, reference: /root/reference) designed TPU-first: the host runtime
(gossip, DAG storage, blockchain projection, app proxy) is asyncio Python,
and the virtual-voting consensus core is expressed as dense batched array
kernels executed via JAX/XLA, swappable with a scalar CPU engine behind the
same `Hashgraph` API (reference: src/hashgraph/hashgraph.go).
"""

__version__ = "0.1.0"
