"""Open-loop load generator (ISSUE 16): Poisson arrivals at a fixed
offered rate against the ingress pipeline.

Open-loop is the point: a closed-loop generator (submit, wait, submit)
slows down exactly when the system queues, so its latency numbers hide
the queueing it caused — the coordinated-omission trap. Here arrival
times come from a seeded exponential inter-arrival stream fixed up
front; the system's backpressure answers (queued/shed verdicts) are
RECORDED, never allowed to pace the offered load.

Client population: each arrival is a burst of `burst` transactions from
`burst` DISTINCT clients sampled from a `clients`-sized id space — how
10^5..10^6 simulated clients are driven without 10^5 sockets. Distinct
clients per burst also keeps release order equal to submission order
under the pipeline's DRR scheduler (per-client FIFO is guaranteed;
global FIFO only holds when no client appears twice in one burst), which
the batched-vs-single-tx digest-equality gate in bench_ingest.py relies
on.

Two drivers off one deterministic schedule:

- `drive_sim(cluster, ...)` — schedules arrivals on the SimScheduler
  (virtual time). `via="ingress"` submits through the proxy's batch
  entry (the pipeline path); `via="direct"` bypasses the pipeline and
  feeds the raw submit queue — the single-tx control for digest
  equality. Injected retries exercise the dedup window on the ingress
  path and are skipped on the direct path (the pipeline filters them,
  so the unique workload is identical either way).
- `drive_tcp(proxy, ...)` — same arrival law over a real
  SocketBabbleProxy: paced on the system Clock, batches shipped with
  `Babble.SubmitTxBatch`.

All randomness comes from `random.Random(f"{seed}|loadgen")`: the same
seed offers the same transactions at the same times to the same clients.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ..common import Clock, SYSTEM_CLOCK


class OpenLoopLoadGen:
    """Deterministic Poisson arrival schedule + verdict bookkeeping."""

    def __init__(
        self,
        rate: float,
        clients: int = 100_000,
        burst: int = 8,
        tx_bytes: int = 32,
        retry_every: int = 0,
        seed: int = 0,
    ):
        if rate <= 0:
            raise ValueError("rate must be > 0 (offered tx/s)")
        if clients < 1:
            raise ValueError("clients must be >= 1")
        if burst < 1:
            raise ValueError("burst must be >= 1")
        self.rate = rate
        self.clients = clients
        self.burst = min(burst, clients)  # distinct clients per burst
        self.tx_bytes = max(16, tx_bytes)
        # every Nth burst re-offers its first tx (a client retry) to
        # exercise the dedup window; 0 disables
        self.retry_every = retry_every
        self.rng = random.Random(f"{seed}|loadgen")
        self.seq = 0
        self.bursts = 0
        self.offered = 0
        self.retries = 0
        self._last_tx: Optional[bytes] = None
        self.verdicts: Dict[str, int] = {
            "accepted": 0, "queued": 0, "shed": 0, "deduped": 0,
        }

    # -- schedule ------------------------------------------------------

    def next_gap(self) -> float:
        """Exponential inter-arrival gap between BURSTS, sized so the
        offered tx rate (bursts * burst size) matches `rate`."""
        return self.rng.expovariate(self.rate / self.burst)

    def next_burst(self) -> List[Dict[str, Any]]:
        """[{tx, client_id}] for one arrival: `burst` fresh txs from
        distinct clients."""
        ids = self.rng.sample(range(self.clients), self.burst)
        out = []
        for cid in ids:
            body = b"lg|%d|c%d" % (self.seq, cid)
            tx = body + b"." * max(0, self.tx_bytes - len(body))
            self.seq += 1
            out.append({"tx": tx, "client_id": f"c{cid}"})
        self.bursts += 1
        self.offered += len(out)
        return out

    def want_retry(self) -> bool:
        """Whether this arrival should also re-offer a previously
        submitted tx (drawn every `retry_every` bursts). The DRIVER owns
        which tx and where: a retry must go to the node that saw the
        original, because dedup windows are per-node — re-offering to a
        different node is a fresh submission, not a retry."""
        return bool(self.retry_every) and self.bursts % self.retry_every == 0

    def note(self, verdict) -> None:
        if getattr(verdict, "deduped", False):
            self.verdicts["deduped"] += 1
        else:
            self.verdicts[verdict.verdict] = (
                self.verdicts.get(verdict.verdict, 0) + 1
            )

    def stats(self) -> Dict[str, Any]:
        return {
            "offered": self.offered,
            "bursts": self.bursts,
            "retries": self.retries,
            "clients": self.clients,
            "rate": self.rate,
            "verdicts": dict(self.verdicts),
        }

    # -- sim driver (virtual time) -------------------------------------

    def drive_sim(
        self, cluster, until: float, via: str = "ingress",
    ) -> "OpenLoopLoadGen":
        """Schedule the arrival stream on the cluster's SimScheduler.
        Returns self (stats accumulate as the cluster runs). Target node
        per burst is drawn from the same seeded stream, so the ingress
        and direct runs offer identical (tx, node, time) triples."""
        if via not in ("ingress", "direct"):
            raise ValueError("via must be 'ingress' or 'direct'")
        # (tx, client_id, node_index) of the last DELIVERED burst's first
        # tx — the retry source. Per-node dedup means a retry only counts
        # as a retry when it lands on the node that saw the original.
        last: List[Any] = [None]

        def arrival() -> None:
            if cluster.clock.now >= until:
                return
            burst = self.next_burst()
            want_retry = self.want_retry()
            i = self.rng.randrange(cluster.n)
            sn = cluster.sns[i]
            if not sn.crashed:
                if via == "ingress":
                    # one wire batch per burst: per-tx verdicts, one
                    # pump, one (or few) released downstream batches
                    for v in sn.proxy.submit_tx_batch(
                        [e["tx"] for e in burst],
                        client_id=burst[0]["client_id"],
                    ):
                        self.note(v)
                else:
                    # single-tx control: the raw pre-pipeline path, one
                    # queue put per tx
                    for entry in burst:
                        tx = bytes(entry["tx"])
                        sn.proxy._trace_submit(tx)
                        sn.proxy.submit_ch().put(tx)
                last[0] = (burst[0]["tx"], burst[0]["client_id"], i)
            # client retry: re-offer an already-delivered tx TO THE NODE
            # THAT SAW IT. On the ingress path its dedup window absorbs
            # it (verdict accepted/deduped, nothing re-enters the pool);
            # the direct path skips it — so the unique workload, and the
            # commit digests, match between the two modes.
            if want_retry and last[0] is not None and via == "ingress":
                rtx, rcid, rnode = last[0]
                rsn = cluster.sns[rnode]
                if not rsn.crashed:
                    self.retries += 1
                    for v in rsn.proxy.submit_tx_batch(
                        [rtx], client_id=rcid
                    ):
                        self.note(v)
            # open loop: the next arrival is scheduled regardless of
            # what the verdicts said
            cluster.sched.after(self.next_gap(), arrival, label="loadgen")

        cluster.sched.after(self.next_gap(), arrival, label="loadgen")
        return self

    # -- TCP driver (wall clock through the Clock seam) ----------------

    def drive_tcp(
        self, proxy, duration: float, clock: Clock = SYSTEM_CLOCK,
    ) -> Dict[str, Any]:
        """Offer the arrival stream to a live node through an app-side
        SocketBabbleProxy (`Babble.SubmitTxBatch`). Arrival times are
        fixed up front from the schedule; when the generator falls
        behind wall clock (slow RPCs), pending arrivals are sent
        back-to-back rather than skipped — offered load is preserved,
        not thinned (that would be coordinated omission again)."""
        from .pipeline import SubmitRejected

        start = clock.monotonic()
        next_at = start + self.next_gap()
        errors = 0
        while True:
            now = clock.monotonic()
            if now >= start + duration:
                break
            if next_at > now:
                clock.sleep(min(next_at - now, start + duration - now))
                continue
            burst = self.next_burst()
            txs = [e["tx"] for e in burst]
            # single target node over TCP: a retry of the last delivered
            # tx rides along and is absorbed by that node's dedup window
            if self.want_retry() and self._last_tx is not None:
                self.retries += 1
                txs.append(self._last_tx)
            try:
                for v in proxy.submit_tx_batch(
                    txs, client_id=burst[0]["client_id"],
                ):
                    self.note(v)
                self._last_tx = burst[0]["tx"]
            except SubmitRejected as e:
                if e.verdict == "shed":
                    self.verdicts["shed"] += len(txs)
                else:
                    errors += 1
            next_at += self.next_gap()
        out = self.stats()
        out["errors"] = errors
        out["duration"] = clock.monotonic() - start
        return out
