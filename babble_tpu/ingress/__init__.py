"""Production ingress (ISSUE 16): the batched, back-pressured submit
pipeline between every AppProxy submit entry point and the node's
transaction worker, plus the open-loop load generator that drives it.

- `pipeline.py` — IngressPipeline: size/deadline-bounded batching on the
  injected Clock, bounded admission queue with explicit
  accepted/queued/shed verdicts, per-client token buckets with
  deficit-round-robin fairness, and trace_id dedup over an LRU window.
- `loadgen.py` — OpenLoopLoadGen: Poisson arrivals at a fixed offered
  rate (open-loop, so coordinated omission cannot hide queueing) over
  the deterministic sim fabric or real TCP.
"""

from .pipeline import (
    IngressPipeline,
    IngressVerdict,
    SubmitRejected,
    VERDICT_ACCEPTED,
    VERDICT_QUEUED,
    VERDICT_SHED,
    verdict_from_wire,
)
from .loadgen import OpenLoopLoadGen

__all__ = [
    "IngressPipeline",
    "IngressVerdict",
    "SubmitRejected",
    "VERDICT_ACCEPTED",
    "VERDICT_QUEUED",
    "VERDICT_SHED",
    "verdict_from_wire",
    "OpenLoopLoadGen",
]
