"""IngressPipeline: the batched, back-pressured front door (ISSUE 16).

Transactions used to enter one at a time through framed JSON-RPC
(`proxy/socket_app.py` -> `submit_ch`), unbounded and unfair — the
cheapest flooding attack on a leaderless mesh. The pipeline sits between
every proxy submit entry point and the node's transaction worker and
applies, in order:

1. **dedup** — the sha256 trace_id (obs/tracectx.py) over an LRU window
   (common/lru.py), so client retries are idempotent: a duplicate gets
   the `accepted` verdict back (its first submission stands) and never
   re-enters the pool.
2. **admission control** — a bounded queue with EXPLICIT verdicts: every
   submission is answered `accepted` (released with the current batch),
   `queued` (admitted, held until the client's token bucket refills) or
   `shed` (queue full / sustained overrate). Never a silent drop.
3. **fairness** — per-client token buckets (client = peer addr or the
   app-supplied client_id) drained by a deficit-round-robin scheduler,
   so one flooder cannot starve the mesh: a meek client's transactions
   release ahead of a flooder's backlog.
4. **batching** — released transactions coalesce into size/deadline-
   bounded batches on the injected Clock (the dispatch-batching
   discipline of PR 9, applied at ingress: amortize many small submits
   into one `core.add_transactions` per batch). An oversize transaction
   bypasses coalescing and ships alone.

Every time read goes through the injected Clock — never wallclock — so
the deterministic simulator replays identical verdicts, batch shapes and
shed decisions for a given seed (the `ingress` entry in SimCluster's
result is part of the determinism fingerprint).

Thread model: RPC handler threads, the node's tx worker and the
heartbeat tick all call in; one pipeline lock serializes admission and
release. Released batches are handed downstream OUTSIDE the lock so the
pipeline never holds its lock across node-side queues.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..common import LRU, Clock, SYSTEM_CLOCK
from ..obs.metrics import DEFAULT_COUNT_BUCKETS, log_buckets
from ..obs.tracectx import trace_id_for

VERDICT_ACCEPTED = "accepted"
VERDICT_QUEUED = "queued"
VERDICT_SHED = "shed"

# bound on distinct live token buckets / client queues: admission state,
# not consensus state, so an LRU bound (evicted flooders simply start a
# fresh bucket) beats unbounded growth under a client-id churn attack
DEFAULT_CLIENT_CAP = 8192

# sheds inside one rolling window that flag a shed storm (flight record
# + dump): distinguishes sustained overload from an isolated rejection
SHED_STORM_WINDOW = 1.0
SHED_STORM_THRESHOLD = 64


@dataclass
class IngressVerdict:
    """The pipeline's answer to one submission — returned to the client
    (in-mem: as this object; JSON-RPC: as `to_wire()`), never implied."""

    verdict: str  # accepted | queued | shed
    reason: str = ""
    deduped: bool = False
    trace_id: str = ""

    def to_wire(self) -> Dict[str, Any]:
        return {
            "verdict": self.verdict,
            "reason": self.reason,
            "deduped": self.deduped,
            "trace_id": self.trace_id,
        }


def verdict_from_wire(res: Any) -> IngressVerdict:
    """Decode a SubmitTx/SubmitTxBatch JSON-RPC result. A pre-pipeline
    server answers plain `True` — mapped to a bare `accepted`."""
    if isinstance(res, dict):
        return IngressVerdict(
            verdict=str(res.get("verdict", "")),
            reason=str(res.get("reason", "")),
            deduped=bool(res.get("deduped", False)),
            trace_id=str(res.get("trace_id", "")),
        )
    if res:
        return IngressVerdict(verdict=VERDICT_ACCEPTED, reason="legacy")
    return IngressVerdict(verdict=VERDICT_SHED, reason="rejected")


class SubmitRejected(RuntimeError):
    """A submission did not land: `verdict` distinguishes server-side
    backpressure (``shed`` — retry later, the node is protecting itself)
    from transport/server failure (``error`` — the submission may never
    have been seen). Raised by the app-side socket proxy so callers can
    branch on backpressure instead of parsing a bare RuntimeError."""

    def __init__(self, verdict: str, reason: str = "",
                 server_verdict: Optional[IngressVerdict] = None):
        self.verdict = verdict
        self.reason = reason
        self.server_verdict = server_verdict
        super().__init__(f"SubmitTx rejected ({verdict}): {reason}")


class TokenBucket:
    """Per-client rate limiter. Pure state — refills are computed from
    the caller-provided Clock reading, and all access happens under the
    pipeline lock, so the bucket itself needs none."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now

    def take(self, now: float) -> bool:  # requires-lock: IngressPipeline._lock
        if now > self.stamp:
            self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
            self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class _ClientQueue:
    """Pending (tx, paid) entries for one client plus its DRR deficit.
    All access under the pipeline lock."""

    __slots__ = ("entries", "deficit")

    def __init__(self) -> None:
        self.entries: Deque[Tuple[bytes, bool]] = deque()
        self.deficit = 0.0


class IngressPipeline:
    def __init__(
        self,
        downstream: Callable[[List[bytes]], None],
        clock: Clock = SYSTEM_CLOCK,
        obs=None,
        batch_bytes: int = 65536,
        batch_deadline: float = 0.0,
        queue_cap: int = 8192,
        client_rate: float = 0.0,
        client_burst: Optional[float] = None,
        dedup_window: int = 65536,
        client_cap: int = DEFAULT_CLIENT_CAP,
        logger: Optional[logging.Logger] = None,
    ):
        if batch_bytes < 1:
            raise ValueError("batch_bytes must be >= 1")
        if batch_deadline < 0:
            raise ValueError("batch_deadline must be >= 0")
        if queue_cap < 0:
            raise ValueError("queue_cap must be >= 0 (0 = unbounded)")
        if client_rate < 0:
            raise ValueError("client_rate must be >= 0 (0 = unlimited)")
        self.downstream = downstream
        self.clock = clock
        self.logger = logger or logging.getLogger("babble.ingress")
        if obs is None:
            from ..obs import Observability

            obs = Observability(clock=clock)
        self.obs = obs
        self.batch_bytes = batch_bytes
        self.batch_deadline = batch_deadline
        self.queue_cap = queue_cap
        self.client_rate = client_rate
        # default burst: one second's worth of tokens (>= 1 so a single
        # submit from a fresh client always has a token to take)
        self.client_burst = (
            client_burst if client_burst is not None else max(1.0, client_rate)
        )
        # DRR quantum: bytes a client may release per scheduler round —
        # a quarter-batch keeps several clients' traffic in every batch
        self.drr_quantum = max(1.0, batch_bytes / 4.0)

        self._lock = threading.Lock()
        # dedup window over trace_ids (retry idempotency horizon)
        self._dedup = LRU(max(1, dedup_window))  # guarded-by: _lock
        # token bucket per live client, LRU-bounded (see DEFAULT_CLIENT_CAP)
        self._buckets = LRU(max(1, client_cap))  # guarded-by: _lock
        # per-client pending queues, insertion-ordered (the DRR rotation
        # order); a queue is dropped the moment it drains
        self._queues: Dict[str, _ClientQueue] = {}  # guarded-by: _lock
        self._pending = 0  # guarded-by: _lock
        # the open batch: released txs waiting for size/deadline flush
        self._batch: List[bytes] = []  # guarded-by: _lock
        self._batch_size = 0  # guarded-by: _lock
        self._batch_open_t = 0.0  # guarded-by: _lock
        # shed-storm detection window state
        self._shed_window_start = 0.0  # guarded-by: _lock
        self._shed_window_count = 0  # guarded-by: _lock
        self._storm_flagged = False  # guarded-by: _lock

        # -- metric declarations (static names; obs-* lint) -------------
        self._m_verdicts = self.obs.counter(
            "babble_ingress_verdicts_total",
            "Ingress admission verdicts returned to clients",
            labels=("verdict",),
        )
        self._m_shed = self.obs.counter(
            "babble_ingress_shed_total",
            "Submissions shed by the ingress pipeline, by reason",
            labels=("reason",),
        )
        self._m_dedup = self.obs.counter(
            "babble_ingress_dedup_hits_total",
            "Retries absorbed by the trace_id dedup window",
        )
        self._m_batch_txs = self.obs.histogram(
            "babble_ingress_batch_txs",
            "Transactions per released ingress batch",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        self._m_batch_bytes = self.obs.histogram(
            "babble_ingress_batch_bytes",
            "Bytes per released ingress batch",
            buckets=log_buckets(64, 4.0, 10),
        )
        self.obs.gauge(
            "babble_ingress_queue_depth",
            "Transactions held in the ingress pipeline (queued + batching)",
        ).set_function(self.pending)

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------

    def pending(self) -> int:
        """Held transactions: rate-deferred queues plus the open batch.
        Feeds the queue-depth gauge and the watchdog's pending_fn (a
        stall with ingress work held must not read as an idle node)."""
        with self._lock:
            return self._pending + len(self._batch)

    def submit(self, tx: bytes, client_id: str = "local") -> IngressVerdict:
        """Admit one transaction; returns its verdict immediately."""
        return self.submit_batch([tx], client_id=client_id)[0]

    def submit_batch(
        self, txs: List[bytes], client_id: str = "local"
    ) -> List[IngressVerdict]:
        """Admit a client batch: per-tx verdicts, one release pump at the
        end — so a wire batch coalesces into (at least) one downstream
        batch instead of one per transaction."""
        out: List[IngressVerdict] = []
        with self._lock:
            now = self.clock.monotonic()
            for tx in txs:
                out.append(self._admit_locked(bytes(tx), client_id, now))
            released = self._pump_locked(now)
        self._emit(released)
        return out

    def tick(self) -> None:
        """Deadline pump: called from the heartbeat tick (threaded node)
        or SimCluster._tick (virtual time) so a partial batch's deadline
        fires even when no new submission arrives."""
        with self._lock:
            released = self._pump_locked(self.clock.monotonic())
        self._emit(released)

    def flush(self) -> None:
        """Release everything releasable and ship the open batch even if
        under both thresholds (shutdown/test seam)."""
        with self._lock:
            released = self._pump_locked(self.clock.monotonic())
            if self._batch:
                released.append(self._close_batch_locked())
        self._emit(released)

    # ------------------------------------------------------------------
    # admission (lock held)
    # ------------------------------------------------------------------

    # requires-lock: _lock
    def _admit_locked(
        self, tx: bytes, client_id: str, now: float
    ) -> IngressVerdict:
        tid = trace_id_for(tx)
        _, seen = self._dedup.get(tid)
        if seen:
            # idempotent retry: the first submission stands, the client
            # gets a success verdict (not an error) and nothing re-enters
            self._m_dedup.inc()
            self._m_verdicts.labels(verdict="accepted").inc()
            return IngressVerdict(
                VERDICT_ACCEPTED, reason="duplicate", deduped=True,
                trace_id=tid,
            )
        if self.queue_cap and self._pending + len(self._batch) >= self.queue_cap:
            return self._shed_locked(tid, "queue_full", now)
        paid = True
        if self.client_rate > 0:
            bucket, ok = self._buckets.get(client_id)
            if not ok:
                bucket = TokenBucket(self.client_rate, self.client_burst, now)
                self._buckets.add(client_id, bucket)
            paid = bucket.take(now)
            if not paid:
                # overrate: the tx may wait for a refill, but only a
                # bounded backlog per client — past it, shed (a flooder
                # must not park the whole admission queue behind itself)
                q = self._queues.get(client_id)
                backlog = len(q.entries) if q is not None else 0
                if self.queue_cap and backlog >= max(1, self.queue_cap // 4):
                    return self._shed_locked(tid, "rate_limited", now)
        q = self._queues.get(client_id)
        if q is None:
            q = self._queues[client_id] = _ClientQueue()
        q.entries.append((tx, paid))
        self._pending += 1
        self._dedup.add(tid, True)
        verdict = VERDICT_ACCEPTED if paid else VERDICT_QUEUED
        self._m_verdicts.labels(verdict=verdict).inc()
        return IngressVerdict(
            verdict,
            reason="" if paid else "rate_limited",
            trace_id=tid,
        )

    # requires-lock: _lock
    def _shed_locked(
        self, tid: str, reason: str, now: float
    ) -> IngressVerdict:
        self._m_verdicts.labels(verdict="shed").inc()
        self._m_shed.labels(reason=reason).inc()
        # storm detection: sheds are expected in isolation (that is the
        # backpressure contract working); a burst of them inside one
        # window is an overload event worth a flight record + dump
        if now - self._shed_window_start >= SHED_STORM_WINDOW:
            self._shed_window_start = now
            self._shed_window_count = 0
            self._storm_flagged = False
        self._shed_window_count += 1
        if (
            self._shed_window_count >= SHED_STORM_THRESHOLD
            and not self._storm_flagged
        ):
            self._storm_flagged = True
            self.obs.flightrec.record(
                "ingress.shed_storm",
                sheds=self._shed_window_count,
                window_s=SHED_STORM_WINDOW,
                reason=reason,
                queue_depth=self._pending + len(self._batch),
            )
            self.obs.flightrec.dump("ingress-shed-storm")
        return IngressVerdict(VERDICT_SHED, reason=reason, trace_id=tid)

    # ------------------------------------------------------------------
    # release: DRR scheduler + batch former (lock held)
    # ------------------------------------------------------------------

    def _pump_locked(self, now: float) -> List[List[bytes]]:  # requires-lock: _lock
        """Move releasable txs from the client queues into the open
        batch (deficit round robin), flushing on the size threshold;
        then apply the deadline rule. Returns closed batches for the
        caller to emit outside the lock."""
        out: List[List[bytes]] = []
        # DRR: every full round grants each waiting client one quantum
        # of bytes; rounds repeat while at least one tx released OR a
        # head is blocked only on deficit (a few more grants always free
        # it — deficits grow a quantum per round, so that loop is
        # bounded by max_tx_len/quantum; rate-starved heads do NOT
        # extend rounds or a drained bucket would spin this forever).
        # A burst thus drains in one pump, interleaved fairly — a
        # quantum per client at a time, not flooder-first.
        progressed = True
        deficit_starved = False
        while (progressed or deficit_starved) and self._queues:
            progressed = False
            deficit_starved = False
            for cid in list(self._queues.keys()):
                q = self._queues.get(cid)
                if q is None or not q.entries:
                    self._queues.pop(cid, None)
                    continue
                q.deficit += self.drr_quantum
                while q.entries:
                    tx, paid = q.entries[0]
                    oversize = len(tx) >= self.batch_bytes
                    if not oversize and q.deficit < len(tx):
                        deficit_starved = True
                        break  # quantum spent — next client's turn
                    if not paid:
                        bucket, ok = self._buckets.get(cid)
                        if not ok or not bucket.take(now):
                            # still overrate — wait for a refill. The
                            # deficit is forfeited: an ineligible queue
                            # is idle in DRR terms, and banking credit
                            # across the wait would let it burst past
                            # its quantum share once tokens return
                            q.deficit = 0.0
                            break
                    q.entries.popleft()
                    self._pending -= 1
                    progressed = True
                    if oversize:
                        # oversize bypasses coalescing: ship the open
                        # batch as-is, then the big tx alone (deficit is
                        # zeroed — it consumed far more than a quantum)
                        q.deficit = 0.0
                        if self._batch:
                            out.append(self._close_batch_locked())
                        self._observe_batch([tx])
                        out.append([tx])
                        continue
                    q.deficit -= len(tx)
                    if not self._batch:
                        self._batch_open_t = now
                    self._batch.append(tx)
                    self._batch_size += len(tx)
                    if self._batch_size >= self.batch_bytes:
                        out.append(self._close_batch_locked())
                if not q.entries:
                    self._queues.pop(cid, None)
        # deadline rule: 0 => release every pump (no hold); > 0 => hold
        # the partial batch until the deadline elapses on the Clock
        if self._batch and (
            self.batch_deadline <= 0.0
            or now - self._batch_open_t >= self.batch_deadline
        ):
            out.append(self._close_batch_locked())
        return out

    def _close_batch_locked(self) -> List[bytes]:  # requires-lock: _lock
        batch = self._batch
        self._batch = []
        self._batch_size = 0
        self._observe_batch(batch)
        return batch

    def _observe_batch(self, batch: List[bytes]) -> None:
        self._m_batch_txs.observe(len(batch))
        self._m_batch_bytes.observe(sum(len(t) for t in batch))

    def _emit(self, batches: List[List[bytes]]) -> None:
        """Hand released batches downstream, outside the pipeline lock
        (the downstream is the node's submit queue; never hold our lock
        across someone else's)."""
        for batch in batches:
            if batch:
                self.downstream(batch)
