"""Simulated network: fault-injected message passing on virtual time.

`SimNetwork` owns every inter-node interaction. Faults (drops, latency,
duplication, partitions, dead peers) are sampled from one dedicated RNG
stream *in scheduling order*, so a given seed produces the same fault
sequence on every run. Two delivery styles are offered:

- `call`: synchronous request/response with zero virtual duration, used
  where production code blocks inline on a transport verb (the node's
  `fast_forward()` path). Faults surface as `TransportError`, exactly
  what the threaded code expects from a real socket.
- `send`: the event-driven round trip used by the cluster's split-step
  gossip choreography — request leg latency, handler execution at the
  destination, response leg latency, then `on_ok`/`on_fail` fire as
  scheduled events. Failures are detected after `tcp_timeout`, matching
  how a real dialer learns about a dead or partitioned peer.

`SimTransport` adapts the synchronous path onto the `Transport` ABC so an
unmodified `Node` can be constructed against the simulated network.
"""

from __future__ import annotations

import queue
import random
from typing import Any, Callable, Dict, Optional, Tuple

from ..net.commands import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    SyncRequest,
    SyncResponse,
)
from ..net.transport import RPC, Transport, TransportError
from .faults import FaultPlan
from .scheduler import SimScheduler

# handler takes an inbound RPC and must respond synchronously (the
# cluster wires this to Node._process_rpc, which always responds)
Handler = Callable[[RPC], None]


class SimNetwork:
    def __init__(
        self,
        sched: SimScheduler,
        plan: FaultPlan,
        rng: random.Random,
        tcp_timeout: float = 1.0,
    ):
        self.sched = sched
        self.plan = plan
        self.rng = rng
        self.tcp_timeout = tcp_timeout
        self._handlers: Dict[str, Tuple[int, Handler]] = {}
        self._alive: Dict[str, bool] = {}
        self.stats = {
            "delivered": 0,
            "dropped": 0,
            "severed": 0,
            "duplicated": 0,
            "failed_calls": 0,
        }

    # -- registry -------------------------------------------------------

    def register(self, idx: int, addr: str, handler: Handler) -> None:
        self._handlers[addr] = (idx, handler)
        self._alive[addr] = True

    def set_handler(self, addr: str, handler: Handler) -> None:
        """Re-point an address at a fresh node instance (crash-restart)."""
        idx, _ = self._handlers[addr]
        self._handlers[addr] = (idx, handler)

    def set_alive(self, addr: str, alive: bool) -> None:
        self._alive[addr] = alive

    def node_index(self, addr: str) -> int:
        return self._handlers[addr][0]

    # -- fault sampling (one RNG stream, sampled in scheduling order) ---

    def unreachable(self, src: str, dst: str) -> Optional[str]:
        """Returns a failure reason, or None when the link is up."""
        if dst not in self._handlers:
            return f"failed to connect to peer: {dst}"
        if not self._alive.get(dst, False):
            return f"peer down: {dst}"
        if not self._alive.get(src, False):
            return f"sender down: {src}"
        t = self.sched.clock.now
        if self.plan.partitioned(self.node_index(src), self.node_index(dst), t):
            return f"partitioned: {src} -/- {dst}"
        return None

    def sample_latency(self) -> float:
        lat = self.plan.latency
        return lat.base + (self.rng.uniform(0.0, lat.jitter) if lat.jitter else 0.0)

    def should_drop(self) -> bool:
        return self.plan.drop_rate > 0 and self.rng.random() < self.plan.drop_rate

    def should_dup(self) -> bool:
        return self.plan.dup_rate > 0 and self.rng.random() < self.plan.dup_rate

    # -- synchronous path (inline fast-forward) -------------------------

    def call(self, src: str, dst: str, command: Any) -> Any:
        reason = self.unreachable(src, dst)
        if reason is None and self.should_drop():
            reason = f"dropped: {src} -> {dst}"
            self.stats["dropped"] += 1
        if reason is not None:
            self.stats["failed_calls"] += 1
            raise TransportError(reason)
        resp = self._dispatch(dst, command)
        self.stats["delivered"] += 1
        if resp.error:
            raise TransportError(resp.error)
        return resp.response

    def _dispatch(self, dst: str, command: Any):
        rpc = RPC(command=command)
        _, handler = self._handlers[dst]
        handler(rpc)
        try:
            return rpc.resp_queue.get_nowait()
        except queue.Empty:
            raise TransportError(
                f"handler for {dst} did not respond synchronously"
            ) from None

    # -- event-driven path (split-step gossip) --------------------------

    def send(
        self,
        src: str,
        dst: str,
        command: Any,
        on_ok: Callable[[Any], None],
        on_fail: Callable[[TransportError], None],
        label: str = "rpc",
    ) -> None:
        """Full round trip on virtual time. Fault decisions for this
        message are sampled NOW (scheduling order == sampling order, the
        determinism invariant); delivery and callbacks fire later as
        scheduled events."""
        reason = self.unreachable(src, dst)
        if reason is None and self.should_drop():
            reason = f"dropped: {src} -> {dst}"
            self.stats["dropped"] += 1
        elif reason is not None and "partitioned" in reason:
            self.stats["severed"] += 1
        if reason is not None:
            # a dead/partitioned/dropped request surfaces at the caller
            # only after the dial timeout, like a real socket
            self.sched.after(
                self.tcp_timeout,
                lambda: on_fail(TransportError(reason)),
                label=f"{label}:fail",
            )
            return

        req_lat = self.sample_latency()
        resp_lat = self.sample_latency()
        duplicate = self.should_dup()

        def deliver() -> None:
            # destination may have crashed (or partitioned) in flight
            late_reason = self.unreachable(src, dst)
            if late_reason is not None:
                self.stats["severed"] += 1
                self.sched.after(
                    max(0.0, self.tcp_timeout - req_lat),
                    lambda: on_fail(TransportError(late_reason)),
                    label=f"{label}:fail-late",
                )
                return
            resp = self._dispatch(dst, command)
            self.stats["delivered"] += 1
            if resp.error:
                self.sched.after(
                    resp_lat,
                    lambda: on_fail(TransportError(resp.error)),
                    label=f"{label}:err",
                )
            else:
                self.sched.after(
                    resp_lat, lambda: on_ok(resp.response), label=f"{label}:ok"
                )

        self.sched.after(req_lat, deliver, label=f"{label}:deliver")

        if duplicate:
            # the destination handles the request a second time; the
            # stray response is discarded (caller already got one)
            self.stats["duplicated"] += 1
            dup_lat = self.sample_latency()

            def deliver_dup() -> None:
                if self.unreachable(src, dst) is None:
                    self._dispatch(dst, command)

            self.sched.after(req_lat + dup_lat, deliver_dup, label=f"{label}:dup")


class SimTransport(Transport):
    """Transport ABC adapter over SimNetwork's synchronous path.

    The consumer queue exists to satisfy the interface; in simulation the
    node's RPC-dispatch thread never runs — inbound RPCs are handed to
    `Node._process_rpc` directly by the cluster."""

    def __init__(self, net: SimNetwork, addr: str):
        self.net = net
        self._addr = addr
        self._consumer: "queue.Queue[RPC]" = queue.Queue()

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self._addr

    def sync(self, target: str, req: SyncRequest) -> SyncResponse:
        return self.net.call(self._addr, target, req)

    def eager_sync(self, target: str, req: EagerSyncRequest) -> EagerSyncResponse:
        return self.net.call(self._addr, target, req)

    def fast_forward(self, target: str, req: FastForwardRequest) -> FastForwardResponse:
        return self.net.call(self._addr, target, req)

    def close(self) -> None:
        pass
