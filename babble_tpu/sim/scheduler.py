"""Discrete-event scheduler driving the simulation's virtual time.

A single min-heap of (time, seq, label, callback). Ties in time are
broken by insertion sequence, so two runs that schedule the same work in
the same order execute it in the same order — the determinism backbone
everything else (transport, ticks, fault injection) builds on.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, List, Optional, Tuple

from .clock import SimClock


class SimScheduler:
    def __init__(self, clock: Optional[SimClock] = None):
        self.clock = clock or SimClock()
        self._heap: List[Tuple[float, int, str, Callable[[], None]]] = []
        self._seq = itertools.count()
        self.events_run = 0

    def at(self, t: float, fn: Callable[[], None], label: str = "") -> None:
        """Schedule fn at absolute virtual time t (clamped to now: the
        past is immutable)."""
        heapq.heappush(
            self._heap, (max(t, self.clock.now), next(self._seq), label, fn)
        )

    def after(self, delay: float, fn: Callable[[], None], label: str = "") -> None:
        self.at(self.clock.now + max(0.0, delay), fn, label)

    def pending(self) -> int:
        return len(self._heap)

    def peek_time(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def step(self) -> bool:
        """Run the earliest event; returns False when the heap is empty.
        The clock advances to the event's time BEFORE its callback runs,
        so everything the callback reads or schedules sees a consistent
        'now'."""
        if not self._heap:
            return False
        t, _, _, fn = heapq.heappop(self._heap)
        self.clock.advance_to(t)
        self.events_run += 1
        fn()
        return True

    def run_until(self, t: float, max_events: int = 1_000_000) -> int:
        """Run every event due at or before virtual time t (bounded by
        max_events as a runaway backstop). Returns events executed."""
        ran = 0
        while (
            ran < max_events
            and self._heap
            and self._heap[0][0] <= t
        ):
            self.step()
            ran += 1
        self.clock.advance_to(t)
        return ran
