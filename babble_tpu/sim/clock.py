"""Virtual time for the deterministic simulator.

`SimClock` satisfies the node layer's `Clock` seam
(babble_tpu/common/clock.py) with scheduler-advanced time: `monotonic()`
reads the event loop's current instant, and `sleep()` — which a real
thread would block on — records the requested duration instead. The
simulation is single-threaded, so a blocking sleep would freeze the
whole world; the driver (SimCluster) collects the pending amount and
charges it to the caller's next scheduled step, preserving the timing
semantics the code asked for without stopping anyone else.
"""

from __future__ import annotations

from ..common import Clock


class SimClock(Clock):
    def __init__(self, start: float = 0.0):
        self.now = float(start)
        self._pending_sleep = 0.0

    def monotonic(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self._pending_sleep += max(0.0, float(seconds))

    def take_pending_sleep(self) -> float:
        """Drain sleep requested since the last take — the driver adds it
        to the requester's next wakeup delay."""
        pending, self._pending_sleep = self._pending_sleep, 0.0
        return pending

    def advance_to(self, t: float) -> None:
        """Monotonic advance only: the scheduler owns time's arrow."""
        if t > self.now:
            self.now = t
