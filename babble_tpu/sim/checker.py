"""Cross-node divergence detection with replay-artifact capture.

Consensus safety in one sentence: every node that commits block *i* must
commit byte-identical contents for it. The checker enforces that
continuously during a simulation — after every burst of virtual-time
activity the cluster hands it the live nodes' stores, and each newly
*settled* block index (reached by every live node) is byte-compared via
`BlockBody.marshal()`. Signatures are excluded on purpose: signature
sets legitimately differ across nodes (each hears a different subset of
the sig gossip); the body is the consensus payload.

On mismatch a replay artifact is dumped to `docs/artifacts/` carrying
everything needed to reproduce the run from scratch: the master seed,
the fault plan (JSON round-trippable), cluster shape, the per-node block
dumps at the divergent index, and the tail of the event trace. The
artifact is the bug report — `python -m babble_tpu sim --seed S --plan P`
replays it deterministically.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..obs import DivergenceBisector
from ..utils.codec import b64e


class DivergenceError(Exception):
    def __init__(self, message: str, artifact_path: Optional[str] = None,
                 localized: Optional[Dict[str, Any]] = None,
                 bisect_path: Optional[str] = None):
        super().__init__(message)
        self.artifact_path = artifact_path
        # first-divergence bisection (obs/provenance.py): the earliest
        # divergent (pass, table, round, witness) cell, when the cluster
        # supplied provenance streams to bisect
        self.localized = localized
        self.bisect_path = bisect_path


class DivergenceChecker:
    def __init__(self, artifact_dir: str = "docs/artifacts"):
        self.artifact_dir = artifact_dir
        # highest block index already verified identical on all nodes;
        # the watermark only moves forward, so each settled block is
        # compared exactly once per run
        self.checked_upto = -1
        self.blocks_checked = 0

    def check(
        self,
        views: List[Tuple[str, Any]],
        context: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Compare every newly settled block across `views` (name, store)
        pairs for the currently-live nodes. A store whose replayed
        history starts above an index — an inmem node that rejoined via
        fast-forward and never held the early blocks — is skipped for
        that index rather than treated as divergent. Returns the new
        watermark; raises DivergenceError on the first mismatch."""
        if not views:
            return self.checked_upto

        frontier = min(self._last_index(store) for _, store in views)
        for i in range(self.checked_upto + 1, frontier + 1):
            ref_bytes: Optional[bytes] = None
            ref_name = ""
            holders: List[Tuple[str, Any]] = []
            settled = True
            for name, store in views:
                blk = self._get_block(store, i)
                if blk is None:
                    continue
                if not blk.state_hash():
                    # commit channel is asynchronous: a block without its
                    # state hash is still mid-commit on that node, so this
                    # index (and everything above it) is not comparable yet
                    settled = False
                    break
                holders.append((name, blk))
                body = blk.body.marshal()
                if ref_bytes is None:
                    ref_bytes, ref_name = body, name
                elif body != ref_bytes:
                    loc, bisect_path = self._bisect(
                        i, ref_name, name, context
                    )
                    path = self._dump_artifact(
                        i, holders, views, context, localized=loc
                    )
                    msg = "block %d diverges: %s != %s (artifact: %s)" % (
                        i, name, ref_name, path,
                    )
                    if loc is not None:
                        msg += (
                            "; localized to round %s %s/%s cell %s" % (
                                loc["round"], loc["pass"], loc["table"],
                                (loc.get("cell") or "")[:18],
                            )
                        )
                    raise DivergenceError(
                        msg, artifact_path=path, localized=loc,
                        bisect_path=bisect_path,
                    )
            if not settled:
                break
            self.checked_upto = i
            self.blocks_checked += 1
        return self.checked_upto

    @staticmethod
    def _last_index(store: Any) -> int:
        try:
            return store.last_block_index()
        except Exception:
            return -1

    @staticmethod
    def _get_block(store: Any, index: int):
        try:
            return store.get_block(index)
        except Exception:
            return None

    # -- bisection ------------------------------------------------------

    def _bisect(
        self,
        index: int,
        a_name: str,
        b_name: str,
        context: Optional[Dict[str, Any]],
    ) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
        """Diff the two divergent holders' decision-provenance streams
        (supplied lazily by the cluster as context['provenance_fn']) and
        export the triage artifact naming the earliest divergent cell.
        Deterministic filename: seed + block index, like the replay
        artifact it sits beside."""
        fn = (context or {}).get("provenance_fn")
        if fn is None:
            return None, None
        try:
            streams = fn()
        except Exception:  # noqa: BLE001 — triage must not mask the trip
            return None, None
        a_doc, b_doc = streams.get(a_name), streams.get(b_name)
        if a_doc is None or b_doc is None:
            return None, None
        bis = DivergenceBisector(self.artifact_dir)
        loc = bis.bisect(a_name, a_doc, b_name, b_doc)
        if loc is None:
            return None, None
        seed = (context or {}).get("seed", "unseeded")
        path = bis.export(
            loc, f"bisect-seed{seed}-block{index}.json",
            context={"seed": seed, "block_index": index},
        )
        return loc, path

    # -- artifact -------------------------------------------------------

    def _dump_artifact(
        self,
        index: int,
        holders: List[Tuple[str, Any]],
        views: List[Tuple[str, Any]],
        context: Optional[Dict[str, Any]],
        localized: Optional[Dict[str, Any]] = None,
    ) -> str:
        context = dict(context or {})
        context.pop("provenance_fn", None)
        trace = context.pop("trace", [])
        artifact = {
            "kind": "babble-tpu-sim-divergence",
            "block_index": index,
            "localized": localized,
            **context,
            "blocks": {
                name: {
                    "body": blk.body.to_canonical(),
                    "body_hash": b64e(blk.body.hash()),
                    "n_signatures": len(blk.signatures),
                }
                for name, blk in holders
            },
            "frontiers": {
                name: self._last_index(store) for name, store in views
            },
            # the last stretch of the event trace shows what the cluster
            # was doing when consensus split; the seed+plan above replay
            # the whole run if more is needed
            "trace_tail": list(trace)[-400:],
        }
        os.makedirs(self.artifact_dir, exist_ok=True)
        seed = context.get("seed", "unseeded")
        path = os.path.join(
            self.artifact_dir, f"divergence-seed{seed}-block{index}.json"
        )
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
        return path
